"""A custom experiment showing the framework's general-purpose surface:
any measured activity, any factors, any profilers — not just LLM energy.

Measures matrix-multiply throughput across sizes and dtypes:
    python -m cain_2025_device_remote_llm_energy_rep_pkg_tpu examples/custom_experiment.py
"""

import time
from pathlib import Path

from cain_2025_device_remote_llm_energy_rep_pkg_tpu import (
    ExperimentConfig,
    Factor,
    RunTableModel,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import (
    HostResourceProfiler,
)


class RunnerConfig(ExperimentConfig):
    name = "matmul_throughput"
    results_output_path = Path("experiments_output")
    time_between_runs_in_ms = 1000
    isolate_runs = False  # keep the jit cache warm across runs
    profilers = [HostResourceProfiler(period_s=0.2)]

    def create_run_table_model(self) -> RunTableModel:
        return RunTableModel(
            factors=[
                Factor("size", [512, 1024, 2048]),
                Factor("dtype", ["float32", "bfloat16"]),
            ],
            repetitions=3,
            data_columns=["tflops", "wall_s"],
            shuffle=True,
        )

    def interact(self, context):
        import jax
        import jax.numpy as jnp

        n = context.factor("size")
        dtype = jnp.dtype(context.factor("dtype"))
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n)).astype(dtype)
        f = jax.jit(lambda a: a @ a)
        f(x).block_until_ready()  # compile outside the timed region
        t0 = time.monotonic()
        iters = 10
        for _ in range(iters):
            y = f(x)
        y.block_until_ready()
        wall = time.monotonic() - t0
        context.scratch["wall_s"] = wall
        context.scratch["tflops"] = 2 * n**3 * iters / wall / 1e12

    def populate_run_data(self, context):
        return {
            "tflops": round(context.scratch["tflops"], 3),
            "wall_s": round(context.scratch["wall_s"], 4),
        }
