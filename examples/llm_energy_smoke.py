"""Smoke-test variant of the energy study: 2 tiny models, 1 length, 2 reps.

Runs in a couple of minutes on CPU or a single chip:
    python -m cain_2025_device_remote_llm_energy_rep_pkg_tpu examples/llm_energy_smoke.py
"""

from pathlib import Path

import jax.numpy as jnp

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import JaxEngine
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
    LlmEnergyConfig,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)

_MODELS = ["qwen2:1.5b", "gemma:2b"]
_REGISTRY = {name: get_model_config(name).tiny(max_seq_len=1024) for name in _MODELS}
_ENGINE = JaxEngine(registry=_REGISTRY, dtype=jnp.float32)


class RunnerConfig(LlmEnergyConfig):
    def __init__(self):
        super().__init__(
            models=_MODELS,
            lengths=[100],
            repetitions=2,
            cooldown_ms=500,
            results_output_path=Path("experiments_output"),
            backends={"on_device": _ENGINE, "remote": _ENGINE},
            # Tiny models make the 8-chip mesh model meaningless — the
            # TP roofline (correctly) says a toy model's decode step sits
            # on the ICI latency floor and the mesh would be ~70× SLOWER,
            # so aliased remote rows would be billed absurd mesh windows.
            # The smoke serves both treatments from one chip; the real
            # topology belongs to the full study (llm_energy_study.py).
            n_chips_by_location={"on_device": 1, "remote": 1},
        )
