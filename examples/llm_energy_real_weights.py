"""Real-weights study cell: learned model, EOS-driven generation lengths.

Run with:
    python -m cain_2025_device_remote_llm_energy_rep_pkg_tpu examples/llm_energy_real_weights.py

The sweep's 7 reference families run from random-init weights (no egress,
no checkpoints in this environment), which means generation always runs to
its token budget. This cell closes that gap (VERDICT.md round-1 item 6)
with the framework's own *trained* tiny LM (models/tiny_lm.py): the model
learned an in-repo corpus and emits EOS on its own, so ``generated_tokens``
varies per row and is below the budget, and the per-run artifacts contain
readable text. Weights are trained once and checkpointed under the
experiment output dir; re-runs restore them through Orbax.
"""

from pathlib import Path

import jax.numpy as jnp

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import JaxEngine
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
    LlmEnergyConfig,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tiny_lm import (
    TINY_LM_NAME,
    load_or_train_tiny_lm,
)

_CKPT_DIR = Path("experiments_output") / "tiny_lm_weights"

_cfg, _params = load_or_train_tiny_lm(_CKPT_DIR, log_every=100)
_ENGINE = JaxEngine(registry={}, dtype=jnp.float32)
_ENGINE.install_model(TINY_LM_NAME, _cfg, _params)


class RunnerConfig(LlmEnergyConfig):
    def __init__(self):
        super().__init__(
            models=[TINY_LM_NAME],
            lengths=[100],
            repetitions=3,
            cooldown_ms=500,
            results_output_path=Path("experiments_output"),
            backends={"on_device": _ENGINE, "remote": _ENGINE},
        )
