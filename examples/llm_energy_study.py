"""The flagship study: on-device vs remote LLM generation energy on TPU.

Run with:
    python -m cain_2025_device_remote_llm_energy_rep_pkg_tpu examples/llm_energy_study.py

This is the full 7-model × 2-location × 3-length × 30-repetition sweep of the
reference (experiment/RunnerConfig.py:77-88) on the JAX engine: "on_device"
serves from a single chip, "remote" from a tensor-parallel mesh over all
visible devices. Expect many hours on real hardware (90 s cooldown × 1260
runs, like the original study). For a quick smoke test see
``llm_energy_smoke.py``.
"""

from pathlib import Path

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
    LlmEnergyConfig,
)


class RunnerConfig(LlmEnergyConfig):
    def __init__(self):
        super().__init__(
            repetitions=30,
            results_output_path=Path("experiments_output"),
        )
