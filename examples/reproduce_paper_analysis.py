"""Reproduce the CAIN 2025 paper's statistical results from its shipped raw
data — through THIS framework's analysis pipeline.

The reference analyses its 1,260-run table with an R notebook
(data-analysis/analysis-visualization.ipynb: IQR outlier removal, Wilcoxon
two-sided, Cliff's delta with the .147/.33/.474 labels, Spearman). This
script feeds the same CSV (treated purely as input data) to the Python
pipeline in ``analysis/`` and prints the paper's headline numbers: energy
per treatment × length, the H1 hypothesis tests, and the H2 correlates.

Usage::

    python examples/reproduce_paper_analysis.py [path/to/run_table.csv]

Default path is the read-only reference checkout used during development.
"""

from __future__ import annotations

import csv
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.stats import (
    cliffs_delta,
    descriptives,
    significance_stars,
    spearman,
    wilcoxon_rank_sum,
)

DEFAULT_CSV = Path("/root/reference/data-analysis/run_table.csv")
LENGTH_NAMES = {100: "short", 500: "medium", 1000: "long"}


def load(csv_path: Path):
    with csv_path.open() as fh:
        rows = list(csv.DictReader(fh))
    for row in rows:
        for key in (
            "execution_time",
            "cpu_usage",
            "gpu_usage",
            "memory_usage",
            "energy_usage_J",
        ):
            row[key] = float(row[key])
        row["length"] = int(row["length"])
    return rows


def iqr_filter_per_group(rows):
    """The notebook filters outliers per (method × length) subset over every
    metric (cells 11+13): the framework's own ``apply_iqr_filter`` (ANY
    outlying metric drops the row) applied per subset."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.pipeline import (
        apply_iqr_filter,
    )

    metrics = (
        "energy_usage_J",
        "execution_time",
        "cpu_usage",
        "gpu_usage",
        "memory_usage",
    )
    kept = []
    for method in ("on_device", "remote"):
        for length in (100, 500, 1000):
            subset = [
                r
                for r in rows
                if r["method"] == method and r["length"] == length
            ]
            kept.extend(apply_iqr_filter(subset, metrics))
    return kept


def main(csv_path: Path) -> int:
    rows = load(csv_path)
    clean = iqr_filter_per_group(rows)
    print(f"rows: {len(rows)} raw, {len(clean)} after per-subset IQR filter\n")

    print("Energy (J) by treatment × length  [mean / median / sd / n]")
    ratios = {}
    for length in (100, 500, 1000):
        line = f"  {LENGTH_NAMES[length]:>6}:"
        means = {}
        for method in ("on_device", "remote"):
            vals = [
                r["energy_usage_J"]
                for r in clean
                if r["method"] == method and r["length"] == length
            ]
            d = descriptives(vals)
            means[method] = d.mean
            line += (
                f"  {method} {d.mean:7.1f} / {d.median:7.1f} / "
                f"{d.sd:6.1f} (n={d.n})"
            )
        ratios[length] = means["on_device"] / means["remote"]
        line += f"  → on-device/remote = {ratios[length]:.1f}×"
        print(line)

    print("\nH1: energy(on-device) vs energy(remote), per length")
    for length in (100, 500, 1000):
        a = [
            r["energy_usage_J"]
            for r in clean
            if r["method"] == "on_device" and r["length"] == length
        ]
        b = [
            r["energy_usage_J"]
            for r in clean
            if r["method"] == "remote" and r["length"] == length
        ]
        stat, p = wilcoxon_rank_sum(a, b)
        delta, label = cliffs_delta(a, b)
        print(
            f"  {LENGTH_NAMES[length]:>6}: Wilcoxon p={p:.3g} "
            f"{significance_stars(p)}  Cliff's δ={delta:+.3f} ({label})"
        )

    print("\nH2: Spearman ρ of on-device energy vs correlates")
    on_device = [r for r in clean if r["method"] == "on_device"]
    energy = [r["energy_usage_J"] for r in on_device]
    for metric in ("execution_time", "cpu_usage", "gpu_usage", "memory_usage"):
        rho, p = spearman(energy, [r[metric] for r in on_device])
        print(
            f"  {metric:>16}: ρ={rho:+.3f} p={p:.3g} {significance_stars(p)}"
        )

    headline = (
        f"\nHeadline: on-device costs {ratios[100]:.1f}× (short) to "
        f"{max(ratios[500], ratios[1000]):.1f}× (medium/long) more "
        "client-side energy than fetching remotely."
    )
    print(headline)
    return 0


if __name__ == "__main__":
    path = Path(sys.argv[1]) if len(sys.argv) > 1 else DEFAULT_CSV
    if not path.exists():
        print(f"run table not found: {path}", file=sys.stderr)
        sys.exit(2)
    sys.exit(main(path))
