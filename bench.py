"""Benchmark: decode throughput of the JAX engine on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (BASELINE.md): the reference's on-device treatment
generates 1000 words in 43.35 s mean wall-time (IQR-filtered, all models) —
1000 · 4/3 ≈ 1333 tokens → **30.8 tokens/s** on the M2 via Ollama. This bench
greedy-decodes the same flagship-class model (qwen2:1.5b, full architecture)
on one TPU chip and reports steady-state decode tokens/s; ``vs_baseline``
> 1 means faster than the reference's on-device rate.

Weights are int8 weight-only quantized on the accelerator (activations and
KV stay bf16): decode is HBM-bandwidth-bound, and the reference's own
baseline models are Ollama defaults — 4-bit GGUF quants — so quantized
serving is the matching configuration, not an extra trick. The "quantize"
field in the JSON records it.

Falls back to a depth-reduced model on CPU (clearly marked in the JSON extras)
so the bench always emits a line even where no TPU is reachable.
"""

import dataclasses
import json
import sys
import time

BASELINE_TOKENS_PER_S = 1000.0 * (4.0 / 3.0) / 43.35  # ≈ 30.75 (BASELINE.md)
# Batch timing discipline — used by BOTH the measurement loop and the
# emitted JSON so the self-describing metadata cannot drift from what ran.
BATCH_TIMED_RUNS = 2
BATCH_STAT = "best"  # max over the timed windows (relay sessions land low)


def _attach_obs(line: dict) -> None:
    """Attach the obs registry snapshot (`obs_metrics`), the flight-
    recorder summary (`obs_flight`: event counts by type + drop count)
    and — when any SLO engine is live — the per-objective attainment/
    burn state (`obs_slo`) to a bench JSON line, so a BENCH_*.json row
    records not just the figures but the scheduler/engine decisions
    (slices, joins, retirements, fallbacks) and contract state behind
    them. Guarded: the perf line must never die on telemetry."""
    try:
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
            FLIGHT,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
            REGISTRY,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.slo import (
            active_snapshot,
        )

        snap = REGISTRY.snapshot()
        if snap:
            line["obs_metrics"] = snap
        flight = FLIGHT.summary()
        if flight.get("events_total"):
            line["obs_flight"] = flight
        slo = active_snapshot()
        if slo:
            line["obs_slo"] = slo
    except Exception:
        pass


def continuous_batching_bench() -> int:
    """A/B of the two request schedulers under STAGGERED (Poisson)
    arrivals: window dispatch (batches run to completion) vs the
    iteration-level continuous scheduler (admit/step/retire at decode-
    step granularity — serve/scheduler.py, engine/stepped.py).

    CPU-functional and fake-clock-free: a depth-reduced real JaxEngine
    decodes real tokens on whatever backend JAX has, and the arrival
    process sleeps real wall-clock (seeded exponential inter-arrival via
    scripts/poisson_load.py). The figures that matter are the RELATIVE
    ones — p50/p95 TTFT, completion latency, aggregate tokens/s at the
    same arrival trace — recorded in docs/PERF.md "Continuous vs window
    batching". Prints ONE JSON line.
    """
    import dataclasses as _dc
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        # CPU-functional: the tiny architecture decodes real tokens in
        # ~ms steps, so the latency SHAPES under staggered load are
        # real while the full-width model's per-shape XLA compiles
        # (minutes each on CPU) stay out of the bench
        cfg = cfg.tiny()
    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.bfloat16 if on_accelerator else jnp.float32,
        decode_attention="auto" if on_accelerator else None,
    )

    n = int(_os.environ.get("BENCH_CB_REQUESTS", "18"))
    mean_ms = float(_os.environ.get("BENCH_CB_INTERARRIVAL_MS", "60"))
    budgets = (8, 16, 96)  # mixed targets: arrivals straddle the long rows
    # one prompt bucket (all < 32 tokens): the A/B measures scheduling,
    # not prefill-shape compile churn
    prompts = ("alpha beta", "gamma delta epsilon", "zeta eta")
    workload = build_workload(
        n, mean_ms / 1e3, seed=7, model=cfg.name, budgets=budgets,
        prompts=prompts,
        stop_at_eos=False,  # fixed lengths: both schedulers do equal work
    )

    # Warm every compiled shape OUTSIDE the measured traces (both
    # schedulers replay the same arrival trace; neither may pay XLA).
    warm = [req for _, req in workload[:6]]
    engine.generate_batch(warm)
    for req in {r.max_new_tokens: r for r in warm}.values():
        engine.generate(req)
    sess = engine.decode_open(warm, reserve_rows=2 * len(warm))
    while sess.active:
        sess.step()
    sess.close()

    results = {}
    for mode, make in (
        ("window", lambda: BatchScheduler(engine, window_s=0.05)),
        ("continuous", lambda: ContinuousScheduler(engine)),
    ):
        sched = make()
        sched.start()
        try:
            records = run_load(sched.submit, workload)
        finally:
            sched.stop()
        results[mode] = summarize(records)

    line = {
        "metric": "continuous_batching",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "n_layers": cfg.n_layers,
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "budgets": list(budgets),
        "window": results["window"],
        "continuous": results["continuous"],
        "ttft_p50_speedup": (
            round(
                results["window"]["ttft_p50_s"]
                / results["continuous"]["ttft_p50_s"],
                2,
            )
            if results["continuous"].get("ttft_p50_s")
            else None
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def chunked_join_bench() -> int:
    """A/B of the continuous scheduler's JOIN policy under a
    heavy-tailed (lognormal) prompt-length Poisson trace: synchronous
    one-shot joins (PR 3 — the whole prompt prefills between two decode
    slices) vs chunked joins (PR 4 — token-budgeted prefill chunks
    interleaved with slices, `--prefill-chunk-tokens`).

    Headline figures: the IN-FLIGHT inter-token gap p99 (the wall
    between two consecutive decode-slice completions that live rows sat
    through — what a caller mid-decode experiences when a long-prompt
    joiner streams in; with sync joins one gap swallows the joiner's
    whole prefill, with chunked joins every gap is bounded by one slice
    + one chunk) and joiner TTFT p95, at the same seeded arrival trace,
    plus aggregate tok/s (chunking must not cost throughput) and
    bit-parity of every stream vs solo generate(). CPU-functional like
    the continuous_batching bench: tiny real architecture, real tokens,
    real wall-clock; RELATIVE positions are the result (docs/PERF.md
    "Chunked join-prefill"). Prints ONE JSON line.
    """
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, percentile, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        # room for the heavy tail: prompts to ~352 tokens + budgets
        cfg = cfg.tiny(max_seq_len=1024)
    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.bfloat16 if on_accelerator else jnp.float32,
        decode_attention="auto" if on_accelerator else None,
    )

    n = int(_os.environ.get("BENCH_CJ_REQUESTS", "14"))
    mean_ms = float(_os.environ.get("BENCH_CJ_INTERARRIVAL_MS", "50"))
    chunk_tokens = int(_os.environ.get("BENCH_CJ_CHUNK_TOKENS", "64"))
    slice_steps = int(_os.environ.get("BENCH_CJ_SLICE_STEPS", "8"))
    # request 0 (the session anchor) rotates onto the LONG budget so the
    # session outlives the arrivals (160 steps of slices spans the whole
    # trace — heavy-tailed joiners must land MID-FLIGHT, the case under
    # test); anchor_longest gives it the longest prompt so the session
    # cache fits every later joiner — the A/B then varies ONLY the join
    # policy, not capacity feasibility
    budgets = (160, 12, 24)
    workload = build_workload(
        n,
        mean_ms / 1e3,
        seed=11,
        model=cfg.name,
        budgets=budgets,
        stop_at_eos=False,  # fixed lengths: both arms do equal work
        prompt_len_dist="lognormal",
        prompt_len_median=40.0,
        prompt_len_sigma=1.1,
        prompt_len_max=352,
        anchor_longest=True,
    )
    prompt_tokens = [len(req.prompt) + 1 for _, req in workload]

    # solo references: parity oracle AND warm-up of the solo shapes
    solo = {id(req): engine.generate(req).tokens for _, req in workload}

    def run_mode(chunked: bool):
        sched = ContinuousScheduler(
            engine,
            slice_steps=slice_steps,
            prefill_chunk_tokens=chunk_tokens,
            chunked_joins=chunked,
        )
        gaps = []
        sched.slice_gap_sink = lambda gap_s, rows: gaps.append(gap_s)
        tokens_by_req = {}

        def submit(req):
            res = sched.submit(req)
            tokens_by_req[id(req)] = res.tokens
            return res

        sched.start()
        try:
            records = run_load(submit, workload)
        finally:
            sched.stop()
        joiners = [r for r in records if r.get("joined")]
        joiner_ttfts = [
            r["ttft_s"] for r in joiners if r.get("ttft_s") is not None
        ]
        return {
            **summarize(records),
            "inflight_gap_p99_s": (
                round(percentile(gaps, 99), 4) if gaps else None
            ),
            "inflight_gap_max_s": round(max(gaps), 4) if gaps else None,
            "slice_gaps_observed": len(gaps),
            "joined": len(joiners),
            "join_chunks_total": sum(
                r.get("join_chunks") or 0 for r in records
            ),
            "joiner_ttft_p95_s": (
                round(percentile(joiner_ttfts, 95), 4)
                if joiner_ttfts
                else None
            ),
            "parity_vs_solo": all(
                tokens_by_req.get(i) == toks for i, toks in solo.items()
            ),
        }

    # warm BOTH arms outside the measured traces (session shapes, chunk
    # prefill buckets, stepped decode fns — neither arm may pay XLA)
    run_mode(False)
    run_mode(True)
    results = {"sync": run_mode(False), "chunked": run_mode(True)}

    line = {
        "metric": "chunked_join",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "n_layers": cfg.n_layers,
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "budgets": list(budgets),
        "prompt_len": {
            "dist": "lognormal", "median": 40.0, "sigma": 1.1,
            "max": 352, "anchor_longest": True,
            "drawn_min": min(prompt_tokens),
            "drawn_max": max(prompt_tokens),
        },
        "prefill_chunk_tokens": chunk_tokens,
        "decode_slice_steps": slice_steps,
        **results,
        "inflight_gap_p99_ratio": (
            round(
                results["sync"]["inflight_gap_p99_s"]
                / results["chunked"]["inflight_gap_p99_s"],
                2,
            )
            if results["sync"]["inflight_gap_p99_s"]
            and results["chunked"]["inflight_gap_p99_s"]
            else None
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def streaming_cancellation_bench() -> int:
    """A/B of streaming delivery + mid-stream cancellation (ISSUE 6)
    under the same seeded Poisson trace, three arms on one tiny PAGED
    JaxEngine through the continuous scheduler:

    - **buffered**: blocking submits — the pre-streaming baseline; a
      25%-cancellation INTENT is recorded but cannot take effect, so
      every abandoned row decodes to its full budget;
    - **streaming**: every request consumes its per-slice egress
      channel, nobody cancels — the tok/s-regression guard (streamed
      delivery must not cost aggregate throughput on the uncancelled
      subset);
    - **streaming_cancel**: the same trace with the 25% of clients
      actually hanging up after their drawn token count — rows retire
      mid-flight (reason="cancelled") and their pages recycle.

    Headline figures: TTFT-at-first-chunk percentiles, the paged pool's
    HIGH-WATER page occupancy (cancellation keeps it lower), and the
    GOODPUT RATIO — tokens a client actually wanted, over row-steps the
    device executed (llm_engine_stepped_tokens_total deltas). Cancelled
    rows stop consuming steps, so the ratio must improve vs the
    buffered arm, which keeps decoding for nobody. CPU-functional,
    seeded, relative positions are the result (docs/PERF.md "Streaming
    delivery + cancellation"). Prints ONE JSON line.
    """
    import os as _os
    import sys as _sys
    import threading as _threading
    import time as _time

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import (
        build_cancellations,
        build_workload,
        channel_chunks,
        percentile,
        run_load,
        summarize,
    )

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
        STEPPED_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        cfg = cfg.tiny()
    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.bfloat16 if on_accelerator else jnp.float32,
        decode_attention="auto" if on_accelerator else None,
        paged_kv=True,  # the pool high-water figure is a paged-pool story
    )

    n = int(_os.environ.get("BENCH_SC_REQUESTS", "16"))
    mean_ms = float(_os.environ.get("BENCH_SC_INTERARRIVAL_MS", "50"))
    slice_steps = int(_os.environ.get("BENCH_SC_SLICE_STEPS", "8"))
    # every 4th request draws the LONG budget — and that quarter is the
    # cancellation target: the realistic abandonment case (a client who
    # has read enough of a long generation hangs up) and the only one
    # where reclaiming matters — a cancelled short row's session is
    # still bounded by its longest companion, so cancelling short rows
    # saves no bucket-steps by construction
    budgets = (128, 12, 24, 48)
    prompts = ("alpha beta", "gamma delta epsilon", "zeta eta")
    workload = build_workload(
        n, mean_ms / 1e3, seed=13, model=cfg.name, budgets=budgets,
        prompts=prompts, stop_at_eos=False,
    )
    # seeded per-request hang-up points, applied to the long-budget
    # quarter: entry i = tokens delivered before client i disconnects
    # (None = runs to completion). Same plan in every arm.
    draws = build_cancellations(n, 1.0, after_tokens=(4, 24), seed=13)
    cancellations = [
        d if req.max_new_tokens == max(budgets) else None
        for d, (_, req) in zip(draws, workload)
    ]
    cancel_frac = sum(1 for c in cancellations if c is not None) / n
    # tokens each client actually WANTS under the cancellation intent —
    # the goodput numerator for every arm (a buffered arm still decodes
    # the full budget; the excess is the waste streaming reclaims)
    useful = [
        min(c, req.max_new_tokens) if c is not None else req.max_new_tokens
        for c, (_, req) in zip(cancellations, workload)
    ]

    # solo warm-up: every compiled shape + the parity oracle
    solo = {id(req): engine.generate(req).tokens for _, req in workload}
    warm_sess = engine.decode_open(
        [req for _, req in workload[:4]], reserve_rows=8
    )
    while warm_sess.active:
        warm_sess.step(slice_steps)
    warm_sess.close()

    # run_load streams exactly the requests with a cancel-after plan, so
    # the all-streaming arms give no-cancel requests an unreachable
    # cancel point (every token streams, the stream runs to completion)
    NEVER = 1 << 30
    stream_all_plan = [c if c is not None else NEVER for c in cancellations]

    def run_arm(cancel_plan):
        sched = ContinuousScheduler(engine, slice_steps=slice_steps)
        # paged-pool high-water sampler: peak pages in use across the
        # arm (the scheduler's live debug handle; /debug/state's twin)
        high_water = [0]
        stop_probe = _threading.Event()

        def probe():
            while not stop_probe.is_set():
                dbg = sched._dbg
                if dbg is not None:
                    try:
                        pool = dbg[0].pool
                        in_use = pool.n_pages - pool.free_pages
                        high_water[0] = max(high_water[0], in_use)
                    except Exception:  # noqa: BLE001 — racing close()
                        pass
                _time.sleep(0.004)

        tokens_by_req = {}

        def submit(req):
            res = sched.submit(req)
            tokens_by_req[id(req)] = res.tokens
            return res

        def stream_submit(req):
            def recording():
                inner = channel_chunks(sched.submit_stream(req))
                try:
                    for chunk in inner:
                        if chunk.done and chunk.result is not None:
                            tokens_by_req[id(req)] = chunk.result.tokens
                        yield chunk
                finally:
                    inner.close()  # early close propagates the cancel

            return recording()

        stepped0 = STEPPED_C.labels().value
        sched.start()
        prober = _threading.Thread(target=probe, daemon=True)
        prober.start()
        try:
            records = run_load(
                submit,
                workload,
                stream_submit=(
                    stream_submit if cancel_plan is not None else None
                ),
                cancellations=cancel_plan,
            )
        finally:
            stop_probe.set()
            sched.stop()
            prober.join(timeout=2)
        stepped = STEPPED_C.labels().value - stepped0
        ttfts = [r["ttft_s"] for r in records if r.get("ttft_s") is not None]
        uncancelled = [
            r for r in records
            if "error" not in r and not r.get("cancelled")
        ]
        return {
            **summarize(records),
            "ttft_first_chunk_p50_s": (
                round(percentile(ttfts, 50), 4) if ttfts else None
            ),
            "ttft_first_chunk_p95_s": (
                round(percentile(ttfts, 95), 4) if ttfts else None
            ),
            "pool_high_water_pages": high_water[0],
            "stepped_row_steps": int(stepped),
            "goodput_ratio": (
                round(sum(useful) / stepped, 3) if stepped else None
            ),
            "uncancelled_tokens": sum(r["tokens"] for r in uncancelled),
            "parity_vs_solo": all(
                tokens_by_req.get(i) == toks
                for i, toks in solo.items()
                if i in tokens_by_req
            ),
        }

    # warm the arm machinery itself (join shapes, stream plumbing)
    run_arm([NEVER] * n)
    results = {
        "buffered": run_arm(None),
        "streaming": run_arm([NEVER] * n),
        "streaming_cancel": run_arm(stream_all_plan),
    }

    line = {
        "metric": "streaming_cancellation",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "n_layers": cfg.n_layers,
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "budgets": list(budgets),
        "cancel_frac": cancel_frac,
        "planned_cancellations": sum(
            1 for c in cancellations if c is not None
        ),
        "decode_slice_steps": slice_steps,
        **results,
        "streaming_vs_buffered_tok_s": (
            round(
                results["streaming"]["agg_tokens_per_s"]
                / results["buffered"]["agg_tokens_per_s"],
                3,
            )
            if results["buffered"]["agg_tokens_per_s"]
            else None
        ),
        "goodput_ratio_gain": (
            round(
                results["streaming_cancel"]["goodput_ratio"]
                / results["buffered"]["goodput_ratio"],
                2,
            )
            if results["buffered"]["goodput_ratio"]
            else None
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def tenant_attribution_bench() -> int:
    """Per-tenant slice-attribution accuracy (ISSUE 20): one seeded
    Poisson trace, two tenants at a 70/30 mix, driven through the
    continuous scheduler so rows JOIN a shared decode session
    mid-flight, with a seeded fraction of clients hanging up
    mid-stream. Two arms over the SAME requests:

    - **shared**: the full trace at speed — joiners, cancellations,
      token-share slice splits; each completed request's Joules come
      from its ``extras["energy_model"]`` close-out;
    - **solo** (ground truth): the shared arm's COMPLETED requests
      replayed one at a time through a fresh scheduler — every row
      alone in its session, so its attribution is trivially exact.

    The engine is the fake backend with a per-token synthetic energy
    price: its model charges decode tokens and nothing else, so the
    shared arm's per-tenant J/token must reproduce the solo figure
    EXACTLY — unlike a real batch (where amortizing the weight stream
    across rows is the point), any deviation here is tokens billed to
    the wrong row, not physics. The headline is the worst per-tenant
    attribution error (target <5%; the conservation tests pin the same
    split at 1e-6 granularity), cross-checked against the server-side
    tenant table the scheduler accounted into. Prints ONE JSON line.
    """
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    from poisson_load import (
        build_cancellations,
        build_workload,
        channel_chunks,
        run_load,
        summarize,
    )

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
        tenants as obs_tenants,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    JPT = 0.21  # synthetic Joules per decode token
    n = int(_os.environ.get("BENCH_TA_REQUESTS", "24"))
    mean_ms = float(_os.environ.get("BENCH_TA_INTERARRIVAL_MS", "15"))
    backend = FakeBackend(
        tokens_per_s=600.0, simulate_delay=True, joules_per_token=JPT
    )
    workload = build_workload(
        n, mean_ms / 1e3, seed=20, model="bench:1b",
        budgets=(64, 12, 24, 48), stop_at_eos=False,
        tenant_mix={"a": 0.7, "b": 0.3},
    )
    cancellations = build_cancellations(n, 0.25, after_tokens=(4, 16), seed=20)

    obs_tenants.reset_tenants()
    sched = ContinuousScheduler(backend)
    sched.start()
    try:
        shared_records = run_load(
            sched.submit,
            workload,
            stream_submit=lambda req: channel_chunks(
                sched.submit_stream(req)
            ),
            cancellations=cancellations,
        )
    finally:
        sched.stop()
    table = obs_tenants.snapshot()["tenants"]

    # ground truth: the completed requests, one at a time — nothing to
    # share a slice with, so per-request attribution is exact by
    # construction (and the fake is deterministic, so tokens replay)
    done = [
        (i, rec) for i, rec in enumerate(shared_records)
        if "error" not in rec and not rec.get("cancelled")
    ]
    solo_sched = ContinuousScheduler(backend)
    solo_sched.start()
    try:
        solo_J = {}
        for i, _rec in done:
            res = solo_sched.submit(workload[i][1])
            solo_J[i] = (res.extras or {})["energy_model"]["J"]
    finally:
        solo_sched.stop()

    def per_tenant(figures):
        out = {}
        for i, rec in done:
            t = rec["tenant"]
            acct = out.setdefault(t, {"joules": 0.0, "tokens": 0})
            acct["joules"] += figures(i, rec)
            acct["tokens"] += rec["tokens"]
        return {
            t: round(a["joules"] / a["tokens"], 6)
            for t, a in out.items() if a["tokens"]
        }

    shared_jpt = per_tenant(lambda i, rec: rec["joules"])
    solo_jpt = per_tenant(lambda i, rec: solo_J[i])
    errors = {
        t: round(abs(shared_jpt[t] - solo_jpt[t]) / solo_jpt[t], 6)
        for t in solo_jpt
    }
    max_error = max(errors.values()) if errors else None

    # cross-check: the scheduler accounted the SAME joules into the
    # tenant table the /debug/tenants surface serves. A client that
    # hangs up in the same instant its row finishes records "cancelled"
    # while the server legitimately closes the row out "ok" (with its
    # Joules) — so the table may exceed the client-side sum by at most
    # those rows' full budgets, and never fall below it.
    def _tenant_ok(check):
        for t in shared_jpt:
            client_J = sum(
                rec["joules"] for _i, rec in done if rec["tenant"] == t
            )
            slack = JPT * sum(
                workload[i][1].max_new_tokens
                for i, rec in enumerate(shared_records)
                if rec.get("tenant") == t and rec.get("cancelled")
            )
            if not check(table.get(t, {}).get("joules", 0.0), client_J, slack):
                return False
        return True

    table_agrees = _tenant_ok(
        lambda table_J, client_J, slack: -1e-6
        <= table_J - client_J
        <= slack + 1e-6
    )

    summary = summarize(shared_records)
    line = {
        "metric": "tenant_attribution",
        "unit": "relative_error",
        "value": max_error,
        "target": 0.05,
        "passed": max_error is not None and max_error < 0.05,
        "model": "bench:1b",
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "tenant_mix": {"a": 0.7, "b": 0.3},
        "joules_per_token_model": JPT,
        "completed": len(done),
        "cancelled": summary["cancelled"],
        "rows_joined": sum(
            1 for _i, r in done if r.get("joined")
        ),
        "shared_j_per_token": shared_jpt,
        "solo_j_per_token": solo_jpt,
        "attribution_error": errors,
        "tenant_table_agrees": table_agrees,
        "tenants": summary.get("tenants"),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0 if line["passed"] and table_agrees else 1


def preemption_overload_bench() -> int:
    """SLO tiers + mid-flight preemption under overload (ISSUE 11):
    the SAME seeded tiered Poisson trace — a 2×-pool-saturating storm
    of long LOW-tier rows with a short HIGH-tier minority riding a
    per-request deadline — replayed through three continuous-scheduler
    arms on one tiny PAGED JaxEngine:

    - **shed_only** (``preempt_policy="off"``): the pre-ISSUE-11
      overload response — a high-tier ticket that cannot be admitted
      waits behind low-tier long rows until its deadline sheds it;
    - **preempt_swap**: the victim's KV pages spill to host memory and
      restore bit-exactly at resume;
    - **preempt_recompute**: the victim's KV is dropped and
      re-prefilled through the chunked-join machinery at resume.

    Headlines: HIGH-TIER TTFT p99 + served fraction (the SLO the tiers
    exist for), total GOODPUT tokens (llm_engine_goodput_tokens_total
    delta — preemption must not torch aggregate useful work), swap
    bytes out/in, and PARITY of every resumed row against its solo
    generate() oracle. CPU-functional, seeded; relative positions are
    the result (docs/PERF.md "SLO tiers + preemption"). One JSON line.
    """
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
        GOODPUT_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        SWAP_BYTES_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.protocol import (
        PRIORITY_TIERS,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        cfg = cfg.tiny()
    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.bfloat16 if on_accelerator else jnp.float32,
        decode_attention="auto" if on_accelerator else None,
        paged_kv=True,  # page swap is the tentpole under test
    )

    n = int(_os.environ.get("BENCH_PO_REQUESTS", "18"))
    mean_ms = float(_os.environ.get("BENCH_PO_INTERARRIVAL_MS", "15"))
    deadline_ms = float(_os.environ.get("BENCH_PO_DEADLINE_MS", "2500"))
    slice_steps = int(_os.environ.get("BENCH_PO_SLICE_STEPS", "8"))
    high = PRIORITY_TIERS["high"]
    # long low-tier budgets vs a storm-tight arrival clock: concurrent
    # page demand runs ~2× the pool the first arrival sizes (the bench
    # reports the measured ratio as overload_x)
    budgets = (96, 128, 48)
    workload = build_workload(
        n, mean_ms / 1e3, seed=29, model=cfg.name, budgets=budgets,
        stop_at_eos=False, deadline_ms=deadline_ms,
        tier_mix={"high": 0.25, "low": 0.75},
    )
    solo = {id(req): engine.generate(req).tokens for _, req in workload}

    # warm every compiled shape once so arm walls compare policies, not
    # compilation
    warm = engine.decode_open(
        [req for _, req in workload[:4]], reserve_rows=8
    )
    while warm.active:
        warm.step(slice_steps)
    warm.close()

    def run_arm(policy):
        sched = ContinuousScheduler(
            engine,
            slice_steps=slice_steps,
            preempt_policy=policy,
            preempt_max_wait_s=5.0,
        )
        tokens_by_req = {}
        extras_by_req = {}
        pool_stats = {"pages": 0, "high_water": 0}

        def submit(req):
            res = sched.submit(req)
            tokens_by_req[id(req)] = res.tokens
            extras_by_req[id(req)] = (res.extras or {}).get("sched", {})
            dbg = sched._dbg
            if dbg is not None:
                try:
                    pool = dbg[0].pool
                    pool_stats["pages"] = pool.n_pages
                    pool_stats["high_water"] = max(
                        pool_stats["high_water"],
                        pool.n_pages - pool.free_pages,
                    )
                except Exception:  # noqa: BLE001 — racing close()
                    pass
            return res

        goodput0 = GOODPUT_C.labels().value
        swap_out0 = SWAP_BYTES_C.labels(direction="out").value
        swap_in0 = SWAP_BYTES_C.labels(direction="in").value
        sched.start()
        try:
            records = run_load(submit, workload)
        finally:
            sched.stop()
        resumed_ids = [
            i for i, ex in extras_by_req.items() if ex.get("resumed")
        ]
        # page demand the trace actually put up, relative to the pool
        demand_pages = None
        if pool_stats["pages"]:
            per_row = [
                -(-(len(req.prompt) + 1 + req.max_new_tokens) // 128)
                for _, req in workload
            ]
            demand_pages = sum(per_row)
        return {
            **summarize(records),
            "goodput_tokens": int(GOODPUT_C.labels().value - goodput0),
            "swap_bytes_out": int(
                SWAP_BYTES_C.labels(direction="out").value - swap_out0
            ),
            "swap_bytes_in": int(
                SWAP_BYTES_C.labels(direction="in").value - swap_in0
            ),
            "resumed_rows": len(resumed_ids),
            "resumed_parity_vs_solo": all(
                tokens_by_req.get(i) == solo[i] for i in resumed_ids
            ),
            "pool_pages": pool_stats["pages"],
            "pool_high_water_pages": pool_stats["high_water"],
            "overload_x": (
                round(demand_pages / pool_stats["pages"], 2)
                if pool_stats["pages"]
                else None
            ),
        }

    results = {
        "shed_only": run_arm("off"),
        "preempt_swap": run_arm("swap"),
        "preempt_recompute": run_arm("recompute"),
    }

    def high_p99(arm):
        return (results[arm].get("tiers", {}).get(str(high), {})).get(
            "ttft_p99_s"
        )

    base_p99 = high_p99("shed_only")
    line = {
        "metric": "preemption_overload",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "n_layers": cfg.n_layers,
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "deadline_ms": deadline_ms,
        "budgets": list(budgets),
        "tier_mix": {"high": 0.25, "low": 0.75},
        "decode_slice_steps": slice_steps,
        **results,
        "high_tier_ttft_p99_gain_swap": (
            round(base_p99 / high_p99("preempt_swap"), 2)
            if base_p99 and high_p99("preempt_swap")
            else None
        ),
        "high_tier_ttft_p99_gain_recompute": (
            round(base_p99 / high_p99("preempt_recompute"), 2)
            if base_p99 and high_p99("preempt_recompute")
            else None
        ),
        "goodput_ratio_swap": (
            round(
                results["preempt_swap"]["goodput_tokens"]
                / results["shed_only"]["goodput_tokens"],
                3,
            )
            if results["shed_only"]["goodput_tokens"]
            else None
        ),
        "goodput_ratio_recompute": (
            round(
                results["preempt_recompute"]["goodput_tokens"]
                / results["shed_only"]["goodput_tokens"],
                3,
            )
            if results["shed_only"]["goodput_tokens"]
            else None
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def shared_prefix_bench() -> int:
    """A/B of shared-prefix copy-on-write paging (ISSUE 7) on a
    high-share Poisson trace: the chunked-join baseline (every joiner
    prefills its whole prompt) vs `prefix_share=True` (joiners map the
    anchor's refcounted read-only prefix pages and chunk-prefill only
    the divergent tail).

    Headline figures at the same seeded trace: joiner TTFT p50/p95,
    prefill tokens actually COMPUTED (prompt tokens minus
    llm_prefix_hit_tokens_total's delta), pool high-water (peak pages
    in use — shared pages billed once shrink it), aggregate tok/s
    (sharing must not cost throughput), and bit-parity of every stream
    vs solo generate() in BOTH arms. A second part drives sessions
    directly on the bf16 AND int8 paged pools: N sharers admitted then
    all retired (incl. a mid-flight cancellation) must restore the
    pool free-count EXACTLY, and close() must restore it fully.
    CPU-functional like the chunked_join bench; RELATIVE positions are
    the result (docs/PERF.md "Shared-prefix CoW paging"). Prints ONE
    JSON line.
    """
    import os as _os
    import sys as _sys
    import threading as _threading

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, percentile, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
        _POOL_FREE,
        _POOL_PAGES,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.prefix import (
        PREFIX_COW_COPIES_C,
        PREFIX_HIT_TOKENS_C,
        PREFIX_SHARED_PAGES_G,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        # room for the 192-token shared prefix + tails + budgets
        cfg = cfg.tiny(max_seq_len=1024)
    dtype = jnp.bfloat16 if on_accelerator else jnp.float32

    # arrivals dense enough that admission prefill CONTENDS with decode
    # (the regime sharing exists for: under a sparse trace both arms
    # idle between joiners and the A/B only moves TTFT)
    n = int(_os.environ.get("BENCH_SP_REQUESTS", "16"))
    mean_ms = float(_os.environ.get("BENCH_SP_INTERARRIVAL_MS", "25"))
    chunk_tokens = int(_os.environ.get("BENCH_SP_CHUNK_TOKENS", "64"))
    slice_steps = int(_os.environ.get("BENCH_SP_SLICE_STEPS", "8"))
    share_frac = float(_os.environ.get("BENCH_SP_SHARE_FRAC", "0.75"))
    prefix_tokens = int(_os.environ.get("BENCH_SP_PREFIX_TOKENS", "192"))
    # anchor rotates onto the LONG budget so the session outlives the
    # arrivals and carries the page-backed shared prefix (see
    # anchor_shared_prefix in scripts/poisson_load.py)
    budgets = (192, 10, 16)
    workload = build_workload(
        n,
        mean_ms / 1e3,
        seed=7,
        model=cfg.name,
        budgets=budgets,
        stop_at_eos=False,  # fixed lengths: both arms do equal work
        shared_prefix_frac=share_frac,
        prefix_pool=1,
        shared_prefix_tokens=prefix_tokens,
        anchor_shared_prefix=True,
    )
    prompt_tokens = [len(req.prompt) + 1 for _, req in workload]
    shared_requests = sum(
        1 for _, req in workload if req.prompt.startswith("<sys0>")
    )

    def make_engine(share: bool) -> JaxEngine:
        return JaxEngine(
            registry={cfg.name: cfg},
            dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            paged_kv=True,
            prefix_share=share,
        )

    engines = {False: make_engine(False), True: make_engine(True)}
    # solo references: parity oracle AND warm-up of the solo shapes
    solo = {
        id(req): engines[False].generate(req).tokens for _, req in workload
    }

    def run_arm(share: bool):
        engine = engines[share]
        if share and engine.prefix_store is not None:
            # the ISSUE-14 store is ENGINE-lifetime: drop the previous
            # run's publications so every arm (warm and measured)
            # starts empty — this bench measures the WITHIN-session
            # win at PR-7 semantics; bench.py radix_prefix measures
            # the cross-session story deliberately
            engine.prefix_store.release_all()
        sched = ContinuousScheduler(
            engine,
            slice_steps=slice_steps,
            prefill_chunk_tokens=chunk_tokens,
            chunked_joins=True,
        )
        hits0 = PREFIX_HIT_TOKENS_C.labels().value
        cow0 = PREFIX_COW_COPIES_C.labels().value
        tokens_by_req = {}
        high_water = {"pages": 0.0, "shared": 0.0}
        stop_probe = _threading.Event()

        def probe():
            while not stop_probe.wait(0.01):
                total = _POOL_PAGES.labels().value
                free = _POOL_FREE.labels().value
                high_water["pages"] = max(
                    high_water["pages"], total - free
                )
                high_water["shared"] = max(
                    high_water["shared"], PREFIX_SHARED_PAGES_G.labels().value
                )

        def submit(req):
            res = sched.submit(req)
            tokens_by_req[id(req)] = res.tokens
            return res

        sched.start()
        prober = _threading.Thread(target=probe, daemon=True)
        prober.start()
        try:
            records = run_load(submit, workload)
        finally:
            sched.stop()
            stop_probe.set()
            prober.join(timeout=2)
        joiners = [r for r in records if r.get("joined")]
        joiner_ttfts = [
            r["ttft_s"] for r in joiners if r.get("ttft_s") is not None
        ]
        hit_tokens = PREFIX_HIT_TOKENS_C.labels().value - hits0
        return {
            **summarize(records),
            "joined": len(joiners),
            "joiner_ttft_p50_s": (
                round(percentile(joiner_ttfts, 50), 4)
                if joiner_ttfts
                else None
            ),
            "joiner_ttft_p95_s": (
                round(percentile(joiner_ttfts, 95), 4)
                if joiner_ttfts
                else None
            ),
            "prefill_tokens_total": sum(prompt_tokens),
            "prefix_hit_tokens": int(hit_tokens),
            "prefill_tokens_computed": int(sum(prompt_tokens) - hit_tokens),
            "cow_copies": int(PREFIX_COW_COPIES_C.labels().value - cow0),
            "pool_high_water_pages": int(high_water["pages"]),
            "shared_pages_high_water": int(high_water["shared"]),
            "parity_vs_solo": all(
                tokens_by_req.get(i) == toks for i, toks in solo.items()
            ),
        }

    # warm BOTH arms outside the measured traces (session shapes, chunk
    # prefill buckets, stepped decode fns — neither arm may pay XLA)
    run_arm(False)
    run_arm(True)
    results = {"baseline": run_arm(False), "prefix_share": run_arm(True)}

    # part 2: exact pool accounting on both quantizations — N sharers
    # admitted then all retired (eos/budget AND a mid-flight cancel)
    # restore the free-count exactly; close() restores the pool fully
    accounting = {}
    shared_sys = "<sys0>" + "s" * (prefix_tokens - 7)
    for kv in (None, "int8"):
        eng = JaxEngine(
            registry={cfg.name: cfg},
            dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            paged_kv=True,
            kv_quantize=kv,
            prefix_share=True,
        )
        anchor = GenerationRequest(
            cfg.name, shared_sys + " anchor", max_new_tokens=160,
            stop_at_eos=False, seed=1,
        )
        sess = eng.decode_open([anchor], reserve_rows=8)
        sess.step(4)
        free_before = sess.pool.free_pages
        sharers = [
            GenerationRequest(
                cfg.name, shared_sys + f" q{k}", max_new_tokens=8,
                stop_at_eos=False, seed=k + 2,
            )
            for k in range(3)
        ]
        for req in sharers[:2]:
            sess.join(req)
        sess.join(sharers[2])
        sess.cancel(sharers[2])  # the cancellation path frees shared refs too
        done = 0
        while done < 2:
            done += len(sess.step(8))
        restored = sess.pool.free_pages == free_before
        total = sess.pool.n_pages
        sess.close()
        accounting["int8" if kv else "bf16"] = {
            "free_restored_after_sharers": bool(restored),
            "close_restores_pool": sess.pool.free_pages == total - 1,
        }

    line = {
        "metric": "shared_prefix",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "n_layers": cfg.n_layers,
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "budgets": list(budgets),
        "shared_prefix": {
            "frac": share_frac,
            "tokens": prefix_tokens,
            "pool": 1,
            "shared_requests": shared_requests,
        },
        "prefill_chunk_tokens": chunk_tokens,
        "decode_slice_steps": slice_steps,
        **results,
        "joiner_ttft_p50_ratio": (
            round(
                results["baseline"]["joiner_ttft_p50_s"]
                / results["prefix_share"]["joiner_ttft_p50_s"],
                2,
            )
            if results["baseline"]["joiner_ttft_p50_s"]
            and results["prefix_share"]["joiner_ttft_p50_s"]
            else None
        ),
        "computed_prefill_ratio": (
            round(
                results["prefix_share"]["prefill_tokens_computed"]
                / results["baseline"]["prefill_tokens_computed"],
                3,
            )
            if results["baseline"]["prefill_tokens_computed"]
            else None
        ),
        "pool_accounting": accounting,
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def radix_prefix_bench() -> int:
    """A/B of the ISSUE-14 persistent cross-session prefix store on a
    seeded MULTI-SESSION trace: the same requests replay through S
    session segments, each driven by a FRESH ContinuousScheduler over
    the same engine (a scheduler restart mid-trace), with a high
    shared-prefix fraction inside every segment.

    Arms (same trace, same engine shapes):
    - ``session_scoped``: prefix_store_scope="session" — the PR-7
      lifetime (the store's tree dies with each session's pool), so
      hits only happen WITHIN a segment;
    - ``engine_store``: the ISSUE-14 default — publications survive
      session close and scheduler restarts, so later segments' joiners
      hit prefixes published before the restart;
    - ``engine_store_spill``: engine scope under maximal HBM budget
      pressure (prefix_store_hbm_bytes=0) — every publication spills
      to host and every cross-session hit must RESTORE, measuring the
      hit-rate with spill pressure.

    Headlines: cross-session hit tokens (post-restart hit tokens the
    session-scoped arm cannot get), joiner TTFT p50, prefill tokens
    actually computed, and the store's hit/spill/restore counters.
    CPU-functional; RELATIVE positions are the result (docs/PERF.md
    "Persistent prefix store"). Prints ONE JSON line.
    """
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, percentile, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.prefix import (
        PREFIX_HIT_TOKENS_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.radix_store import (
        STORE_HITS_C,
        STORE_RESTORES_C,
        STORE_SPILLS_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        cfg = cfg.tiny(max_seq_len=1024)
    dtype = jnp.bfloat16 if on_accelerator else jnp.float32

    sessions = int(_os.environ.get("BENCH_RP_SESSIONS", "3"))
    n_per = int(_os.environ.get("BENCH_RP_REQUESTS_PER_SESSION", "6"))
    mean_ms = float(_os.environ.get("BENCH_RP_INTERARRIVAL_MS", "25"))
    chunk_tokens = int(_os.environ.get("BENCH_RP_CHUNK_TOKENS", "64"))
    slice_steps = int(_os.environ.get("BENCH_RP_SLICE_STEPS", "8"))
    prefix_tokens = int(_os.environ.get("BENCH_RP_PREFIX_TOKENS", "192"))
    share_frac = float(_os.environ.get("BENCH_RP_SHARE_FRAC", "0.75"))
    budgets = (96, 10, 16)  # anchor outlives the arrivals (see PR-7 bench)
    segments = [
        build_workload(
            n_per,
            mean_ms / 1e3,
            seed=7 + s,
            model=cfg.name,
            budgets=budgets,
            stop_at_eos=False,
            shared_prefix_frac=share_frac,
            prefix_pool=1,
            shared_prefix_tokens=prefix_tokens,
            anchor_shared_prefix=True,
        )
        for s in range(sessions)
    ]
    all_requests = [req for seg in segments for _, req in seg]
    prompt_tokens_total = sum(len(r.prompt) + 1 for r in all_requests)

    solo_eng = JaxEngine(
        registry={cfg.name: cfg},
        dtype=dtype,
        decode_attention="auto" if on_accelerator else None,
        paged_kv=True,
    )
    solo = {id(r): solo_eng.generate(r).tokens for r in all_requests}

    def run_arm(scope: str, hbm_bytes=None):
        engine = JaxEngine(
            registry={cfg.name: cfg},
            dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            paged_kv=True,
            prefix_share=True,
            prefix_store_scope=scope,
            prefix_store_hbm_bytes=hbm_bytes,
        )
        hits_t0 = PREFIX_HIT_TOKENS_C.labels().value
        c0 = {
            "hits": STORE_HITS_C.labels().value,
            "spills": STORE_SPILLS_C.labels().value,
            "restores": STORE_RESTORES_C.labels().value,
        }
        records = []
        hit_tokens_by_segment = []
        tokens_by_req = {}
        for segment in segments:
            seg_hits0 = PREFIX_HIT_TOKENS_C.labels().value
            sched = ContinuousScheduler(
                engine,
                slice_steps=slice_steps,
                prefill_chunk_tokens=chunk_tokens,
                chunked_joins=True,
            )

            def submit(req, _s=sched):
                res = _s.submit(req)
                tokens_by_req[id(req)] = res.tokens
                return res

            sched.start()
            try:
                records.extend(run_load(submit, segment))
            finally:
                sched.stop()  # the mid-trace scheduler restart
            hit_tokens_by_segment.append(
                PREFIX_HIT_TOKENS_C.labels().value - seg_hits0
            )
        joiners = [r for r in records if r.get("joined")]
        joiner_ttfts = [
            r["ttft_s"] for r in joiners if r.get("ttft_s") is not None
        ]
        hit_tokens = PREFIX_HIT_TOKENS_C.labels().value - hits_t0
        return {
            **summarize(records),
            "joined": len(joiners),
            "joiner_ttft_p50_s": (
                round(percentile(joiner_ttfts, 50), 4)
                if joiner_ttfts
                else None
            ),
            "prefix_hit_tokens": int(hit_tokens),
            "hit_tokens_after_restart": int(
                sum(hit_tokens_by_segment[1:])
            ),
            "prefill_tokens_total": prompt_tokens_total,
            "prefill_tokens_computed": int(prompt_tokens_total - hit_tokens),
            "store_hits": int(STORE_HITS_C.labels().value - c0["hits"]),
            "store_spills": int(
                STORE_SPILLS_C.labels().value - c0["spills"]
            ),
            "store_restores": int(
                STORE_RESTORES_C.labels().value - c0["restores"]
            ),
            "parity_vs_solo": all(
                tokens_by_req.get(i) == toks for i, toks in solo.items()
            ),
        }

    run_arm("engine")  # warm every shape outside the measured arms
    results = {
        "session_scoped": run_arm("session"),
        "engine_store": run_arm("engine"),
        "engine_store_spill": run_arm("engine", hbm_bytes=0),
    }
    cross = (
        results["engine_store"]["hit_tokens_after_restart"]
        - results["session_scoped"]["hit_tokens_after_restart"]
    )
    line = {
        "metric": "radix_prefix",
        "unit": "latency_seconds",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "sessions": sessions,
        "requests_per_session": n_per,
        "shared_prefix": {"frac": share_frac, "tokens": prefix_tokens},
        **results,
        "cross_session_hit_tokens": int(cross),
        "computed_prefill_ratio": (
            round(
                results["engine_store"]["prefill_tokens_computed"]
                / results["session_scoped"]["prefill_tokens_computed"],
                3,
            )
            if results["session_scoped"]["prefill_tokens_computed"]
            else None
        ),
        "joiner_ttft_p50_ratio": (
            round(
                results["session_scoped"]["joiner_ttft_p50_s"]
                / results["engine_store"]["joiner_ttft_p50_s"],
                2,
            )
            if results["session_scoped"]["joiner_ttft_p50_s"]
            and results["engine_store"]["joiner_ttft_p50_s"]
            else None
        ),
        "spill_pressure_hit_rate": (
            round(
                results["engine_store_spill"]["store_hits"]
                / max(1, results["engine_store"]["store_hits"]),
                3,
            )
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def model_fleet_bench() -> int:
    """A/B of ISSUE-15 multi-model fleet serving on ONE seeded mixed
    trace (two tiny models — "small" and a 3×-deeper "big" — arrivals
    and per-request model assignment drawn once by the
    ``poisson_load --model-mix`` machinery, then shaped per phase).

    TTFT phase (head-of-line blocking; big-anchor shaping — request 0
    is a LONG big-model decode, the rest keep their seeded models and
    gaps):
    - ``small_solo``: only the trace's small-model requests, their own
      scheduler — the small model's UNCONTENDED TTFT reference;
    - ``serialized``: the full mixed trace through ONE model-affine
      ContinuousScheduler (the pre-ISSUE-15 shape) — small tickets
      queue behind the big model's whole session;
    - ``fleet``: the same trace through the ModelFleetScheduler —
      per-model lanes interleave decode slices under one backend lock,
      so small TTFT p99 stays within ~1.2× of solo while the
      serialized baseline blows up by multiples.

    Energy phase (the paper's headline restated ONLINE; throughput
    shaping — same arrivals/models, moderate budgets — at matched
    token output across arms):
    - ``always_big``: every request pinned to the big model (the
      "serve everything from the flagship" default);
    - ``auto_cheapest``: every request ``model:"auto"`` under
      cheapest-joules;
    - ``auto_small_first``: every request ``model:"auto"`` under the
      small-first cascade — long-budget length-cut answers ESCALATE,
      and the abandoned small-model work is COUNTED in the arm's J.

    Fleet J is accounted at the FLEET level: one chip's idle power for
    the arm's wall clock (concurrent rows share the idle window —
    summing per-row solo estimates would bill it once per row and
    penalise exactly the concurrency under test) plus each served
    token's marginal compute/HBM energy at the SERVING model's config,
    plus the escalated attempts' abandoned marginal work. Every arm
    checks per-model token parity vs solo ``generate()`` and exact
    per-model pool free-count restoration. CPU-functional; RELATIVE
    positions are the result (docs/PERF.md "Multi-model fleet
    serving"). Prints ONE JSON line.
    """
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import (
        build_workload,
        percentile,
        run_load,
        summarize,
        synth_prompt,
    )

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
        energy as obs_energy,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.model_fleet import (
        ModelFleetScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    dtype = jnp.bfloat16 if on_accelerator else jnp.float32
    tiny = get_model_config("qwen2:1.5b").tiny(max_seq_len=1024)
    SMALL, BIG = "tiny-small", "tiny-big"
    small_cfg = dataclasses.replace(tiny, name=SMALL)
    # the "big" model: 3× the depth and twice the FFN — ~4× the weight
    # stream, so its J/token is measurably higher and size ordering is
    # unambiguous
    big_cfg = dataclasses.replace(tiny, name=BIG, n_layers=6, d_ff=256)
    registry = {SMALL: small_cfg, BIG: big_cfg}

    n = int(_os.environ.get("BENCH_MF_REQUESTS", "7"))
    mean_ms = float(_os.environ.get("BENCH_MF_INTERARRIVAL_MS", "250"))
    small_budget = int(_os.environ.get("BENCH_MF_SMALL_BUDGET", "6"))
    anchor_budget = int(_os.environ.get("BENCH_MF_ANCHOR_BUDGET", "400"))
    small_prompt = int(_os.environ.get("BENCH_MF_SMALL_PROMPT", "256"))
    escalate_floor = int(_os.environ.get("BENCH_MF_ESCALATE_TOKENS", "32"))
    slice_steps = int(_os.environ.get("BENCH_MF_SLICE_STEPS", "1"))
    chunk_tokens = int(_os.environ.get("BENCH_MF_CHUNK_TOKENS", "32"))
    mix = {SMALL: 0.8, BIG: 0.2}
    base_trace = build_workload(
        n,
        mean_ms / 1e3,
        seed=11,
        model=SMALL,
        stop_at_eos=False,  # deterministic length-cut (the escalation
        # trigger) — no dependence on tiny random weights sampling EOS
        model_mix=mix,
    )

    def shape(budgets: "dict") -> list:
        """Shape the ONE seeded trace for a phase: request 0 becomes
        the BIG anchor (arriving 350 ms early), everyone else keeps
        their seeded model and arrival gap; smalls carry a real prefill
        (small_prompt tokens). ``budgets`` maps anchor/small/big/open
        to token budgets — the last small request is the OPEN-ENDED one
        (budget past the escalation floor) so the small-first cascade
        escalates a FRACTION of auto traffic, not all of it."""
        shaped = []
        for i, (off, req) in enumerate(base_trace):
            if i == 0:
                shaped.append(
                    (
                        0.0,
                        dataclasses.replace(
                            req,
                            model=BIG,
                            prompt=synth_prompt(128),
                            max_new_tokens=budgets["anchor"],
                        ),
                    )
                )
                continue
            if req.model == BIG:
                entry = dataclasses.replace(
                    req, max_new_tokens=budgets["big"]
                )
            else:
                entry = dataclasses.replace(
                    req,
                    prompt=synth_prompt(small_prompt) + f" q{i}",
                    max_new_tokens=budgets["small"],
                )
            shaped.append((0.35 + off, entry))
        for i in range(len(shaped) - 1, 0, -1):
            off, req = shaped[i]
            if req.model == SMALL:
                shaped[i] = (
                    off,
                    dataclasses.replace(req, max_new_tokens=budgets["open"]),
                )
                break
        return shaped

    hol_trace = shape(
        {
            "anchor": anchor_budget,
            "small": small_budget,
            "big": 24,
            "open": small_budget,
        }
    )
    # throughput shaping for the energy arms: moderate budgets so no
    # single request dominates the token mass
    energy_trace = shape(
        {"anchor": 64, "small": 24, "big": 24, "open": 48}
    )
    if not any(req.model == SMALL for _, req in hol_trace):
        raise RuntimeError("seeded mix drew no small-model requests")

    def fresh_engine() -> JaxEngine:
        return JaxEngine(
            registry=dict(registry),
            dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            paged_kv=True,
        )

    # solo references: token-parity target + the marginal-energy source
    # for abandoned (escalated) small attempts — one solo generate()
    # per (model, request shape)
    solo_eng = fresh_engine()
    solo_results: dict = {}

    def solo_for(model: str, req):
        key = (model, req.prompt, req.seed, req.max_new_tokens)
        if key not in solo_results:
            solo_results[key] = solo_eng.generate(
                dataclasses.replace(req, model=model)
            )
        return solo_results[key]

    # Energy accounting, V5E-MODELLED (the repo's roofline convention —
    # tp_continuous/spec_continuous record honest CPU walls NEXT TO the
    # v5e prediction): a depth-reduced model's CPU wall is dispatch-
    # dominated and cannot tell a 2-layer model from a 6-layer one, so
    # each request is priced by the SAME run-table energy model the
    # study uses, at the serving model's flops/bytes, over the v5e
    # bandwidth-bound duration (decode is HBM-bound: t = bytes / BW).
    # One chip serializes the fleet's compute, so per-request modelled
    # windows sum without double-counting the idle power.
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (  # noqa: E501
        generation_stats_from,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (  # noqa: E501
        V5E_SUSTAINED_HBM_GBPS,
    )

    def modelled_j(model: str, result) -> float:
        stats = generation_stats_from(registry[model], result)
        if not stats or not stats.get("bytes"):
            return 0.0
        stats = {
            **stats,
            "duration_s": stats["bytes"] / (V5E_SUSTAINED_HBM_GBPS * 1e9),
        }
        est = obs_energy.estimate_from_stats(stats, n_chips=1)
        return float(est["J"]) if est and est.get("J") else 0.0

    def pool_restored(engine, model: str) -> bool:
        """Exact per-model pool free-count restoration: open a session,
        run every row to retirement — all row pages must be back on the
        free list (only the session's parking page stays held)."""
        sess = engine.decode_open(
            [GenerationRequest(model, "restore probe", max_new_tokens=8)]
        )
        try:
            while sess.active:
                sess.step(8)
            return sess.pool.free_pages == sess.pool.n_pages - 1
        finally:
            sess.close()

    def run_arm(
        name: str,
        arm_trace,
        policy: "str | None" = None,
        resolved_model=None,
    ):
        """One arm: a fresh engine + scheduler, the seeded trace, TTFT/
        throughput records, fleet-level Joules and the parity/
        restoration checks. ``resolved_model(req)`` maps each request
        to the model expected to SERVE it (parity target); None = the
        request's own model."""
        engine = fresh_engine()
        if policy is not None:
            sched = ModelFleetScheduler(
                engine,
                models=[SMALL, BIG],
                model_policy=policy,
                escalate_max_tokens=escalate_floor,
                slice_steps=slice_steps,
                prefill_chunk_tokens=chunk_tokens,
            )
        else:
            sched = ContinuousScheduler(
                engine,
                slice_steps=slice_steps,
                prefill_chunk_tokens=chunk_tokens,
            )
        results: dict = {}

        def submit(req, _s=sched):
            res = _s.submit(req)
            results[id(req)] = res
            return res

        sched.start()
        t_arm0 = time.monotonic()
        try:
            records = run_load(submit, arm_trace)
        finally:
            arm_wall_s = time.monotonic() - t_arm0
            sched.stop()
        served_j = 0.0
        abandoned_j = 0.0
        tokens = 0
        parity = True
        for _off, req in arm_trace:
            res = results.get(id(req))
            if res is None:
                parity = False
                continue
            served = res.request.model
            expect = resolved_model(req) if resolved_model else req.model
            if served != expect:
                parity = False
            if res.tokens != solo_for(served, req).tokens:
                parity = False
            tokens += res.generated_tokens
            served_j += modelled_j(served, res)
            fleet_extras = (res.extras or {}).get("fleet", {})
            if fleet_extras.get("escalated"):
                # the abandoned small attempt decoded exactly what a
                # solo small run of this request decodes — its modelled
                # window is charged to the arm too
                frm = fleet_extras["escalated_from"]
                abandoned_j += modelled_j(frm, solo_for(frm, req))
        fleet_j = served_j + abandoned_j
        small_ttfts = [
            r["ttft_s"]
            for r in records
            if r.get("model") == SMALL and r.get("ttft_s") is not None
        ]
        out = {
            **summarize(records),
            "small_ttft_p99_s": (
                round(percentile(small_ttfts, 99), 4)
                if small_ttfts
                else None
            ),
            "wall_s": round(arm_wall_s, 3),
            "v5e_served_J": round(served_j, 6),
            "v5e_abandoned_escalation_J": round(abandoned_j, 6),
            "fleet_J": round(fleet_j, 6),
            "fleet_J_per_token": (
                round(fleet_j / tokens, 9) if tokens else None
            ),
            "parity_vs_solo": parity,
            "pool_restored": {
                m: pool_restored(engine, m) for m in (SMALL, BIG)
            },
        }
        return out

    small_only = [
        (off, req) for off, req in hol_trace if req.model == SMALL
    ]
    # energy arms: EVERYTHING asks for model:"auto" (vs the always-big
    # single-model default) — the acceptance A/B at matched budgets
    auto_energy = [
        (off, dataclasses.replace(req, model="auto"))
        for off, req in energy_trace
    ]
    big_energy = [
        (off, dataclasses.replace(req, model=BIG))
        for off, req in energy_trace
    ]

    def small_first_resolved(req):
        # deterministic cascade outcome: every answer is length-cut
        # (stop_at_eos=False), so auto requests at/above the floor
        # escalate; named requests serve where they asked
        if req.model != "auto":
            return req.model
        return BIG if req.max_new_tokens >= escalate_floor else SMALL

    def cheapest_resolved(req):
        return SMALL if req.model == "auto" else req.model

    # compile every shape outside the measured arms
    run_arm("warm_fleet", hol_trace, policy="small-first")
    run_arm("warm_serialized", hol_trace)
    run_arm(
        "warm_auto",
        auto_energy,
        policy="small-first",
        resolved_model=small_first_resolved,
    )
    run_arm("warm_big", big_energy)
    arms = {
        "small_solo": run_arm("small_solo", small_only),
        "serialized": run_arm("serialized", hol_trace),
        "fleet": run_arm("fleet", hol_trace, policy="small-first"),
        "always_big": run_arm("always_big", big_energy),
        "auto_cheapest": run_arm(
            "auto_cheapest",
            auto_energy,
            policy="cheapest-joules",
            resolved_model=cheapest_resolved,
        ),
        "auto_small_first": run_arm(
            "auto_small_first",
            auto_energy,
            policy="small-first",
            resolved_model=small_first_resolved,
        ),
    }
    solo_p99 = arms["small_solo"]["small_ttft_p99_s"]

    def ratio(a, b):
        return (
            round(a / b, 3)
            if a is not None and b not in (None, 0)
            else None
        )

    fleet_vs_solo = ratio(arms["fleet"]["small_ttft_p99_s"], solo_p99)
    line = {
        "metric": "model_fleet",
        "unit": "latency_seconds",
        "models": {SMALL: "2L/d64", BIG: "6L/d64/ff256"},
        "backend": jax.default_backend(),
        "requests": n,
        "model_mix": mix,
        "escalate_max_tokens": escalate_floor,
        **arms,
        # (a) head-of-line blocking: fleet small TTFT p99 vs its solo
        # figure (target ≤ ~1.2×) next to the serialized baseline's
        # multiple-× blowup on the SAME trace
        "small_ttft_p99_fleet_vs_solo": fleet_vs_solo,
        "small_ttft_p99_serialized_vs_solo": ratio(
            arms["serialized"]["small_ttft_p99_s"], solo_p99
        ),
        "no_hol_blocking": bool(
            fleet_vs_solo is not None
            and fleet_vs_solo
            <= float(_os.environ.get("BENCH_MF_HOL_FACTOR", "1.2"))
        ),
        # (b) the paper's headline online: auto-routing fleet J/token
        # vs always-big single-model at matched token output
        # (escalation's abandoned work INCLUDED in the auto arms' J)
        "j_per_token_cheapest_vs_always_big": ratio(
            arms["auto_cheapest"]["fleet_J_per_token"],
            arms["always_big"]["fleet_J_per_token"],
        ),
        "j_per_token_small_first_vs_always_big": ratio(
            arms["auto_small_first"]["fleet_J_per_token"],
            arms["always_big"]["fleet_J_per_token"],
        ),
        "escalations": arms["auto_small_first"].get("escalations", 0),
        "parity_all_arms": all(a["parity_vs_solo"] for a in arms.values()),
        "pools_restored_all_arms": all(
            all(a["pool_restored"].values()) for a in arms.values()
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def _tp_continuous_arm(n_devices: int) -> int:
    """ONE arm of the tp_continuous A/B, run in its own process (the
    parent pins ``xla_force_host_platform_device_count`` in XLA_FLAGS —
    a device count is a process-lifetime property, so each arm needs a
    fresh interpreter). Serves a seeded Poisson trace through the
    continuous scheduler on an ``n_devices`` TP mesh, plus a CONTROLLED
    fixed-occupancy slice-timing phase whose per-step wall is what the
    1→n ratio is computed from. Prints ONE JSON line."""
    import os as _os
    import statistics as _stats
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    if len(jax.devices()) < n_devices:
        print(json.dumps({"error": f"need {n_devices} devices, have {len(jax.devices())}"}))
        return 1
    # tiny config whose 8 KV heads divide both mesh sizes — the SPMD
    # program shape (heads-sharded pool, replicated row control) is the
    # real one; only the arithmetic is CPU-sized
    cfg = dataclasses.replace(
        get_model_config("qwen2:1.5b").tiny(),
        n_heads=8, n_kv_heads=8, d_ff=128, d_model=64, d_head=16,
        max_seq_len=1024,
    )
    mesh = build_mesh(MeshSpec.tp_only(), devices=jax.devices()[:n_devices])
    engine = TensorParallelEngine(
        mesh=mesh,
        registry={cfg.name: cfg},
        dtype=jnp.float32,
        paged_kv=True,
    )
    slice_steps = 8
    rows = int(_os.environ.get("BENCH_TPC_ROWS", "8"))
    budget = 64

    # -- controlled phase: fixed occupancy, measured per-slice walls ------
    fleet = [
        GenerationRequest(
            cfg.name, f"row {i} holds its slot", max_new_tokens=budget,
            stop_at_eos=False, seed=100 + i,
        )
        for i in range(rows)
    ]
    solo = [engine.generate(r) for r in fleet]  # also warms every shape
    sess = engine.decode_open(
        fleet, reserve_rows=rows, slice_steps=slice_steps
    )
    sess.step(slice_steps)  # first slice pays any residual compile
    slice_walls = []
    results = []
    while sess.active:
        full = sess.active == rows
        t0 = time.monotonic()
        retired = sess.step(slice_steps)
        if full and sess.active == rows:  # full-occupancy slices only
            slice_walls.append(time.monotonic() - t0)
        results.extend(retired)
    parity = all(
        got.tokens == ref.tokens
        for ref, got in zip(
            solo,
            sorted(results, key=lambda r: fleet.index(r.request)),
        )
    )
    sess.close()
    mean_slice = _stats.mean(slice_walls) if slice_walls else None
    controlled = {
        "rows": rows,
        "slice_steps": slice_steps,
        "full_occupancy_slices": len(slice_walls),
        "mean_slice_s": round(mean_slice, 6) if mean_slice else None,
        "mean_step_s": (
            round(mean_slice / slice_steps, 6) if mean_slice else None
        ),
        "p95_slice_s": (
            round(sorted(slice_walls)[int(0.95 * (len(slice_walls) - 1))], 6)
            if slice_walls
            else None
        ),
    }

    # -- served phase: Poisson trace through the continuous scheduler -----
    n = int(_os.environ.get("BENCH_TPC_REQUESTS", "12"))
    mean_ms = float(_os.environ.get("BENCH_TPC_INTERARRIVAL_MS", "50"))
    workload = build_workload(
        n, mean_ms / 1e3, seed=11, model=cfg.name,
        budgets=(8, 16, 48),
        prompts=("alpha beta", "gamma delta epsilon", "zeta eta"),
        stop_at_eos=False,
    )
    for req in {r.max_new_tokens: r for _, r in workload}.values():
        engine.generate(req)  # warm the trace's buckets outside timing
    sched = ContinuousScheduler(engine, slice_steps=slice_steps)
    sched.start()
    try:
        records = run_load(sched.submit, workload)
    finally:
        sched.stop()
    poisson = summarize(records)

    # per-slice step-time breakdown as the flight recorder saw it: every
    # slice of BOTH phases, with rows + duration (forensics twin of the
    # controlled figure)
    slice_events = []
    try:
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
            FLIGHT,
        )

        slice_events = [
            {"rows": e.get("rows"), "dur_s": e.get("dur_s")}
            for e in FLIGHT.events(n=4096, type_="slice")
        ]
    except Exception:
        pass

    line = {
        "arm": "tp_continuous",
        "devices": n_devices,
        "mesh": engine.mesh_info(),
        "backend": jax.default_backend(),
        "model": cfg.name,
        "kv_heads_sharded": cfg.n_kv_heads % n_devices == 0
        and n_devices > 1,
        "parity_vs_solo": parity,
        "controlled": controlled,
        "poisson": poisson,
        "sched_slice_events": len(slice_events),
        "slice_time_by_rows": _slice_breakdown(slice_events),
    }
    print(json.dumps(line))
    return 0


def _slice_breakdown(slice_events) -> dict:
    """Group flight slice events by row count → {rows: {n, mean_s}}."""
    import statistics as _stats

    by_rows = {}
    for e in slice_events:
        if e.get("dur_s") is None:
            continue
        by_rows.setdefault(e.get("rows"), []).append(e["dur_s"])
    return {
        str(rows): {"n": len(ds), "mean_s": round(_stats.mean(ds), 6)}
        for rows, ds in sorted(
            by_rows.items(), key=lambda kv: (kv[0] is None, kv[0])
        )
    }


def spec_continuous_bench() -> int:
    """A/B of BATCHED speculative decoding inside the continuous
    scheduler (ISSUE 9) at 1/8/32-row Poisson traces: per arm the SAME
    seeded trace of greedy requests drives a ContinuousScheduler over a
    plain tiny engine and over one with an acceptance-friendly draft
    (the draft registry entry aliases the target config, so seeded init
    gives identical weights — every proposal is accepted, the upper
    bound of the Leviathan-style amortization the mode exists for;
    acceptance-hostile drafts are covered by the fallback tests).

    Reported per row count: aggregate tok/s both arms, the speculative
    arm's measured TOKENS-PER-TARGET-STEP (each retired row's decode
    tokens / its draft-verify rounds — 1.0 by definition in the plain
    arm; > 1.0 is the acceptance criterion), bit-exact parity of the
    two arms' token streams (both must be the target's greedy stream),
    and exact pool free-count restoration after join + cancel + close
    on bf16 AND int8 paged pools. The PAGED-NATIVE arm (ISSUE 10)
    records pages-billed-per-spec-row — native (slack-free) vs the
    retired legacy ``2k+2``-slack formula — and max-admission-rows at
    equal HBM budget for a spec vs a plain engine (the no-admission-tax
    acceptance criterion: spec ≥ plain). NEXT TO the
    measured CPU-functional numbers sits the v5e ROOFLINE column: the
    modelled speedup E[m]/(1 + k·c) for the paper's serving config
    (qwen2:1.5b int8 weights, ctx 512) with a ¼-depth self-draft
    (c = modelled draft/target step-time ratio), at the measured
    acceptance and at a conservative α=0.7 — the number a real-slice
    run should approach. Prints ONE JSON line."""
    import dataclasses as _dc
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    cfg = _dc.replace(
        cfg.tiny(max_seq_len=1024) if not on_accelerator else cfg,
        name="tiny-spec-target",
    )
    spec_k = int(_os.environ.get("BENCH_SPEC_K", "4"))
    registry = {"tiny-spec-target": cfg, "tiny-spec-draft": cfg}
    dtype = jnp.bfloat16 if on_accelerator else jnp.float32

    def make_engine(spec: bool) -> JaxEngine:
        return JaxEngine(
            registry=dict(registry),
            dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            speculative=(
                {"tiny-spec-target": ("tiny-spec-draft", spec_k)}
                if spec
                else None
            ),
        )

    budgets = (16, 32, 48)
    prompts = ("alpha beta", "gamma delta epsilon", "zeta eta")
    mean_ms = float(_os.environ.get("BENCH_SPEC_INTERARRIVAL_MS", "30"))

    arms = {}
    for rows in (1, 8, 32):
        workload = build_workload(
            rows, mean_ms / 1e3, seed=11, model=cfg.name,
            budgets=budgets, prompts=prompts, stop_at_eos=False,
        )
        per_rows = {}
        tokens_by_req = {}
        for arm in ("plain", "speculative"):
            engine = make_engine(arm == "speculative")
            # warm every compiled shape outside the measured trace
            warm = [req for _, req in workload[: min(rows, 6)]]
            sess = engine.decode_open(warm, reserve_rows=2 * len(warm))
            while sess.active:
                sess.step()
            sess.close()
            sched = ContinuousScheduler(engine)
            sched.start()
            results = []

            def submit(req, _sched=sched, _sink=results):
                res = _sched.submit(req)
                _sink.append(res)
                return res

            try:
                records = run_load(submit, workload)
            finally:
                sched.stop()
            summary = summarize(records)
            tokens_by_req[arm] = {
                f"{r.request.prompt}|{r.request.seed}"
                f"|{r.request.max_new_tokens}": r.tokens
                for r in results
            }
            tpts = None
            if arm == "speculative":
                per_row_ratios = [
                    (r.generated_tokens - 1) / r.extras["spec"]["rounds"]
                    for r in results
                    if (r.extras or {}).get("spec", {}).get("rounds")
                ]
                tpts = (
                    round(sum(per_row_ratios) / len(per_row_ratios), 3)
                    if per_row_ratios
                    else None
                )
            per_rows[arm] = {
                "agg_tokens_per_s": summary.get("agg_tokens_per_s"),
                "completion_p50_s": summary.get("completion_p50_s"),
                "tokens_per_target_step": tpts if tpts else (
                    1.0 if arm == "plain" else None
                ),
            }
        per_rows["parity_spec_vs_plain"] = (
            tokens_by_req["plain"] == tokens_by_req["speculative"]
        )
        arms[str(rows)] = per_rows

    # exact pool free-count restoration after join + cancel + retire +
    # close, on bf16 AND int8 paged pools — plus the ISSUE-10 paged-
    # native billing A/B: pages-billed-per-spec-row native vs the
    # retired legacy slack formula, and max-admission-rows at equal HBM
    # budget spec vs plain (no spec admission tax)
    restoration = {}
    paged_native = {}
    page = 128
    for kv in (None, "int8"):
        eng = JaxEngine(
            registry=dict(registry), dtype=dtype, paged_kv=True,
            kv_quantize=kv,
            decode_attention="auto" if on_accelerator else None,
            speculative={"tiny-spec-target": ("tiny-spec-draft", spec_k)},
        )
        plain_paged = JaxEngine(
            registry=dict(registry), dtype=dtype, paged_kv=True,
            kv_quantize=kv,
            decode_attention="auto" if on_accelerator else None,
        )
        # budgets sized so the anchor is STILL live across the join +
        # cancel (spec rounds advance ~k+1 tokens per step at full
        # acceptance — a short anchor would retire mid-check and return
        # its own pages, muddying the exactness assertion)
        anchor = GenerationRequest(
            cfg.name, "pool anchor", max_new_tokens=200, stop_at_eos=False
        )
        victim = GenerationRequest(
            cfg.name, "victim", max_new_tokens=150, stop_at_eos=False, seed=3
        )
        sess = eng.decode_open([anchor], reserve_rows=4)
        ok = sess.spec is not None
        # slack-free billing: the session's sizing rule bills a spec row
        # EXACTLY the plain-decode page count
        # the legacy column is the RETIRED rule: pre-ISSUE-10 spec rows
        # were excluded from stacked mode and billed prompt + budget +
        # 2k+2 slack through the table
        s_probe, mnt_probe = 100, 150
        native_pages = sess._pages_needed(s_probe, mnt_probe)
        legacy_pages = -(-(s_probe + mnt_probe + 2 * spec_k + 2) // page)
        plain_sess = plain_paged.decode_open([anchor], reserve_rows=2)
        ok = ok and native_pages == plain_sess._pages_needed(
            s_probe, mnt_probe
        )
        plain_sess.close()
        admission_req = GenerationRequest(
            cfg.name, "admission probe", max_new_tokens=mnt_probe,
            stop_at_eos=False,
        )
        adm_spec = eng.max_admission_rows(admission_req)
        adm_plain = plain_paged.max_admission_rows(admission_req)
        paged_native["bf16" if kv is None else "int8"] = {
            "pages_per_spec_row_native": int(native_pages),
            "pages_per_spec_row_legacy_formula": int(legacy_pages),
            "verify_mode": sess._verify_mode(),
            "max_admission_rows_spec": int(adm_spec),
            "max_admission_rows_plain": int(adm_plain),
            "no_spec_admission_tax": bool(adm_spec >= adm_plain),
        }
        free0 = sess.pool.free_pages
        sess.step(2)
        sess.join(victim)
        sess.step(2)
        ok = ok and sess.active == 2  # both rows still live
        ok = ok and sess.cancel(victim) and sess.pool.free_pages == free0
        while sess.active:
            sess.step()
        sess.close()
        ok = ok and sess.pool.free_pages == sess.pool.n_pages - 1
        restoration["bf16" if kv is None else "int8"] = bool(ok)

    # v5e roofline column: modelled speedup for the paper's serving
    # config with a ¼-depth self-draft
    roofline = None
    try:
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
            modeled_tp_decode_step_s,
        )

        full = get_model_config("qwen2:1.5b")
        draft_full = _dc.replace(full, n_layers=max(1, full.n_layers // 4))
        ctx = 512
        t_target = modeled_tp_decode_step_s(full, "int8", 1, ctx)
        c = modeled_tp_decode_step_s(draft_full, "int8", 1, ctx) / t_target

        def expected_m(alpha: float) -> float:
            if alpha >= 1.0:
                return spec_k + 1
            return (1 - alpha ** (spec_k + 1)) / (1 - alpha)

        measured_alpha = 1.0  # the acceptance-friendly draft accepts all
        roofline = {
            "config": "qwen2:1.5b int8 ctx512, draft=quarter-depth self",
            "draft_cost_ratio_c": round(c, 4),
            "k": spec_k,
            "predicted_speedup_at_measured_alpha": round(
                expected_m(measured_alpha) / (1 + spec_k * c), 3
            ),
            "predicted_speedup_at_alpha_0p7": round(
                expected_m(0.7) / (1 + spec_k * c), 3
            ),
        }
    except Exception:
        pass

    line = {
        "metric": "spec_continuous",
        "unit": "tokens_per_target_step",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "k": spec_k,
        "arms_by_rows": arms,
        "pool_restoration_exact": restoration,
        "paged_native_billing": paged_native,
        "roofline_v5e": roofline,
        "note": (
            "CPU-functional figures measure the MECHANICS (per-row "
            "variable-stride acceptance, parity, pool accounting); the "
            "wall-clock win needs real HBM bandwidth — the roofline "
            "column is what a v5e run should approach"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def spec_sampled_bench() -> int:
    """Sampled speculative decoding (ISSUE 16): measured
    TOKENS-PER-TARGET-STEP at temperature 0.7 across 3 content lengths
    × the three draft sources — model-draft (acceptance-friendly
    aliased draft: q = p, every proposal accepted — the rejection-
    resampling upper bound), n-gram prompt-lookup (real acceptance on
    repetitive content, zero extra weights), and cross-model (another
    lane's resident model as draft). Each retired row contributes
    (decode tokens − 1) / rounds; > 1 means sampled traffic amortizes
    target steps exactly like greedy traffic did pre-ISSUE-16 — the
    population the greedy-only gate previously excluded entirely.

    The FLEET column prices cross-model drafting in the paper's unit of
    account: v5e-modelled J/token of big+small-draft speculation vs
    big-solo plain decode (qwen2:1.5b int8 ctx512 target, quarter-depth
    small draft; decode is HBM-bound so a step's energy is its modelled
    wall × (idle + HBM-active) W). Fleet J/token = solo × (1 + k·c) /
    E[m] — the acceptance criterion is fleet < solo at the measured
    per-round acceptance. Prints ONE JSON line."""
    import dataclasses as _dc
    import os as _os

    import jax
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    on_accelerator = jax.default_backend() in ("tpu", "axon")
    cfg = get_model_config("qwen2:1.5b")
    cfg = _dc.replace(
        cfg.tiny(max_seq_len=1024) if not on_accelerator else cfg,
        name="tiny-spec-target",
    )
    spec_k = int(_os.environ.get("BENCH_SPEC_K", "4"))
    registry = {"tiny-spec-target": cfg, "tiny-spec-draft": cfg}
    dtype = jnp.bfloat16 if on_accelerator else jnp.float32
    temperature = 0.7

    # three content lengths: repetitive prompts of growing history (the
    # n-gram source's acceptance is a function of lookup-able content;
    # the model sources are length-insensitive by construction)
    lengths = {
        "short": "the quick brown fox " * 2,
        "medium": "the quick brown fox jumps over the lazy dog " * 4,
        "long": "the quick brown fox jumps over the lazy dog " * 10,
    }
    sources = {
        "model": ("tiny-spec-draft", spec_k),
        "ngram": ("ngram", spec_k),
        "cross": ("cross:tiny-spec-draft", spec_k),
    }
    rows_per_cell = int(_os.environ.get("BENCH_SPEC_SAMPLED_ROWS", "8"))
    budget = int(_os.environ.get("BENCH_SPEC_SAMPLED_TOKENS", "64"))

    by_source = {}
    measured_alpha = {}
    for source, spec in sources.items():
        eng = JaxEngine(
            registry=dict(registry), dtype=dtype,
            decode_attention="auto" if on_accelerator else None,
            speculative={"tiny-spec-target": spec},
        )
        cells = {}
        acc_tot = drafted_tot = 0
        for label, prompt in lengths.items():
            reqs = [
                GenerationRequest(
                    "tiny-spec-target", prompt, max_new_tokens=budget,
                    temperature=temperature, seed=100 + i,
                    stop_at_eos=False,
                )
                for i in range(rows_per_cell)
            ]
            sess = eng.decode_open(reqs)
            results = []
            while sess.active:
                results.extend(sess.step(16))
            sess.close()
            ratios, acc, drafted = [], 0, 0
            for r in results:
                sx = (r.extras or {}).get("spec") or {}
                if sx.get("rounds"):
                    ratios.append(
                        (r.generated_tokens - 1) / sx["rounds"]
                    )
                    acc += sx.get("accepted", 0)
                    drafted += sx.get("drafted", 0)
            cells[label] = {
                "tokens_per_target_step": (
                    round(sum(ratios) / len(ratios), 3) if ratios else None
                ),
                "acceptance": (
                    round(acc / drafted, 3) if drafted else None
                ),
            }
            acc_tot += acc
            drafted_tot += drafted
        tpts_all = [
            c["tokens_per_target_step"]
            for c in cells.values()
            if c["tokens_per_target_step"]
        ]
        by_source[source] = {
            **cells,
            "mean_tokens_per_target_step": (
                round(sum(tpts_all) / len(tpts_all), 3) if tpts_all else None
            ),
        }
        measured_alpha[source] = (
            acc_tot / drafted_tot if drafted_tot else 0.0
        )

    # v5e-modelled fleet J/token: big + small-draft speculation vs
    # big-solo plain decode, priced at the HBM-bound decode power point
    fleet = None
    try:
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
            modeled_tp_decode_step_s,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
            V5E_HBM_ACTIVE_W,
            V5E_IDLE_W,
        )

        big = get_model_config("qwen2:1.5b")
        small = _dc.replace(big, n_layers=max(1, big.n_layers // 4))
        ctx = 512
        t_big = modeled_tp_decode_step_s(big, "int8", 1, ctx)
        t_small = modeled_tp_decode_step_s(small, "int8", 1, ctx)
        c = t_small / t_big
        watts = V5E_IDLE_W + V5E_HBM_ACTIVE_W
        solo_jpt = t_big * watts

        def expected_m(alpha: float) -> float:
            if alpha >= 1.0:
                return float(spec_k + 1)
            return (1 - alpha ** (spec_k + 1)) / (1 - alpha)

        # the per-round acceptance probability the cross arm measured:
        # accepted/drafted is the mean fraction of k accepted, a
        # conservative stand-in for the geometric alpha
        alpha = measured_alpha["cross"]
        e_m = expected_m(alpha)
        fleet_jpt = solo_jpt * (1 + spec_k * c) / e_m
        fleet = {
            "config": (
                "qwen2:1.5b int8 ctx512 target, quarter-depth small draft"
            ),
            "power_point_W": watts,
            "draft_cost_ratio_c": round(c, 4),
            "k": spec_k,
            "measured_cross_acceptance": round(alpha, 3),
            "expected_tokens_per_round": round(e_m, 3),
            "solo_big_J_per_token": round(solo_jpt, 6),
            "fleet_spec_J_per_token": round(fleet_jpt, 6),
            "fleet_beats_solo": bool(fleet_jpt < solo_jpt),
        }
    except Exception:
        pass

    line = {
        "metric": "spec_sampled",
        "unit": "tokens_per_target_step",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "k": spec_k,
        "temperature": temperature,
        "rows_per_cell": rows_per_cell,
        "budget": budget,
        "by_source": by_source,
        "fleet_energy_v5e": fleet,
        "note": (
            "CPU-functional figures measure the sampled-acceptance "
            "MECHANICS (rejection resampling's per-row stride); the "
            "model/cross arms alias draft and target configs (q = p, "
            "acceptance -> 1 — the amortization ceiling), the ngram "
            "arm shows real prompt-lookup acceptance on repetitive "
            "content; the fleet column is the v5e-modelled J/token "
            "a real-slice run should approach"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def tp_continuous_bench() -> int:
    """Poisson A/B of the continuous scheduler on a 1-device vs a
    forced-host 8-device TP mesh (ISSUE 8): the stepped carry is an
    explicitly-sharded SPMD pytree, so the SAME scheduler loop drives
    both arms — each arm runs in its own interpreter because the
    virtual device count is fixed at process start
    (``--xla_force_host_platform_device_count``).

    The headline figure is the measured 1→8 per-step wall ratio at
    fixed occupancy, recorded NEXT TO the roofline model's predicted
    v5e ratio (parallel/roofline.py — the AOT-validated 2.1–4.8×
    modelled 8-chip speedups this PR makes servable). On the CPU dev
    environment the measured ratio is an SPMD-OVERHEAD figure (8
    virtual devices share one CPU's bandwidth; expect ≤1×) — the bench
    exists so the identical entry run on a real slice fills in the
    hardware column, and so CPU regressions in the sharded step path
    are visible per-slice. Prints ONE JSON line."""
    import os as _os
    import subprocess as _sp

    arms = {}
    for n_dev in (1, 8):
        env = dict(_os.environ)
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu") or "cpu"
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_dev}")
        env["XLA_FLAGS"] = " ".join(flags)
        proc = _sp.run(
            [sys.executable, _os.path.abspath(__file__),
             "_tp_continuous_arm", str(n_dev)],
            capture_output=True, text=True, env=env,
            cwd=_os.path.dirname(_os.path.abspath(__file__)),
            timeout=1800,
        )
        last = (proc.stdout.strip().splitlines() or ["{}"])[-1]
        try:
            arms[n_dev] = json.loads(last)
        except json.JSONDecodeError:
            arms[n_dev] = {
                "error": f"arm {n_dev} emitted no JSON",
                "stdout_tail": proc.stdout[-500:],
                "stderr_tail": proc.stderr[-500:],
            }
        if proc.returncode != 0 and "error" not in arms[n_dev]:
            arms[n_dev]["error"] = f"exit {proc.returncode}"

    def step_s(arm):
        return ((arm.get("controlled") or {}).get("mean_step_s")) or None

    s1, s8 = step_s(arms.get(1, {})), step_s(arms.get(8, {}))
    measured_ratio = round(s1 / s8, 3) if s1 and s8 else None

    # The roofline's prediction for the PAPER's serving config (qwen2:
    # 1.5b int8 weights, v5e sustained bandwidth) at the study's
    # mid-context — the number the measured ratio should approach when
    # this same entry runs on a real 8-chip slice.
    predicted_ratio = None
    try:
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
            get_model_config,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
            modeled_tp_decode_step_s,
        )

        full = get_model_config("qwen2:1.5b")
        ctx = 512
        predicted_ratio = round(
            modeled_tp_decode_step_s(full, "int8", 1, ctx)
            / modeled_tp_decode_step_s(full, "int8", 8, ctx),
            3,
        )
    except Exception:
        pass

    line = {
        "metric": "tp_continuous",
        "unit": "step_time_ratio",
        "arms": {str(k): v for k, v in arms.items()},
        "measured_step_ratio_1_to_8": measured_ratio,
        "roofline_predicted_ratio_1_to_8_v5e": predicted_ratio,
        "note": (
            "measured ratio is forced-host CPU SPMD overhead unless run "
            "on a real slice; predicted ratio is the v5e roofline "
            "(docs/roofline_aot.json validates its structural terms)"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def router_fleet_bench() -> int:
    """Replica-fleet routing A/B (ISSUE 12): aggregate tok/s + TTFT p99
    of 1 vs 2 vs 4 FakeBackend replicas behind the front-door router
    (serve/router.py) on Poisson traces at 1×/2×/4× the SINGLE-replica
    saturating rate, least-queue vs round-robin dispatch arms.

    The fake replica is a calibrated capacity model: with
    ``simulate_delay`` a decode slice of k steps sleeps k/tokens_per_s
    once for ALL live rows (the shared-window semantics of a real
    batched decode), so one replica's ceiling is tokens_per_s ×
    max_rows — the HBM-bound admission cap's stand-in. Overload beyond
    one ceiling can ONLY be served by more replicas, which is exactly
    the router's claim: aggregate tok/s ≥1.8× at 2 replicas (≥3.2× at
    4) on the 2×/4× traces, with fleet TTFT p99 at 1× load no worse
    than the single replica's. Prints ONE JSON line."""
    import os
    import sys as _sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from scripts.poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
        LocalReplica,
        Router,
    )

    TOKENS_PER_S = 400.0  # per-replica decode rate (fake, shared window)
    MAX_ROWS = 8  # per-replica admission ceiling (the HBM stand-in)
    capacity = TOKENS_PER_S * MAX_ROWS  # one replica's tok/s ceiling
    BUDGETS = (48, 96, 160)
    mean_tokens = sum(BUDGETS) / len(BUDGETS)

    def run_arm(n_replicas: int, policy: str, load_x: float, n: int):
        """One (fleet size, policy, load multiple) arm over the SAME
        seeded trace family: mean inter-arrival is scaled so offered
        token demand is load_x × one replica's ceiling."""
        interarrival_s = mean_tokens / (capacity * load_x)
        workload = build_workload(
            n,
            interarrival_s,
            seed=7,
            model="bench:fleet",
            budgets=list(BUDGETS),
            stop_at_eos=False,
        )
        replicas = [
            LocalReplica(
                f"r{i}",
                FakeBackend(
                    tokens_per_s=TOKENS_PER_S,
                    simulate_delay=True,
                    max_rows=MAX_ROWS,
                ),
            )
            for i in range(n_replicas)
        ]
        router = Router(replicas, policy=policy, probe_interval_s=0.25)
        router.start()
        try:
            records = run_load(router.dispatch, workload)
        finally:
            router.stop()
        summary = summarize(records)
        return {
            "replicas": n_replicas,
            "policy": policy,
            "load_x": load_x,
            "requests": n,
            "agg_tokens_per_s": summary.get("agg_tokens_per_s"),
            "ttft_p50_s": summary.get("ttft_p50_s"),
            "ttft_p99_s": summary.get("ttft_p99_s"),
            "completion_p95_s": summary.get("completion_p95_s"),
            "errors": summary.get("errors"),
            "per_replica": summary.get("replicas"),
        }

    arms = {
        # TTFT reference at 1×: the fleet's front door must not tax the
        # un-overloaded case
        "single_1x": run_arm(1, "least-queue", 1.0, 64),
        "fleet2_1x_least_queue": run_arm(2, "least-queue", 1.0, 64),
        # the single replica is saturated 2×/4× over; only more
        # replicas can serve the offered load
        "single_2x": run_arm(1, "least-queue", 2.0, 128),
        "fleet2_2x_least_queue": run_arm(2, "least-queue", 2.0, 128),
        "fleet2_2x_round_robin": run_arm(2, "round-robin", 2.0, 128),
        "single_4x": run_arm(1, "least-queue", 4.0, 192),
        "fleet4_4x_least_queue": run_arm(4, "least-queue", 4.0, 192),
        "fleet4_4x_round_robin": run_arm(4, "round-robin", 4.0, 192),
    }

    def ratio(a, b):
        va, vb = arms[a]["agg_tokens_per_s"], arms[b]["agg_tokens_per_s"]
        return round(va / vb, 3) if va and vb else None

    line = {
        "metric": "router_fleet",
        "unit": "agg_tokens_per_s",
        "replica_model": {
            "tokens_per_s": TOKENS_PER_S,
            "max_rows": MAX_ROWS,
            "ceiling_tokens_per_s": capacity,
        },
        "arms": arms,
        "speedup_2_replicas_at_2x": ratio(
            "fleet2_2x_least_queue", "single_2x"
        ),
        "speedup_4_replicas_at_4x": ratio(
            "fleet4_4x_least_queue", "single_4x"
        ),
        "least_queue_vs_round_robin_2x": ratio(
            "fleet2_2x_least_queue", "fleet2_2x_round_robin"
        ),
        "ttft_p99_fleet_vs_single_at_1x": (
            round(
                arms["fleet2_1x_least_queue"]["ttft_p99_s"]
                / arms["single_1x"]["ttft_p99_s"],
                3,
            )
            if arms["single_1x"].get("ttft_p99_s")
            and arms["fleet2_1x_least_queue"].get("ttft_p99_s")
            else None
        ),
        "note": (
            "fake replicas are calibrated capacity models "
            "(tokens_per_s x max_rows ceiling); the figures measure the "
            "ROUTER's scaling/dispatch quality, not engine speed — on "
            "real engines each replica is one mesh/host (serve-fleet "
            "--targets)"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    _sys.stdout.flush()
    return 0


def affinity_routing_bench() -> int:
    """Prefix-affinity fleet routing A/B (ISSUE 19): the SAME seeded
    75%-shared-prefix Poisson trace (two distinct 192-token system
    prompts, ``scripts/poisson_load.py --shared-prefix-frac 0.75
    --prefix-pool 2``) served by a 2-replica prefix-sharing fake fleet
    under ``--route-policy affinity`` vs ``least-queue``.

    Each fake replica owns a budget-capped cross-session prefix store
    (32 KiB HBM ≈ TWO recent entries, zero host tier), so the fleet
    keeps store locality ONLY if the router keeps sending a family to
    the replica whose store is warm on it. Affinity does exactly that —
    the probes carry bounded radix digests and the probe-side estimator
    scores the request's chunk hashes against them — while least-queue
    interleaves both families across both replicas and thrashes the
    stores. Two figures ride the headline: fleet TTFT p99 (a store hit
    prefills only the divergent tail, so the chunked join's wall
    shrinks) and PREFILL COMPUTED TOKENS (total prompt tokens minus the
    llm_prefix_hit_tokens_total delta — the recompute the paper's
    J/request story bills). Decode token parity between the arms is
    asserted structurally: the seeded trace replays exactly, budgets
    are fixed, so both arms must stream the same token totals. Prints
    ONE JSON line."""
    import os
    import sys as _sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from scripts.poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.prefix import (
        PREFIX_HIT_TOKENS_C,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
        _AFFINITY_C,
        LocalReplica,
        Router,
    )

    TOKENS_PER_S = 400.0  # per-replica decode rate (fake, shared window)
    MAX_ROWS = 8  # per-replica admission ceiling
    SHARE = 0.75  # the ISSUE's acceptance point
    PREFIX_POOL = 2  # two families over two replicas: affinity can split
    PREFIX_TOKENS = 192
    N = 96
    BUDGETS = (24, 48, 96)
    mean_tokens = sum(BUDGETS) / len(BUDGETS)
    capacity = TOKENS_PER_S * MAX_ROWS
    # offered decode demand ~0.8× ONE replica's ceiling → the 2-fleet
    # runs ~40% utilised: TTFT is join-prefill-dominated (the channel
    # affinity improves), not queue-saturation noise
    interarrival_s = mean_tokens / (capacity * 0.8)

    def fam_total(fam) -> float:
        return sum(c.value for c in fam._children.values())

    def run_arm(policy: str):
        workload = build_workload(
            N,
            interarrival_s,
            seed=19,
            model="bench:affinity",
            budgets=list(BUDGETS),
            stop_at_eos=False,
            shared_prefix_frac=SHARE,
            prefix_pool=PREFIX_POOL,
            shared_prefix_tokens=PREFIX_TOKENS,
        )
        prompt_tokens = sum(
            len(r.prompt.encode("utf-8")) + 1 for _, r in workload
        )
        replicas = [
            LocalReplica(
                f"r{i}",
                FakeBackend(
                    tokens_per_s=TOKENS_PER_S,
                    simulate_delay=True,
                    max_rows=MAX_ROWS,
                    prefix_share=True,
                    prefix_store_hbm_bytes=32 * 1024,
                    prefix_store_host_bytes=0,
                ),
            )
            for i in range(2)
        ]
        hit0 = fam_total(PREFIX_HIT_TOKENS_C)
        aff0 = fam_total(_AFFINITY_C)
        router = Router(replicas, policy=policy, probe_interval_s=0.25)
        router.start()
        try:
            records = run_load(router.dispatch, workload)
        finally:
            router.stop()
        hit_tokens = int(fam_total(PREFIX_HIT_TOKENS_C) - hit0)
        summary = summarize(records)
        return {
            "policy": policy,
            "requests": N,
            "shared_prefix_frac": SHARE,
            "agg_tokens_per_s": summary.get("agg_tokens_per_s"),
            "ttft_p50_s": summary.get("ttft_p50_s"),
            "ttft_p99_s": summary.get("ttft_p99_s"),
            "completion_p95_s": summary.get("completion_p95_s"),
            "errors": summary.get("errors"),
            "decode_tokens": sum(r.get("tokens") or 0 for r in records),
            "prompt_tokens": prompt_tokens,
            "prefix_hit_tokens": hit_tokens,
            "prefill_computed_tokens": prompt_tokens - hit_tokens,
            "affinity_hits": fam_total(_AFFINITY_C) - aff0,
            "per_replica": summary.get("replicas"),
        }

    arms = {
        "least_queue": run_arm("least-queue"),
        "affinity": run_arm("affinity"),
    }

    def ratio(key):
        va, vb = arms["affinity"].get(key), arms["least_queue"].get(key)
        return round(va / vb, 3) if va and vb else None

    line = {
        "metric": "affinity_routing",
        "unit": "ttft_p99_s",
        "replica_model": {
            "tokens_per_s": TOKENS_PER_S,
            "max_rows": MAX_ROWS,
            "prefix_store_hbm_bytes": 32 * 1024,
        },
        "arms": arms,
        "token_parity": (
            arms["affinity"]["decode_tokens"]
            == arms["least_queue"]["decode_tokens"]
            and not arms["affinity"]["errors"]
            and not arms["least_queue"]["errors"]
        ),
        "ttft_p99_affinity_vs_least_queue": ratio("ttft_p99_s"),
        "prefill_computed_affinity_vs_least_queue": ratio(
            "prefill_computed_tokens"
        ),
        "note": (
            "fake replicas are calibrated capacity models with "
            "budget-capped prefix stores; the figures measure the "
            "ROUTER's locality preservation (digest federation + "
            "probe-side estimation), not engine speed — on real engines "
            "each replica is one mesh/host behind serve-fleet "
            "--route-policy affinity"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    _sys.stdout.flush()
    return 0


def _tp_dp_continuous_arm(dp: int, tp: int) -> int:
    """ONE mesh-shape arm of the tp_dp_continuous A/B, in its own
    process (the parent pins ``xla_force_host_platform_device_count``
    to dp×tp). Builds a dp×tp mesh and, for EVERY cache layout
    (contiguous/paged × bf16/int8kv), runs the controlled
    fixed-occupancy slice-timing phase + bit-exact token parity vs the
    same engine's solo path. Prints ONE JSON line."""
    import os as _os
    import statistics as _stats

    import jax
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    n_dev = dp * tp
    if len(jax.devices()) < n_dev:
        print(json.dumps({"error": f"need {n_dev} devices, have {len(jax.devices())}"}))
        return 1
    cfg = dataclasses.replace(
        get_model_config("qwen2:1.5b").tiny(),
        n_heads=8, n_kv_heads=8, d_ff=128, d_model=64, d_head=16,
        max_seq_len=1024,
    )
    spec = MeshSpec.dp_tp(dp, tp) if dp > 1 else MeshSpec.tp_only(tp)
    mesh = build_mesh(spec, devices=jax.devices()[:n_dev])
    slice_steps = 8
    rows = int(_os.environ.get("BENCH_TPDP_ROWS", "8"))  # divides dp≤4
    budget = 48
    layouts = {}
    for name, paged, kv in (
        ("contiguous-bf16", False, None),
        ("contiguous-int8kv", False, "int8"),
        ("paged-bf16", True, None),
        ("paged-int8kv", True, "int8"),
    ):
        engine = TensorParallelEngine(
            mesh=mesh,
            registry={cfg.name: cfg},
            dtype=jnp.float32,
            paged_kv=paged,
            kv_quantize=kv,
        )
        fleet = [
            GenerationRequest(
                cfg.name, f"dp row {i} holds its slot",
                max_new_tokens=budget, stop_at_eos=False, seed=200 + i,
            )
            for i in range(rows)
        ]
        solo = [engine.generate(r) for r in fleet]  # warms every shape
        sess = engine.decode_open(
            fleet, reserve_rows=rows, slice_steps=slice_steps
        )
        dp_shards = sess.dp_shards
        sess.step(slice_steps)  # first slice pays any residual compile
        slice_walls, results = [], []
        while sess.active:
            full = sess.active == rows
            t0 = time.monotonic()
            retired = sess.step(slice_steps)
            if full and sess.active == rows:
                slice_walls.append(time.monotonic() - t0)
            results.extend(retired)
        parity = all(
            got.tokens == ref.tokens
            for ref, got in zip(
                solo,
                sorted(results, key=lambda r: fleet.index(r.request)),
            )
        )
        sess.close()
        mean_slice = _stats.mean(slice_walls) if slice_walls else None
        layouts[name] = {
            "dp_shards": dp_shards,
            "parity_vs_solo": parity,
            "full_occupancy_slices": len(slice_walls),
            "mean_step_s": (
                round(mean_slice / slice_steps, 6) if mean_slice else None
            ),
        }
    line = {
        "arm": "tp_dp_continuous",
        "dp": dp,
        "tp": tp,
        "devices": n_dev,
        "backend": jax.default_backend(),
        "model": cfg.name,
        "rows": rows,
        "slice_steps": slice_steps,
        "layouts": layouts,
    }
    print(json.dumps(line))
    return 0


def tp_dp_continuous_bench() -> int:
    """tp×dp in-mesh row sharding A/B (ISSUE 19): the stepped-decode
    controlled phase on forced-host 1×1 vs 2×2 vs 1×4 (tp×dp) meshes,
    one subprocess per mesh shape (a device count is process-lifetime),
    ALL FOUR cache layouts per arm with bit-exact token parity vs solo.

    The dp axis shards the ROW dimension of every batch-position carry
    leaf (and the page pool's page dim) under the same divisibility
    fallback as the heads rule, so the SAME scheduler loop serves a
    data-parallel×tensor-parallel mesh with no collective on the row
    axis. On the CPU dev environment the step ratios are SPMD-overhead
    figures (virtual devices share one CPU — expect ≤1×); the bench
    exists so the identical entry run on a real slice fills in the
    hardware column and so parity/dp-engagement regressions are visible
    per-layout in CI-adjacent runs. Prints ONE JSON line."""
    import os as _os
    import subprocess as _sp

    shapes = ((1, 1), (2, 2), (4, 1))  # (dp, tp): 1×1, 2×2 tp×dp, 1×4
    arms = {}
    for dp, tp in shapes:
        n_dev = dp * tp
        env = dict(_os.environ)
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu") or "cpu"
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_dev}")
        env["XLA_FLAGS"] = " ".join(flags)
        proc = _sp.run(
            [sys.executable, _os.path.abspath(__file__),
             "_tp_dp_continuous_arm", str(dp), str(tp)],
            capture_output=True, text=True, env=env,
            cwd=_os.path.dirname(_os.path.abspath(__file__)),
            timeout=1800,
        )
        key = f"tp{tp}_dp{dp}"
        last = (proc.stdout.strip().splitlines() or ["{}"])[-1]
        try:
            arms[key] = json.loads(last)
        except json.JSONDecodeError:
            arms[key] = {
                "error": f"arm {key} emitted no JSON",
                "stdout_tail": proc.stdout[-500:],
                "stderr_tail": proc.stderr[-500:],
            }
        if proc.returncode != 0 and "error" not in arms[key]:
            arms[key]["error"] = f"exit {proc.returncode}"

    def step_s(key, layout="paged-bf16"):
        return ((arms.get(key, {}).get("layouts") or {}).get(layout) or {}).get(
            "mean_step_s"
        )

    base = step_s("tp1_dp1")
    ratios = {
        key: (
            round(base / step_s(key), 3)
            if base and step_s(key)
            else None
        )
        for key in ("tp2_dp2", "tp1_dp4")
    }
    parity_all = all(
        lay.get("parity_vs_solo") is True
        for arm in arms.values()
        for lay in (arm.get("layouts") or {}).values()
    ) and all("error" not in arm for arm in arms.values())
    dp_engaged = all(
        lay.get("dp_shards") == arm.get("dp")
        for key, arm in arms.items()
        if arm.get("dp", 1) > 1
        for lay in (arm.get("layouts") or {}).values()
    )
    line = {
        "metric": "tp_dp_continuous",
        "unit": "step_time_ratio",
        "arms": arms,
        "measured_step_ratio_1x1_to_2x2": ratios.get("tp2_dp2"),
        "measured_step_ratio_1x1_to_1x4": ratios.get("tp1_dp4"),
        "token_parity_all_layouts_all_meshes": parity_all,
        "dp_engaged_all_layouts": dp_engaged,
        "note": (
            "measured ratios are forced-host CPU SPMD overhead unless "
            "run on a real slice; dp shards the row dim (no collective "
            "on it), so on hardware the dp axis scales throughput at "
            "~flat step time while tp divides the per-step FLOPs"
        ),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def slo_overhead_bench() -> int:
    """Overhead micro-arm for ISSUE 17's windowed telemetry: the SAME
    tiny-CPU stepped-decode workload (real JaxEngine, continuous
    scheduler, seeded Poisson arrivals) run three ways —

    - ``telemetry``: obs on, no ring/SLO (the pre-ISSUE baseline);
    - ``slo``: obs on + a TimeSeriesRing sampler at 10 Hz (10x the
      shipped 1 s cadence — a deliberate worst case) + an SLOEngine
      evaluating two objectives every tick;
    - ``off``: kill switch on WITH the ring/SLO still configured — the
      sampler must refuse to start, restoring full parity.

    Budget: the ``slo`` arm's aggregate tokens/s within 2% of the
    ``telemetry`` arm's (recorded in docs/PERF.md). Each arm runs twice
    and keeps its best window (BATCH_STAT), like the decode bench.
    Prints ONE JSON line."""
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "scripts")
    )
    import jax
    import jax.numpy as jnp
    from poisson_load import build_workload, run_load, summarize

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu import obs
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import slo as obs_slo
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
        timeseries as obs_ts,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    cfg = get_model_config("qwen2:1.5b").tiny()
    engine = JaxEngine(registry={cfg.name: cfg}, dtype=jnp.float32)

    n = int(_os.environ.get("BENCH_SLO_REQUESTS", "16"))
    mean_ms = float(_os.environ.get("BENCH_SLO_INTERARRIVAL_MS", "30"))
    workload = build_workload(
        n, mean_ms / 1e3, seed=11, model=cfg.name, budgets=(8, 16, 32),
        prompts=("alpha beta", "gamma delta epsilon", "zeta eta"),
        stop_at_eos=False,  # fixed lengths: every arm does equal work
    )

    was_enabled = obs.enabled()
    interval_s = 0.1  # 10x the shipped cadence: overhead upper bound
    spec = "ttft_p99_ms<=250,completion_p95_s<=4"

    def run_arm(enable: bool, with_slo: bool) -> dict:
        (obs.enable if enable else obs.disable)()
        sampler = None
        if with_slo:
            ring = obs_ts.TimeSeriesRing(interval_s=interval_s)
            slo_engine = obs_slo.SLOEngine(
                obs_slo.parse_slo_spec(spec), ring, name="bench"
            )

            def _tick():
                ring.sample_once()
                slo_engine.evaluate()

            sampler = obs_ts.SamplerThread(
                _tick, interval_s=interval_s, name="bench-ts-sampler"
            )
            started = sampler.start()
            assert started is enable  # kill switch: never starts when off
        sched = ContinuousScheduler(engine)
        sched.start()
        try:
            records = run_load(sched.submit, workload)
        finally:
            sched.stop()
            if sampler is not None:
                sampler.stop()
        return summarize(records)

    arms = {}
    try:
        # warm-up: one full throwaway pass through the measured path so
        # every XLA shape (prefill buckets, stepped decode, admission
        # resizes) compiles BEFORE any arm is timed — arm order must
        # not decide the comparison
        run_arm(True, False)
        for name, enable, with_slo in (
            ("telemetry", True, False),
            ("slo", True, True),
            ("off", False, True),
        ):
            runs = [run_arm(enable, with_slo) for _ in range(BATCH_TIMED_RUNS)]
            arms[name] = max(
                runs, key=lambda s: s.get("agg_tokens_per_s") or 0.0
            )
    finally:
        (obs.enable if was_enabled else obs.disable)()

    def tps(name):
        return arms[name].get("agg_tokens_per_s") or 0.0

    overhead_pct = (
        round((tps("telemetry") - tps("slo")) / tps("telemetry") * 100.0, 2)
        if tps("telemetry")
        else None
    )
    line = {
        "metric": "slo_overhead",
        "unit": "tokens_per_s",
        "model": cfg.name,
        "backend": jax.default_backend(),
        "requests": n,
        "mean_interarrival_ms": mean_ms,
        "sampler_interval_s": interval_s,
        "slo_spec": spec,
        "timed_runs": BATCH_TIMED_RUNS,
        "stat": BATCH_STAT,
        "arms": arms,
        "slo_overhead_pct": overhead_pct,
        "overhead_budget_pct": 2.0,
        "kill_switch_tokens_per_s": tps("off"),
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def pd_disagg_bench() -> int:
    """Disaggregated prefill/decode A/B (ISSUE 18): in-flight
    inter-slice gap p99 + TTFT p99 of a 1-prefill + 1-decode role fleet
    vs 2 MIXED chunked replicas at matched hardware, on one seeded
    heavy-tailed lognormal trace (scripts/poisson_load.py).

    The mechanism under test: on a mixed replica every newcomer's
    chunked prefill runs inside the shared decode loop, so a
    heavy-tailed long prompt STALLS every in-flight stream for its
    chunk walls (the fake sleeps chunk/(tokens_per_s·8) per join_step —
    the same interference a real chunked-prefill slice has). The disagg
    fleet takes prefill on the prefill replica, ships the primed row
    (swap-policy bundle, zero re-prefill at seat) and decodes on the
    decode replica — in-flight streams never share a loop with prefill,
    which is THE inter-slice-gap tail claim of prefill/decode
    disaggregation. TTFT is client-observed at the decode side's first
    relayed chunk, so the transfer toll is IN the reported figure.

    Also records: a drain-latency column (evacuating a mid-stream row
    via live migration vs waiting the row out) and bit-exact token
    parity of a migrated row on all four real-engine cache layouts
    (contig/paged × bf16/int8-KV), with exact page free-count
    restoration on both pools. Prints ONE JSON line."""
    import os
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from scripts.poisson_load import build_workload, percentile

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
        LocalReplica,
        Router,
    )

    TOKENS_PER_S = 400.0  # per-replica decode rate (fake, shared window)
    MAX_ROWS = 8  # per-replica admission ceiling (the HBM stand-in)
    BUDGETS = (48, 96, 160)
    N = 64
    MEAN_INTERARRIVAL_S = 0.08
    # heavy tail: median 64 prompt tokens, sigma 1.5 → the p99 draw
    # saturates the 2048 clamp; on a mixed replica each such prompt
    # stalls the shared decode loop ~chunk/(tokens_per_s·8) s per
    # 256-token chunk wall — 8 walls of ~80 ms for a clamped draw
    LOGNORM = dict(
        prompt_len_dist="lognormal",
        prompt_len_median=64.0,
        prompt_len_sigma=1.5,
        prompt_len_max=2048,
    )

    def trace():
        return build_workload(
            N,
            MEAN_INTERARRIVAL_S,
            seed=18,
            model="bench:pd",
            budgets=list(BUDGETS),
            stop_at_eos=False,
            **LOGNORM,
        )

    def fresh_backend():
        return FakeBackend(
            tokens_per_s=TOKENS_PER_S,
            simulate_delay=True,
            max_rows=MAX_ROWS,
        )

    def run_stream_load(router, workload):
        """Per-request client threads streaming through the router's
        front door, recording EVERY chunk arrival — TTFT at first
        chunk, inter-slice gaps between consecutive chunk walls while
        the row is in flight (run_load only keeps server-side TTFT;
        the gap tail is this bench's whole point)."""
        records = [None] * len(workload)
        start = time.monotonic()

        def client(i, offset, request):
            delay = start + offset - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_submit = time.monotonic()
            rec = {"gaps": [], "tokens": 0}
            prev = None
            final = None
            try:
                for ch in router.dispatch_stream(request):
                    now = time.monotonic()
                    if ch.done:
                        final = ch.result
                        break
                    if not ch.tokens:
                        continue
                    if prev is None:
                        rec["ttft_s"] = now - t_submit
                    else:
                        rec["gaps"].append(now - prev)
                    prev = now
                    rec["tokens"] += len(ch.tokens)
            except BaseException as exc:  # noqa: BLE001
                rec["error"] = f"{type(exc).__name__}: {exc}"
            rec["completion_s"] = time.monotonic() - t_submit
            if final is not None and final.extras:
                sched = final.extras.get("sched") or {}
                route = final.extras.get("router") or {}
                if sched.get("migrated"):
                    rec["migrated"] = True
                if route.get("role"):
                    rec["role"] = route["role"]
            records[i] = rec

        threads = [
            threading.Thread(target=client, args=(i, off, req), daemon=True)
            for i, (off, req) in enumerate(workload)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [r for r in records if r is not None]

    def arm_summary(records):
        ok = [r for r in records if "error" not in r]
        gaps = [g for r in ok for g in r["gaps"]]
        ttfts = [r["ttft_s"] for r in ok if r.get("ttft_s") is not None]
        comps = [r["completion_s"] for r in ok]
        out = {
            "requests": len(records),
            "errors": len(records) - len(ok),
            "tokens": sum(r["tokens"] for r in ok),
            "migrated": sum(1 for r in ok if r.get("migrated")),
            "gap_samples": len(gaps),
            "gap_p50_ms": round(percentile(gaps, 50) * 1e3, 2),
            "gap_p95_ms": round(percentile(gaps, 95) * 1e3, 2),
            "gap_p99_ms": round(percentile(gaps, 99) * 1e3, 2),
            "completion_p95_s": round(percentile(comps, 95), 4),
        }
        if ttfts:
            out["ttft_p50_s"] = round(percentile(ttfts, 50), 4)
            out["ttft_p99_s"] = round(percentile(ttfts, 99), 4)
        roles = sorted({r["role"] for r in ok if r.get("role")})
        if len(roles) > 1 or (roles and roles != ["mixed"]):
            out["by_role"] = {
                name: sum(1 for r in ok if r.get("role") == name)
                for name in roles
            }
        return out

    def run_arm(replicas):
        router = Router(replicas, probe_interval_s=0.25)
        router.start()
        try:
            records = run_stream_load(router, trace())
        finally:
            router.stop()
        return arm_summary(records)

    arms = {
        "disagg_1p1d": run_arm(
            [
                LocalReplica("p", fresh_backend(), role="prefill"),
                LocalReplica("d", fresh_backend(), role="decode"),
            ]
        ),
        "mixed2": run_arm(
            [
                LocalReplica("m1", fresh_backend()),
                LocalReplica("m2", fresh_backend()),
            ]
        ),
    }

    # -- drain-latency column: evacuate a mid-stream row (live
    # migration to the survivor) vs wait it out ---------------------------
    def drain_arm(migrate: bool):
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (  # noqa: E501
            GenerationRequest,
        )

        router = Router(
            [
                LocalReplica("v", fresh_backend()),
                LocalReplica("s", fresh_backend()),
            ],
            probe_interval_s=0.25,
        )
        router.start()
        req = GenerationRequest(
            "bench:pd", "drain latency probe", max_new_tokens=600,
            stop_at_eos=False,
        )
        toks = []
        err = [None]

        def consume():
            try:
                for ch in router.dispatch_stream(req):
                    if not ch.done:
                        toks.extend(ch.tokens)
            except BaseException as exc:  # noqa: BLE001
                err[0] = exc

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 10.0
            while len(toks) < 10 and time.monotonic() < deadline:
                time.sleep(0.005)
            victim = next(
                r.name for r in router.replicas() if r.outstanding > 0
            )
            t0 = time.monotonic()
            drained = router.drain(victim, timeout_s=30.0, migrate=migrate)
            drain_s = time.monotonic() - t0
            t.join(timeout=30.0)
            return {
                "drained": bool(drained),
                "drain_s": round(drain_s, 4),
                "tokens_delivered": len(toks),
                "complete": len(toks) == 600 and err[0] is None,
            }
        finally:
            router.stop()

    drain = {
        "evacuate_migrate": drain_arm(True),
        "wait_out": drain_arm(False),
    }
    ev, wo = drain["evacuate_migrate"]["drain_s"], drain["wait_out"]["drain_s"]
    drain["evacuation_speedup"] = round(wo / ev, 2) if ev else None

    # -- bit-exact migrated-row parity on all four real cache layouts ------
    def parity_all_layouts():
        import jax.numpy as jnp

        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (  # noqa: E501
            GenerationRequest,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (  # noqa: E501
            JaxEngine,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (  # noqa: E501
            get_model_config,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.migrate import (  # noqa: E501
            export_bundle,
            import_bundle,
        )

        registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
        layouts = {
            "contig-bf16": (False, None),
            "contig-int8": (False, "int8"),
            "paged-bf16": (True, None),
            "paged-int8": (True, "int8"),
        }
        out = {}
        for name, (paged, kvq) in layouts.items():
            src = JaxEngine(
                registry=dict(registry), dtype=jnp.float32,
                paged_kv=paged, kv_quantize=kvq,
            )
            dst = JaxEngine(
                registry=dict(registry), dtype=jnp.float32,
                paged_kv=paged, kv_quantize=kvq,
            )
            anchor_s = GenerationRequest(
                "tiny", "source anchor", max_new_tokens=16,
                stop_at_eos=False,
            )
            anchor_d = GenerationRequest(
                "tiny", "destination anchor", max_new_tokens=16,
                stop_at_eos=False,
            )
            victim = GenerationRequest(
                "tiny", "the migrating row", max_new_tokens=16,
                stop_at_eos=False, seed=13,
            )
            solo = src.generate(victim).tokens
            s_sess = src.decode_open([anchor_s, victim], reserve_rows=4)
            d_sess = dst.decode_open([anchor_d], reserve_rows=4)
            s_idle = s_sess.pool.n_pages - 1 if paged else None
            d_idle = d_sess.pool.n_pages - 1 if paged else None
            s_sess.step(4)
            free_s = s_sess.pool.free_pages if paged else None
            pr = s_sess.preempt(victim, policy="swap")
            bundle = json.loads(
                json.dumps(export_bundle(pr, reason="disagg", streamed=0))
            )
            s_sess.resume_discard(pr)
            src_freed = (
                s_sess.pool.free_pages == free_s + pr.n_own_pages
                if paged
                else None
            )
            pr2 = import_bundle(bundle)
            pend = d_sess.resume_begin(pr2, 64)
            while not d_sess.join_step(pend):
                pass
            d_sess.join_commit(pend)
            results = {}
            for sess in (s_sess, d_sess):
                while sess.active:
                    for res in sess.step(8):
                        results[res.request.prompt] = res
            tokens_equal = results[victim.prompt].tokens == solo
            s_sess.close()
            d_sess.close()
            out[name] = {
                "tokens_equal": bool(tokens_equal),
                "src_pages_freed_exact": src_freed,
                "pools_restored_idle": (
                    (
                        s_sess.pool.free_pages == s_idle
                        and d_sess.pool.free_pages == d_idle
                    )
                    if paged
                    else None
                ),
            }
        return out

    parity = parity_all_layouts()

    d_gap = arms["disagg_1p1d"]["gap_p99_ms"]
    m_gap = arms["mixed2"]["gap_p99_ms"]
    line = {
        "metric": "pd_disagg_interslice_gap_p99_ms",
        "value": d_gap,
        "unit": "ms",
        # >1 = the disagg fleet's in-flight gap tail beats the mixed
        # fleet's at matched hardware (the acceptance bar)
        "vs_baseline": round(m_gap / d_gap, 3) if d_gap else None,
        "replica_model": {
            "tokens_per_s": TOKENS_PER_S,
            "max_rows": MAX_ROWS,
            "replicas_per_arm": 2,
        },
        "workload": {
            "n": N,
            "mean_interarrival_s": MEAN_INTERARRIVAL_S,
            "budgets": list(BUDGETS),
            **LOGNORM,
        },
        "arms": arms,
        "ttft_p99_disagg_vs_mixed": (
            round(
                arms["disagg_1p1d"]["ttft_p99_s"]
                / arms["mixed2"]["ttft_p99_s"],
                3,
            )
            if arms["mixed2"].get("ttft_p99_s")
            else None
        ),
        "drain": drain,
        "parity": parity,
    }
    _attach_obs(line)
    print(json.dumps(line))
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "continuous_batching":
        return continuous_batching_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "router_fleet":
        return router_fleet_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "tp_continuous":
        return tp_continuous_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "_tp_continuous_arm":
        return _tp_continuous_arm(int(sys.argv[2]))
    if len(sys.argv) > 1 and sys.argv[1] == "affinity_routing":
        return affinity_routing_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "tp_dp_continuous":
        return tp_dp_continuous_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "_tp_dp_continuous_arm":
        return _tp_dp_continuous_arm(int(sys.argv[2]), int(sys.argv[3]))
    if len(sys.argv) > 1 and sys.argv[1] == "chunked_join":
        return chunked_join_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "streaming_cancellation":
        return streaming_cancellation_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "shared_prefix":
        return shared_prefix_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "radix_prefix":
        return radix_prefix_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "model_fleet":
        return model_fleet_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "preemption_overload":
        return preemption_overload_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "spec_continuous":
        return spec_continuous_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "spec_sampled":
        return spec_sampled_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "slo_overhead":
        return slo_overhead_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "pd_disagg":
        return pd_disagg_bench()
    if len(sys.argv) > 1 and sys.argv[1] == "tenant_attribution":
        return tenant_attribution_bench()
    import jax

    backend = jax.default_backend()
    on_accelerator = backend in ("tpu", "axon")

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    cfg = get_model_config("qwen2:1.5b")
    if not on_accelerator:
        cfg = dataclasses.replace(cfg, n_layers=2)  # keep the CPU fallback quick

    quantize = "int8" if on_accelerator else None
    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.bfloat16 if on_accelerator else jnp.float32,
        decode_attention="auto" if on_accelerator else None,
        quantize=quantize,
    )

    prompt = "In 1000 words, please give me information about the solar system"
    warm = GenerationRequest(cfg.name, prompt, max_new_tokens=16)
    t0 = time.monotonic()
    engine.generate(warm)  # compile prefill + a decode bucket
    warm_s = time.monotonic() - t0

    request = GenerationRequest(cfg.name, prompt, max_new_tokens=256)
    result = engine.generate(request)  # compiles the 256 bucket
    result = engine.generate(
        dataclasses.replace(request, seed=1)
    )  # timed, warm

    tokens_per_s = result.generated_tokens / result.decode_s

    # Secondary figure: batched decode throughput (the serving story —
    # decode is bandwidth-bound, so rows share the weight stream; the
    # 128 rows balances the headline against bench wall time; override
    # with BENCH_BATCH_ROWS. Both batch engines are measured — the
    # contiguous cache AND the paged pool (round 5: with the
    # gather+fused-XLA parts and carry-resident side caches, the paged
    # engine WINS at wide batch — its side cache holds only generated
    # columns while the contiguous cache re-reads the full prompt+gen
    # shape every step; docs/PERF.md) — and the headline figure is the
    # better of the two, with both recorded. Accelerator only — the CPU
    # fallback stays quick by design.
    import os as _os

    batch_rows = int(_os.environ.get("BENCH_BATCH_ROWS", "128"))
    batch_tokens_per_s = None
    batch_by_engine = {}
    batch_windows = {}  # engine → the best run's (tokens, window_s)
    if on_accelerator:
        batch_reqs = [
            dataclasses.replace(request, seed=10 + i)
            for i in range(batch_rows)
        ]

        def measure_batch(name, eng):
            eng.generate_batch(batch_reqs)  # compile the batched loop
            # best of BATCH_TIMED_RUNS warm runs: a single timed window
            # through the relay can land 30% low (docs/PERF.md
            # session-noise analysis)
            best = 0.0
            for _ in range(BATCH_TIMED_RUNS):
                batch_results = eng.generate_batch(batch_reqs)
                batch_tokens = sum(
                    r.generated_tokens for r in batch_results
                )
                # Rows in one decode loop share one window (decode_s is
                # the batch wall-clock); a fleet past the memory-bounded
                # width runs as SEQUENTIAL sub-batches with their own
                # windows — sum the DISTINCT windows (identified by the
                # engine's explicit decode_window id, not by float
                # equality of decode_s) so the figure stays tokens over
                # real decode wall either way.
                windows = {}
                for r in batch_results:
                    key = (r.extras or {}).get(
                        "decode_window", r.decode_s
                    )
                    windows[key] = r.decode_s
                batch_decode_s = sum(windows.values())
                if batch_decode_s > 0 and batch_tokens / batch_decode_s > best:
                    best = batch_tokens / batch_decode_s
                    batch_windows[name] = (batch_tokens, batch_decode_s)
            batch_by_engine[name] = round(best, 2)

        measure_batch("contiguous", engine)
        # Free the contiguous engine's weights/caches BEFORE the paged
        # engine loads: two resident engines measured the paged loop at
        # ~half its solo throughput (HBM pressure), which would corrupt
        # the comparison.
        del engine
        paged_engine = JaxEngine(
            registry={cfg.name: cfg},
            dtype=jnp.bfloat16,
            decode_attention="auto",
            quantize=quantize,
            paged_kv=True,
        )
        measure_batch("paged_kv", paged_engine)
        del paged_engine
        # The composed capacity mode (PR 1: int8 pages + budget-aware
        # admission): the BENCH trajectory tracks it from day one so a
        # step-speed or admission regression in the composition is
        # visible next to the modes it composes.
        paged_int8_engine = JaxEngine(
            registry={cfg.name: cfg},
            dtype=jnp.bfloat16,
            decode_attention="auto",
            quantize=quantize,
            paged_kv=True,
            kv_quantize="int8",
        )
        measure_batch("paged_int8", paged_int8_engine)
        del paged_int8_engine
        batch_tokens_per_s = max(batch_by_engine.values())

    # The study's energy model applied to this very run (per-engine
    # MXU/HBM/VPU power states, docs/PERF.md + profilers/tpu.py): the
    # bench line carries the modelled J/token and utilisation so the
    # recorded perf artifact and the energy story stay joined.
    energy_extra = {}
    try:
        import types as _types

        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
            generation_stats_from,
        )
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
            TpuEnergyModelProfiler,
        )

        stats = generation_stats_from(cfg, result, quantize=quantize)
        ctx = _types.SimpleNamespace(scratch={"generation_stats": stats})
        cols = TpuEnergyModelProfiler().collect(ctx)
        if cols["joules_per_token"] is not None:
            energy_extra = {
                "joules_per_token_model": cols["joules_per_token"],
                "tpu_util_est": cols["tpu_util_est"],
                "tpu_power_model_W": cols["tpu_power_model_W"],
            }
        # Batched-serving J/token per measured engine, from each one's
        # best decode window: weights stream ONCE per step for the whole
        # batch (the amortisation batching exists for) while every row
        # streams its own KV — int8-KV halves the per-row KV term, which
        # is what the paged_int8 entry's model figure tracks.
        if batch_tokens_per_s is not None and batch_windows:
            from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
                decode_kv_stream_bytes,
                decode_vpu_unpack_ops_per_step,
                decode_weight_stream_bytes,
            )

            batch_energy = {}
            for name, (tokens, window_s) in batch_windows.items():
                gen_per_row = tokens / batch_rows
                per_row_total = result.prompt_tokens + gen_per_row
                mid_ctx = int(result.prompt_tokens + gen_per_row / 2)
                kv_mode = "int8" if name == "paged_int8" else None
                steps = gen_per_row
                bstats = {
                    "flops": cfg.flops_per_token(int(per_row_total))
                    * tokens,
                    "bytes": (
                        decode_weight_stream_bytes(cfg, quantize)
                        + batch_rows
                        * decode_kv_stream_bytes(
                            cfg, mid_ctx, kv_quantize=kv_mode
                        )
                    )
                    * steps,
                    "vpu_ops": decode_vpu_unpack_ops_per_step(
                        cfg, quantize
                    )
                    * steps,
                    "duration_s": window_s,
                    "generated_tokens": tokens,
                }
                bctx = _types.SimpleNamespace(
                    scratch={"generation_stats": bstats}
                )
                bcols = TpuEnergyModelProfiler().collect(bctx)
                batch_energy[name] = {
                    "joules_per_token_model": bcols["joules_per_token"],
                    "tpu_power_model_W": bcols["tpu_power_model_W"],
                }
            energy_extra["batch_energy_model"] = batch_energy
    except Exception:  # the perf line must never die on the energy extra
        pass

    line = {
        "metric": "decode_tokens_per_s",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_s / BASELINE_TOKENS_PER_S, 3),
        "model": cfg.name,
        "backend": backend,
        "quantize": quantize,
        "n_layers": cfg.n_layers,
        "generated_tokens": result.generated_tokens,
        "decode_s": round(result.decode_s, 3),
        "prefill_s": round(result.prefill_s, 4),
        "warmup_compile_s": round(warm_s, 1),
        "baseline_tokens_per_s": round(BASELINE_TOKENS_PER_S, 2),
        **energy_extra,
    }
    if batch_tokens_per_s is not None:
        # batch_rows + the timing discipline are recorded so cross-round
        # artifacts under the same key stay self-describing (ADVICE
        # round-4: r01-r03 ran 8 rows / 1 window, r04+ runs 128 rows /
        # best-of-2 — the numbers are not comparable without these)
        line.update(
            batch_rows=batch_rows,
            batch_timed_runs=BATCH_TIMED_RUNS,
            batch_stat=BATCH_STAT,
            # r05+: tokens / sum of DISTINCT decode windows, with fleets
            # ≤ the memory bound running as ONE window. r01–r04 divided
            # a 4-sub-batch fleet's tokens by its first 32-row window,
            # inflating the 128-row figure ~4× (docs/PERF.md round-5
            # correction) — r05+ batch numbers are honest and NOT
            # comparable to earlier rounds' under this key.
            batch_window_sum=True,
            batch_by_engine=batch_by_engine,
            batch_tokens_per_s=round(batch_tokens_per_s, 2),
            batch_vs_baseline=round(
                batch_tokens_per_s / BASELINE_TOKENS_PER_S, 3
            ),
        )
    # Obs attachments: the engines above recorded their prefill/decode
    # windows, step counts per attention path, pool occupancy and
    # modelled J/token into the shared registry — and their decisions
    # into the flight recorder — as they ran; attach both so
    # BENCH_*.json rows carry the distributions and the event counts,
    # not just the aggregate figures.
    _attach_obs(line)
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
