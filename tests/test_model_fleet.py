"""Multi-model serving (ISSUE 15): per-model fleet lanes, energy-aware
model routing, the weight-LRU eviction guard, and the router's model
placement dimension."""

import dataclasses
import threading
import time

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.energy import (
    WASTED_J,
    WASTED_TOKENS,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.model_fleet import (
    ModelFleetScheduler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.protocol import (
    AUTO_MODEL,
)

SMALL, BIG = "small:1b", "big:7b"


def _fleet(backend=None, policy="small-first", **kw):
    backend = backend or FakeBackend(
        model_bytes={SMALL: 100, BIG: 1000},
        model_joules={SMALL: 0.1, BIG: 0.9},
    )
    fleet = ModelFleetScheduler(
        backend, models=[BIG, SMALL], model_policy=policy, **kw
    )
    fleet.start()
    return backend, fleet


def _req(model, prompt="hello", n=8, **kw):
    return GenerationRequest(model, prompt, max_new_tokens=n, **kw)


# -- lanes + head-of-line blocking ---------------------------------------------


def test_lanes_route_by_model_and_fallback_counter_stays_flat():
    """Mixed-model traffic runs per-model lanes — no ticket ever hits
    another model's session, so the window-batch incompatibility
    fallback counter stays flat (the ISSUE-15 satellite pin)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import (
        scheduler as sched_mod,
    )

    fallback0 = sched_mod._BATCH_FALLBACK_C.labels().value
    _backend, fleet = _fleet()
    try:
        results = [
            fleet.submit(_req(m, f"p{i}", n=4))
            for i, m in enumerate([SMALL, BIG, SMALL, BIG])
        ]
        assert [r.request.model for r in results] == [SMALL, BIG, SMALL, BIG]
        state = fleet.debug_state()
        assert state["mode"] == "fleet"
        assert set(state["lanes"]) == {SMALL, BIG}
        assert state["kv_budget_frac"] == 0.5
    finally:
        fleet.stop()
    assert sched_mod._BATCH_FALLBACK_C.labels().value == fallback0


def test_no_cross_model_head_of_line_blocking():
    """A long big-model decode in flight must not delay a small-model
    request: the small lane admits/steps/retires concurrently (slices
    interleave under the shared backend lock) instead of queueing for
    the big session to drain."""
    backend = FakeBackend(
        tokens_per_s=200.0,
        simulate_delay=True,
        model_bytes={SMALL: 100, BIG: 1000},
    )
    _b, fleet = _fleet(backend)
    done_at = {}

    def client(name, model, n, delay_s):
        time.sleep(delay_s)
        fleet.submit(_req(model, name, n=n))
        done_at[name] = time.monotonic()

    try:
        threads = [
            threading.Thread(target=client, args=("big", BIG, 128, 0.0)),
            threading.Thread(target=client, args=("s1", SMALL, 8, 0.08)),
            threading.Thread(target=client, args=("s2", SMALL, 8, 0.12)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert set(done_at) == {"big", "s1", "s2"}
        # the small requests finished strictly before the big decode —
        # under the serialized (model-affine single scheduler) baseline
        # they would wait its whole session out
        assert done_at["s1"] < done_at["big"]
        assert done_at["s2"] < done_at["big"]
    finally:
        fleet.stop()


def test_kv_budget_frac_splits_admission_cap():
    """The HBM envelope split: every live lane's admission cap scales
    to its 1/N share, re-evaluated as lanes appear."""

    class Probe(FakeBackend):
        def max_admission_rows(self, request):
            return 64

    backend = Probe()
    fleet = ModelFleetScheduler(backend, models=[SMALL])
    lane = fleet._lanes[SMALL]
    assert lane.kv_budget_frac == 1.0
    fleet._ensure_lane(BIG)
    assert lane.kv_budget_frac == 0.5
    assert fleet._lanes[BIG].kv_budget_frac == 0.5

    class Ticket:
        request = _req(SMALL)

    assert lane._admission_cap(Ticket()) == 32  # 64-row estimate halved


# -- model: "auto" resolution --------------------------------------------------


def test_auto_resolution_deterministic_under_pinned_registry():
    """small-first always picks the smallest model by weight bytes;
    cheapest-joules prefers the lowest LIVE J/token and falls back to
    weight bytes before any attribution exists. Repeated resolution is
    stable (ties break by name)."""
    backend = FakeBackend(model_bytes={SMALL: 100, BIG: 1000})
    fleet = ModelFleetScheduler(
        backend, models=[BIG, SMALL], model_policy="small-first"
    )
    assert fleet.models_by_size() == [SMALL, BIG]
    assert [fleet._choose()[0] for _ in range(3)] == [SMALL] * 3

    # cheapest-joules, no live attribution: weight-bytes fallback
    cheap = ModelFleetScheduler(
        backend, models=[BIG, SMALL], model_policy="cheapest-joules"
    )
    assert cheap._choose() == (SMALL, False)
    # live figures flip the ranking: big becomes the cheap one
    backend.last_joules_per_token_by_model = {SMALL: 0.9, BIG: 0.1}
    assert cheap._choose() == (BIG, False)
    # a model WITH attribution outranks one without
    backend.last_joules_per_token_by_model = {BIG: 0.5}
    assert cheap._choose() == (BIG, False)

    # pinned-registry determinism on a REAL engine: equal-size tiny
    # models order by name (the weight-bytes estimate ties), repeatably
    eng = _tiny_two_model_engine()
    real = ModelFleetScheduler(
        eng, models=["tiny-b", "tiny-a"], model_policy="small-first"
    )
    assert real.models_by_size() == ["tiny-a", "tiny-b"]
    assert [real._choose() for _ in range(3)] == [("tiny-a", True)] * 3


def test_auto_resolves_and_stamps_fleet_extras():
    _backend, fleet = _fleet(policy="cheapest-joules")
    try:
        result = fleet.submit(_req(AUTO_MODEL, "route me", n=8))
        assert result.request.model == SMALL
        assert result.extras["fleet"] == {
            "model": SMALL,
            "policy": "cheapest-joules",
        }
    finally:
        fleet.stop()


# -- small-first cascade + escalation ------------------------------------------


def test_escalation_on_length_cut_charges_wasted_ledger():
    """An auto request whose small-model answer burns its whole budget
    without EOS (the fake always budget-cuts) escalates to the big
    model; the abandoned small tokens charge cause="escalation" and the
    figure rides x_extras.energy next to the fleet attribution."""
    wasted0 = WASTED_J.labels(cause="escalation").value
    tokens0 = WASTED_TOKENS.labels(cause="escalation").value
    _backend, fleet = _fleet()
    try:
        result = fleet.submit(_req(AUTO_MODEL, "long question", n=64))
        assert result.request.model == BIG
        assert result.extras["fleet"]["escalated"] is True
        assert result.extras["fleet"]["escalated_from"] == SMALL
        wire_j = result.extras["energy"]["wasted_J"]["escalation"]
        ledger_j = WASTED_J.labels(cause="escalation").value - wasted0
        assert wire_j > 0 and abs(wire_j - ledger_j) < 1e-6
        # abandoned = small prompt prefill + its generated budget,
        # priced at the small model's live J/token (0.1)
        abandoned = (
            WASTED_TOKENS.labels(cause="escalation").value - tokens0
        )
        assert abandoned == len(b"long question") + 1 + 64
        assert abs(ledger_j - 0.1 * abandoned) < 1e-6
        assert fleet.escalations == 1
    finally:
        fleet.stop()


def test_no_escalation_below_length_floor():
    """A tightly-capped answer is not evidence of low confidence: below
    escalate_max_tokens the small result stands."""
    _backend, fleet = _fleet(escalate_max_tokens=32)
    try:
        result = fleet.submit(_req(AUTO_MODEL, "short", n=8))
        assert result.request.model == SMALL
        assert "escalated" not in result.extras.get("fleet", {})
        assert fleet.escalations == 0
    finally:
        fleet.stop()


def test_streamed_auto_resolves_but_never_cascades():
    backend = FakeBackend(
        tokens_per_s=500.0,
        simulate_delay=True,
        model_bytes={SMALL: 100, BIG: 1000},
    )
    _b, fleet = _fleet(backend)
    try:
        channel = fleet.submit_stream(_req(AUTO_MODEL, "stream me", n=64))
        final = None
        for event in channel.events():
            if event.kind == "done":
                final = event.result
            elif event.kind == "error":
                raise event.error
        # resolved small and STAYED small despite the budget cut —
        # streamed tokens cannot be replaced by a bigger model's answer
        assert final is not None and final.request.model == SMALL
        assert fleet.escalations == 0
    finally:
        fleet.stop()


def test_fleet_rejects_bad_config():
    backend = FakeBackend()
    with pytest.raises(ValueError, match="model policy"):
        ModelFleetScheduler(backend, models=[SMALL], model_policy="best")
    with pytest.raises(ValueError, match="escalate_max_tokens"):
        ModelFleetScheduler(
            backend, models=[SMALL], escalate_max_tokens=0
        )

    class NoStep:
        pass

    with pytest.raises(ValueError, match="stepped-decode"):
        ModelFleetScheduler(NoStep(), models=[SMALL])


# -- weight-LRU eviction guard (engine side) -----------------------------------


def _tiny_two_model_engine():
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (  # noqa: E501
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    tiny = get_model_config("qwen2:1.5b").tiny(max_seq_len=256)
    a = dataclasses.replace(tiny, name="tiny-a")
    b = dataclasses.replace(tiny, name="tiny-b")
    return JaxEngine(
        registry={"tiny-a": a, "tiny-b": b}, dtype=jnp.float32
    )


def test_eviction_deferred_until_live_session_drains(monkeypatch):
    """The ISSUE-15 sharp edge: an LRU eviction whose victim holds live
    stepped rows is DEFERRED (the deferral counter moves, the weights
    stay) and runs only once the session drains and closes."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        MODEL_EVICT_DEFERRED_C,
        MODEL_EVICTIONS_C,
        MODEL_LOADED_G,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import (
        memory as mem,
    )

    eng = _tiny_two_model_engine()
    budget = int(eng.model_weight_bytes("tiny-a") * 1.5)
    monkeypatch.setattr(
        mem, "device_allocation_budget", lambda device=None: budget
    )
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)

    eng.load_model("tiny-a")
    assert MODEL_LOADED_G.labels(model="tiny-a").value == 1.0
    session = eng.decode_open(
        [GenerationRequest("tiny-a", "hello", max_new_tokens=4)]
    )
    assert eng.live_sessions("tiny-a") == 1
    deferred0 = MODEL_EVICT_DEFERRED_C.labels().value
    evicted0 = MODEL_EVICTIONS_C.labels(reason="lru").value

    eng.load_model("tiny-b")  # over budget — but tiny-a holds live rows
    assert "tiny-a" in eng.loaded_models()  # deferred, not evicted
    assert MODEL_EVICT_DEFERRED_C.labels().value == deferred0 + 1
    assert MODEL_EVICTIONS_C.labels(reason="lru").value == evicted0
    # the engine still answers for the live session — token stream
    # unbroken by the deferral
    while session.active:
        session.step(4)
    session.close()
    assert eng.live_sessions("tiny-a") == 0

    # with the session drained, the NEXT load's capacity pass evicts
    eng._evict_weights("tiny-b", reason="lru")
    eng.load_model("tiny-b")
    assert "tiny-a" not in eng.loaded_models()
    assert MODEL_LOADED_G.labels(model="tiny-a").value == 0.0
    assert MODEL_EVICTIONS_C.labels(reason="lru").value > evicted0


def test_session_pins_release_on_close_even_for_draft(monkeypatch):
    """A failed open leaks no pin; a successful one pins exactly its
    models and close() releases them exactly once."""
    eng = _tiny_two_model_engine()
    session = eng.decode_open(
        [GenerationRequest("tiny-a", "x", max_new_tokens=2)]
    )
    assert eng.live_sessions("tiny-a") == 1
    session.close()
    session.close()  # idempotent
    assert eng.live_sessions("tiny-a") == 0
    with pytest.raises(ValueError):
        eng.decode_open([])  # failed open: no pins
    assert eng._live_sessions == {}


# -- weight-lifecycle observability --------------------------------------------


def test_fake_weight_lifecycle_gauges_and_events():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
        EV_MODEL_EVICTED,
        EV_MODEL_LOADED,
        FLIGHT,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        MODEL_LOADED_G,
        MODEL_WEIGHT_BYTES_G,
    )

    backend = FakeBackend(model_bytes={SMALL: 4096})
    backend.load_model(SMALL)
    assert MODEL_LOADED_G.labels(model=SMALL).value == 1.0
    assert MODEL_WEIGHT_BYTES_G.labels(model=SMALL).value == 4096
    loaded_events = [
        e
        for e in FLIGHT.events(type_=EV_MODEL_LOADED)
        if e.get("model") == SMALL
    ]
    assert loaded_events

    assert backend.evict_model(SMALL) is True
    assert backend.evict_model(SMALL) is False  # already gone
    assert MODEL_LOADED_G.labels(model=SMALL).value == 0.0
    assert SMALL not in backend.loaded_models()
    evict_events = [
        e
        for e in FLIGHT.events(type_=EV_MODEL_EVICTED)
        if e.get("model") == SMALL
    ]
    assert evict_events and evict_events[-1]["reason"] == "lru"


# -- router: /api/ps federation + model placement ------------------------------


def test_router_ps_federation_and_placement_preference():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
        LocalReplica,
        Router,
    )

    warm = FakeBackend()
    warm.load_model(BIG)
    cold = FakeBackend()
    cold.load_model(SMALL)
    router = Router(
        [
            LocalReplica("warm", warm),
            LocalReplica("cold", cold),
        ],
        policy="least-queue",
    )
    try:
        router.probe_now()
        ps = router.ps_state()
        assert ps["x_replicas"] == {
            "warm": [BIG],
            "cold": [SMALL],
        }
        assert {m["name"]: m["x_replicas"] for m in ps["models"]} == {
            BIG: ["warm"],
            SMALL: ["cold"],
        }
        # placement: a BIG ticket prefers the replica holding it warm,
        # repeatedly — even though least-queue alone would alternate
        for _ in range(4):
            assert router._pick(model=BIG).name == "warm"
            assert router._pick(model=SMALL).name == "cold"
        # a model nobody holds leaves the candidate set untouched
        assert router._pick(model="stranger:13b") is not None
        # dispatch routes by the request's model end-to-end
        result = router.dispatch(_req(BIG, "placed", n=4))
        assert result.extras["router"]["replica"] == "warm"
    finally:
        router.stop()


# -- poisson_load model mix ----------------------------------------------------


def test_model_mix_draws_seeded_and_summary_splits():
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ),
    )
    from poisson_load import (
        build_workload,
        draw_models,
        parse_model_mix,
        run_load,
        summarize,
    )

    mix = parse_model_mix(f"{SMALL}=0.5,{BIG}=0.5")
    assert mix == {SMALL: 0.5, BIG: 0.5}
    with pytest.raises(ValueError, match="sum past 1"):
        parse_model_mix(f"{SMALL}=0.9,{BIG}=0.9")
    draws = draw_models(32, mix, "auto", seed=3)
    assert draws == draw_models(32, mix, "auto", seed=3)  # seeded
    assert {SMALL, BIG} <= set(draws)
    # the model stream is independent of arrivals: same seed, mix on or
    # off, identical arrival offsets
    base = build_workload(8, 0.001, seed=5, model=SMALL)
    mixed = build_workload(8, 0.001, seed=5, model=SMALL, model_mix=mix)
    assert [t for t, _ in base] == [t for t, _ in mixed]

    _backend, fleet = _fleet()
    try:
        records = run_load(fleet.submit, mixed)
    finally:
        fleet.stop()
    summary = summarize(records)
    assert summary["errors"] == 0
    assert set(summary["models"]) <= {SMALL, BIG}
    assert (
        sum(m["requests"] for m in summary["models"].values())
        == summary["requests"]
    )
