"""AST-hash invariance and run-table reconciliation on restart."""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import (
    AllRunsCompletedError,
    ResumeError,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.resume import (
    config_ast_hash,
    reconcile_run_tables,
)

BASE_SRC = '''
class Config:
    """Docstring v1."""
    name = "exp"

    def hook(self):
        # a comment
        return 1 + 2
'''

COSMETIC_SRC = '''

class Config:
    """Totally different docstring."""

    name = "exp"
    def hook(self):
        return 1 + 2   # comment moved and lines shifted
'''

SUBSTANTIVE_SRC = BASE_SRC.replace("1 + 2", "1 + 3")


def test_ast_hash_ignores_comments_docstrings_whitespace():
    # reference __main__.py:27-49: cosmetic edits must not invalidate resume
    assert config_ast_hash(BASE_SRC) == config_ast_hash(COSMETIC_SRC)


def test_ast_hash_detects_substantive_change():
    assert config_ast_hash(BASE_SRC) != config_ast_hash(SUBSTANTIVE_SRC)


def _gen(n=3, extra=None):
    rows = []
    for i in range(n):
        row = {
            "__run_id": f"run_{i}_repetition_0",
            "__done": RunProgress.TODO,
            "model": f"m{i}",
            "energy_J": None,
        }
        if extra:
            row.update(extra)
        rows.append(row)
    return rows


def test_reconcile_copies_done_and_data():
    stored = _gen()
    stored[1]["__done"] = RunProgress.DONE
    stored[1]["energy_J"] = 9.5
    merged = reconcile_run_tables(_gen(), stored)
    assert merged[1]["__done"] == RunProgress.DONE
    assert merged[1]["energy_J"] == 9.5
    assert merged[0]["__done"] == RunProgress.TODO


def test_reconcile_preserves_stored_order():
    stored = list(reversed(_gen()))
    stored[0]["__done"] = RunProgress.DONE  # run_2
    merged = reconcile_run_tables(_gen(), stored)
    assert [r["__run_id"] for r in merged] == [r["__run_id"] for r in stored]


def test_reconcile_retries_failed_when_asked():
    stored = _gen()
    stored[0]["__done"] = RunProgress.FAILED
    stored[1]["__done"] = RunProgress.DONE
    merged = reconcile_run_tables(_gen(), stored, retry_failed=True)
    assert merged[0]["__done"] == RunProgress.TODO
    merged = reconcile_run_tables(_gen(), stored, retry_failed=False)
    assert merged[0]["__done"] == RunProgress.FAILED


def test_reconcile_tolerates_added_columns():
    """A profiler upgrade adding data columns must not strand a half-finished
    sweep; completed rows carry None for the new column."""
    stored = _gen()
    stored[0]["__done"] = RunProgress.DONE
    stored[0]["energy_J"] = 5.0
    merged = reconcile_run_tables(_gen(extra={"new_col": None}), stored)
    assert merged[0]["new_col"] is None
    assert merged[0]["energy_J"] == 5.0


def test_reconcile_rejects_removed_columns():
    with pytest.raises(ResumeError, match="removed"):
        reconcile_run_tables(_gen(), _gen(extra={"old_col": None}))


def test_reconcile_rejects_run_id_change():
    with pytest.raises(ResumeError, match="run ids changed"):
        reconcile_run_tables(_gen(n=2), _gen(n=3))


def test_reconcile_rejects_factor_value_drift():
    stored = _gen()
    stored[0]["model"] = "different"
    with pytest.raises(ResumeError, match="factor value changed"):
        reconcile_run_tables(_gen(), stored)


def test_all_done_raises():
    stored = _gen()
    for r in stored:
        r["__done"] = RunProgress.DONE
    with pytest.raises(AllRunsCompletedError):
        reconcile_run_tables(_gen(), stored)
