"""Numeric ops: RoPE, RMSNorm, attention reference implementations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.attention import (
    decode_attention_reference,
    prefill_attention,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.norms import rms_norm
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.rope import (
    apply_rope,
    rope_angles,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.sampling import sample_token


def test_rope_identity_at_position_zero():
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 4, 16))
    cos, sin = rope_angles(jnp.zeros((1, 1), dtype=jnp.int32), 16, 10_000.0)
    np.testing.assert_allclose(apply_rope(x, cos, sin), x, atol=1e-6)


def test_rope_preserves_norm():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 4, 32))
    pos = jnp.arange(3, dtype=jnp.int32)[None, :].repeat(2, axis=0)
    cos, sin = rope_angles(pos, 32, 10_000.0)
    rotated = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(rotated, axis=-1),
        jnp.linalg.norm(x, axis=-1),
        rtol=1e-5,
    )


def test_rope_relative_phase():
    """q·k after RoPE depends only on relative distance."""
    d = 16
    q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, d))
    k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, d))

    def dot_at(p_q, p_k):
        cq, sq = rope_angles(jnp.array([[p_q]], dtype=jnp.int32), d, 10_000.0)
        ck, sk = rope_angles(jnp.array([[p_k]], dtype=jnp.int32), d, 10_000.0)
        return float(jnp.sum(apply_rope(q, cq, sq) * apply_rope(k, ck, sk)))

    assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-5)
    assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-3)


def test_rms_norm_unit_variance():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64)) * 5.0
    out = rms_norm(x, jnp.ones((64,)))
    rms = jnp.sqrt(jnp.mean(out**2, axis=-1))
    np.testing.assert_allclose(rms, 1.0, rtol=1e-3)


def test_rms_norm_gemma_style_zero_weight_is_identity_gain():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    plain = rms_norm(x, jnp.ones((64,)))
    gemma = rms_norm(x, jnp.zeros((64,)), gemma_style=True)
    np.testing.assert_allclose(plain, gemma, atol=1e-6)


def test_prefill_attention_is_causal():
    """Changing a future token must not change earlier outputs."""
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 6, 4, 16)) for i in range(3))
    out1 = prefill_attention(q, k, v)
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out2 = prefill_attention(q, k2, v2)
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_decode_matches_prefill_last_position():
    """Single-step decode vs the cache == last row of full prefill."""
    b, s, hq, hkv, d = 2, 5, 8, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, hq, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d))
    full = prefill_attention(q, k, v)
    # cache layout [B,Hkv,T,D]: s valid entries, padded to a bigger buffer
    t = 12
    as_cache = lambda x: jnp.pad(  # noqa: E731
        x.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, t - s), (0, 0))
    )
    single = decode_attention_reference(
        q[:, -1], as_cache(k), as_cache(v), jnp.full((b,), s, dtype=jnp.int32)
    )
    np.testing.assert_allclose(single, full[:, -1], atol=1e-5)


def test_decode_attention_ignores_cache_garbage():
    b, hq, hkv, d, t = 1, 4, 4, 8, 10
    q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, t, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, t, d))
    lengths = jnp.array([4], dtype=jnp.int32)
    out1 = decode_attention_reference(q, k, v, lengths)
    k2 = k.at[:, :, 4:].set(1e6)
    v2 = v.at[:, :, 4:].set(-1e6)
    out2 = decode_attention_reference(q, k2, v2, lengths)
    np.testing.assert_allclose(out1, out2, atol=1e-6)


def test_sample_token_greedy_and_temperature():
    logits = jnp.array([[0.1, 5.0, 0.2, 0.0]])
    key = jax.random.PRNGKey(0)
    assert int(sample_token(logits, key, 0.0)[0]) == 1
    # high temperature: over many keys, should not always pick argmax
    sampler = jax.jit(lambda k: sample_token(logits, k, 5.0))
    picks = {int(sampler(jax.random.PRNGKey(i))[0]) for i in range(20)}
    assert len(picks) > 1
    # top_k=1 is greedy regardless of temperature
    assert int(sample_token(logits, key, 5.0, top_k=1)[0]) == 1


def test_sample_token_jit_with_traced_temperature():
    f = jax.jit(lambda lg, k, t: sample_token(lg, k, t))
    logits = jnp.array([[0.0, 3.0]])
    assert int(f(logits, jax.random.PRNGKey(0), jnp.float32(0.0))[0]) == 1


def test_top_p_filter_keeps_nucleus_only():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.sampling import (
        top_p_filter,
    )

    # probs ≈ [0.64, 0.23, 0.086, 0.03, 0.01]: top_p=0.5 keeps only argmax,
    # top_p=0.7 keeps the top two.
    logits = jnp.log(jnp.array([[0.64, 0.23, 0.086, 0.032, 0.012]]))
    kept_50 = np.isfinite(np.asarray(top_p_filter(logits, 0.5)))[0]
    assert kept_50.tolist() == [True, False, False, False, False]
    kept_70 = np.isfinite(np.asarray(top_p_filter(logits, 0.7)))[0]
    assert kept_70.tolist() == [True, True, False, False, False]
    # top_p=1.0 keeps everything
    kept_all = np.isfinite(np.asarray(top_p_filter(logits, 1.0)))[0]
    assert kept_all.all()


def test_sample_token_top_p_restricts_support():
    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    sampler = jax.jit(
        lambda k, p: sample_token(logits, k, 1.0, top_p=p)
    )
    picks = {
        int(sampler(jax.random.PRNGKey(i), jnp.float32(0.6))[0])
        for i in range(50)
    }
    assert picks <= {0, 1}
    # wide nucleus reaches the tail eventually
    picks_all = {
        int(sampler(jax.random.PRNGKey(i), jnp.float32(1.0))[0])
        for i in range(50)
    }
    assert len(picks_all) > 2


def test_repeat_penalty_discounts_seen_tokens():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.sampling import (
        apply_repeat_penalty,
    )

    logits = jnp.array([[2.0, 1.9, -0.5]])
    presence = jnp.array([[True, False, True]])
    out = np.asarray(apply_repeat_penalty(logits, presence, 2.0))
    np.testing.assert_allclose(out, [[1.0, 1.9, -1.0]], atol=1e-6)
    # greedy flips from token 0 to token 1 once 0 is penalised
    key = jax.random.PRNGKey(0)
    assert int(sample_token(logits, key, 0.0)[0]) == 0
    assert (
        int(
            sample_token(
                logits, key, 0.0, presence=presence, repeat_penalty=2.0
            )[0]
        )
        == 1
    )


def test_sample_token_per_row_matches_single_calls():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.sampling import (
        sample_token_per_row,
    )

    vocab = 13
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, vocab)) * 3
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4))
    temps = jnp.asarray([0.0, 0.7, 1.3, 2.0])
    batched = sample_token_per_row(logits, keys, temps, top_k=5)
    for r in range(4):
        single = sample_token(
            logits[r : r + 1], keys[r], temps[r], top_k=5
        )
        assert int(batched[r]) == int(single[0]), f"row {r}"
