"""Sampled speculative decoding via rejection resampling (ISSUE 16).

The correctness bar is DISTRIBUTION exactness, not token parity: a
sampled row's speculative stream consumes randomness differently from
plain decode (one key advance per round vs per token), so the streams
differ token-by-token — but Leviathan et al. 2023's rejection-resampling
construction guarantees the per-step conditional distribution is
IDENTICAL to plain ancestral sampling from the same modified
distribution. These tests pin that statistically: two-sample chi-squared
and total-variation distance over >= 10k pooled sampled tokens per cache
layout, spec-on vs spec-off, for all three draft sources (model-draft,
prompt-lookup n-gram, cross-model) — plus temp-0 bit-parity (greedy is
the limiting case), mid-flight sampled joiners, preempt/resume rng
round-trips, and 2-/8-device TP stability of the new carry leaves.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)

# chi-squared critical value, df=15 (16 bins), alpha=0.001: a FIXED-seed
# run either clears it forever or flags a real distribution shift
CHI2_CRIT_DF15 = 37.697
TV_BOUND = 0.06  # ~3x the sampling noise floor at 10k tokens/arm


@pytest.fixture(scope="module")
def registry():
    tiny = get_model_config("qwen2:1.5b").tiny(max_seq_len=1024)
    return {
        "tiny": tiny,
        "tiny-d": dataclasses.replace(tiny, n_layers=1),
    }


SOURCES = [
    pytest.param("model", ("tiny-d", 3), id="model"),
    pytest.param("ngram", ("ngram", 3), id="ngram"),
    pytest.param("cross", ("cross:tiny-d", 3), id="cross"),
]

LAYOUTS = [
    pytest.param(False, None, id="contig-bf16"),
    pytest.param(False, "int8", id="contig-int8"),
    pytest.param(True, None, id="paged-bf16"),
    pytest.param(True, "int8", id="paged-int8"),
]


def _drain(session, max_steps=16, limit=400):
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


def _dist_requests():
    """The shared sampled workload: prompts repeat a little (so the
    n-gram source gets some lookup hits), seeds differ per row (so rows
    are independent draws)."""
    return [
        GenerationRequest(
            "tiny",
            f"the probe row {i % 7} the probe row {i % 7} again",
            max_new_tokens=200,
            temperature=0.7,
            seed=1000 + i,
            stop_at_eos=False,
        )
        for i in range(80)
    ]


def _bins16(results):
    """Pooled token histogram over id mod 16 — collapses the 512-wide
    vocab into stable-mass bins for the chi-squared test."""
    counts = [0] * 16
    for r in results:
        for t in r.tokens:
            counts[t % 16] += 1
    return counts


def _chi2_tv(a, b):
    na, nb = sum(a), sum(b)
    ra, rb = (nb / na) ** 0.5, (na / nb) ** 0.5
    chi2 = sum(
        (ai * ra - bi * rb) ** 2 / (ai + bi)
        for ai, bi in zip(a, b)
        if ai + bi
    )
    tv = 0.5 * sum(abs(ai / na - bi / nb) for ai, bi in zip(a, b))
    return chi2, tv


# spec-OFF baselines, one per layout, shared across the three source
# combos (the expensive half of each comparison only runs 4 times)
_BASELINES = {}


def _baseline(registry, paged, kv):
    key = (paged, kv)
    if key not in _BASELINES:
        eng = JaxEngine(
            registry=dict(registry), dtype=jnp.float32,
            paged_kv=paged, kv_quantize=kv,
        )
        results = _drain(eng.decode_open(_dist_requests()))
        _BASELINES[key] = _bins16(results)
    return _BASELINES[key]


@pytest.mark.parametrize("paged,kv", LAYOUTS)
@pytest.mark.parametrize("source,spec", SOURCES)
def test_sampled_spec_matches_plain_distribution(
    registry, source, spec, paged, kv
):
    """The tentpole invariant: at temperature 0.7, a speculating
    session's pooled token distribution is statistically identical to
    the spec-off session's, on every cache layout and draft source."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=paged, kv_quantize=kv,
        speculative={"tiny": spec},
    )
    results = _drain(eng.decode_open(_dist_requests()))
    spec_bins = _bins16(results)
    assert sum(spec_bins) >= 10_000, "need >= 10k sampled tokens"
    for r in results:
        assert r.extras["spec"]["source"] == source
        assert r.extras["spec"]["rounds"] >= 1
    chi2, tv = _chi2_tv(_baseline(registry, paged, kv), spec_bins)
    assert chi2 < CHI2_CRIT_DF15, (
        f"{source} paged={paged} kv={kv}: chi2={chi2:.2f} tv={tv:.4f}"
    )
    assert tv < TV_BOUND, (
        f"{source} paged={paged} kv={kv}: tv={tv:.4f}"
    )


@pytest.mark.parametrize(
    "paged,kv",
    [
        pytest.param(False, None, id="contig-bf16"),
        pytest.param(True, "int8", id="paged-int8"),
    ],
)
@pytest.mark.parametrize("source,spec", SOURCES)
def test_temp0_spec_bit_parity_all_sources(registry, source, spec, paged, kv):
    """Greedy is rejection resampling's limiting case: at temperature 0
    every source's speculative stream is BIT-identical to plain greedy
    decode (not just distributionally)."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=paged, kv_quantize=kv,
        speculative={"tiny": spec},
    )
    plain = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=paged, kv_quantize=kv,
    )
    reqs = [
        GenerationRequest(
            "tiny", "abc abc abc abc", max_new_tokens=20, stop_at_eos=False
        ),
        GenerationRequest(
            "tiny", "the second greedy row", max_new_tokens=12, seed=2
        ),
    ]
    sess = eng.decode_open(reqs)
    assert sess.spec is not None and sess.spec["source"] == source
    results = {id(r.request): r for r in _drain(sess)}
    for r in reqs:
        assert results[id(r)].tokens == plain._generate_plain(r).tokens, (
            f"{source} diverged from greedy at temp 0"
        )


def test_spec_draft_temperature_keeps_marginals_and_greedy_parity(registry):
    """``spec_draft_temperature`` (ISSUE 18) flattens the draft's
    proposal distribution INDEPENDENTLY of each row's sampler params:
    the accept math follows the proposal distribution (q is computed
    from the same modified chain the proposals were drawn from), so
    the emitted marginals stay exactly the target's — the same
    chi-squared/TV pin as the main suite, with the knob set. Greedy
    rows keep greedy drafts, so temp-0 bit-parity is untouched."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        speculative={"tiny": ("tiny-d", 3)},
        spec_draft_temperature=1.3,
    )
    results = _drain(eng.decode_open(_dist_requests()))
    spec_bins = _bins16(results)
    assert sum(spec_bins) >= 10_000
    assert all(r.extras["spec"]["rounds"] >= 1 for r in results)
    chi2, tv = _chi2_tv(_baseline(registry, False, None), spec_bins)
    assert chi2 < CHI2_CRIT_DF15, f"draft_T=1.3: chi2={chi2:.2f}"
    assert tv < TV_BOUND, f"draft_T=1.3: tv={tv:.4f}"
    # greedy lane unaffected by the knob: bit-parity with plain greedy
    plain = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    greq = GenerationRequest(
        "tiny", "greedy draft-temp probe", max_new_tokens=20, seed=4
    )
    spec_toks = {
        id(r.request): r for r in _drain(eng.decode_open([greq]))
    }[id(greq)].tokens
    assert spec_toks == plain._generate_plain(greq).tokens


def test_sampled_joiner_inherits_ngram_spec_config(registry):
    """A sampled mid-flight joiner inherits the session's spec config —
    here the weightless n-gram source — and retires with its own spec
    extras; its history buffer row is rebuilt at join time."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        speculative={"tiny": ("ngram", 3)},
    )
    anchor = GenerationRequest(
        "tiny", "anchor aaa bbb aaa bbb", max_new_tokens=24,
        stop_at_eos=False,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert sess.spec is not None and sess.spec["source"] == "ngram"
    sess.step(4)
    joiner = GenerationRequest(
        "tiny", "sampled joiner xyz xyz xyz", max_new_tokens=16,
        temperature=0.7, seed=21, stop_at_eos=False,
    )
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    jx = results[id(joiner)].extras["spec"]
    assert jx["source"] == "ngram" and jx["draft_model"] is None
    assert jx["rounds"] >= 1


@pytest.mark.parametrize(
    "source,spec,policy",
    [
        pytest.param("model", ("tiny-d", 3), "swap", id="model-swap"),
        pytest.param(
            "model", ("tiny-d", 3), "recompute", id="model-recompute"
        ),
        pytest.param("ngram", ("ngram", 3), "swap", id="ngram-swap"),
        pytest.param(
            "ngram", ("ngram", 3), "recompute", id="ngram-recompute"
        ),
    ],
)
def test_sampled_spec_preempt_resume_rng_bit_exact(
    registry, source, spec, policy
):
    """Preempting a SAMPLED speculating row and resuming it — swap or
    recompute — continues the stream bit-exactly: the per-row rng key
    (which advances once per round) survives the round-trip, the draft
    cache row (model source) or n-gram history (rebuilt host-side) is
    reinstalled, and the final tokens equal an uninterrupted run's."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        speculative={"tiny": spec},
    )
    anchor = GenerationRequest(
        "tiny", "anchor keeps the session warm", max_new_tokens=40,
        temperature=0.7, seed=31, stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "victim vvv www vvv www", max_new_tokens=32,
        temperature=0.7, seed=32, stop_at_eos=False,
    )
    # the uninterrupted reference run (fresh identical requests so the
    # preempted run's request objects stay independent)
    ref_reqs = [
        dataclasses.replace(anchor), dataclasses.replace(victim)
    ]
    ref_sess = eng.decode_open(ref_reqs, reserve_rows=4)
    assert ref_sess.spec is not None
    ref = {r.request.prompt: r.tokens for r in _drain(ref_sess)}

    sess = eng.decode_open([anchor, victim], reserve_rows=4)
    sess.step(3)
    pr = sess.preempt(victim, policy=policy)
    assert pr is not None, "victim retired before preemption (reseed)"
    if policy == "swap" and source == "model":
        assert pr.draft_blob is not None  # draft cache rode the swap
    sess.step(3)  # the anchor decodes on while the victim is parked
    assert sess.can_resume(pr)
    pend = sess.resume_begin(pr, 64)
    while not sess.join_step(pend):
        pass
    sess.join_commit(pend)
    results = {r.request.prompt: r for r in _drain(sess)}
    assert results[victim.prompt].tokens == ref[victim.prompt], (
        f"{source}/{policy}: resumed stream diverged"
    )
    assert results[anchor.prompt].tokens == ref[anchor.prompt]
    assert results[victim.prompt].extras["spec"]["source"] == source
    sess.close()


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)
@pytest.mark.parametrize("n_devices", [2, 8])
@pytest.mark.parametrize(
    "source,spec",
    [
        pytest.param("model", ("tiny-d8", 3), id="model"),
        pytest.param("ngram", ("ngram", 3), id="ngram"),
    ],
)
def test_tp_sampled_spec_carry_leaves_stable(n_devices, source, spec):
    """The new carry leaves (per-row rng keys, n-gram history/length,
    the rejected-rounds counter) replicate on a 2- and 8-device mesh
    and keep their placement across compiled slice steps — the
    stepped_carry_shardings fallback rule, pinned on the sampled path."""
    from jax.sharding import PartitionSpec as P

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    tiny8 = dataclasses.replace(
        get_model_config("mistral:7b").tiny(),
        n_heads=8, n_kv_heads=8, d_ff=128, d_model=64, d_head=16,
        max_seq_len=1024,
    )
    reg = {"tiny8": tiny8, "tiny-d8": dataclasses.replace(tiny8, n_layers=1)}
    mesh = build_mesh(MeshSpec.tp_only(), devices=jax.devices()[:n_devices])
    eng = TensorParallelEngine(
        mesh=mesh, registry=reg, dtype=jnp.float32,
        speculative={"tiny8": spec},
    )
    reqs = [
        GenerationRequest(
            "tiny8", "mesh row one one one", max_new_tokens=16,
            temperature=0.7, seed=41, stop_at_eos=False,
        ),
        GenerationRequest(
            "tiny8", "mesh row two two two", max_new_tokens=16,
            temperature=0.7, seed=42, stop_at_eos=False,
        ),
    ]
    sess = eng.decode_open(reqs, reserve_rows=4)
    assert sess.spec is not None and sess.spec["source"] == source
    new_leaves = ["rngs", "spec_rejected"]
    if source == "ngram":
        new_leaves += ["ngram_hist", "ngram_len"]
    else:
        new_leaves += ["draft_offsets"]
    before = {}
    for key in new_leaves:
        assert key in sess.carry, key
        before[key] = sess.carry[key].sharding.spec
        assert before[key] == P(), key
    sess.step(4)
    for key in new_leaves:
        assert sess.carry[key].sharding.spec == before[key], key
    results = _drain(sess)
    assert len(results) == 2
    for r in results:
        assert r.extras["spec"]["source"] == source
        assert r.extras["spec"]["rounds"] >= 1
    sess.close()


def test_solo_generate_routes_sampled_through_spec(registry):
    """engine.generate() on a sampled eligible request drives the
    rejection-resampling lane (a one-row stepped session under the
    hood) and surfaces the flat spec extras the solo path documents;
    hotter-than-cap requests serve plain."""
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        speculative={"tiny": ("tiny-d", 3)},
        spec_temperature_max=1.0,
    )
    res = eng.generate(
        GenerationRequest(
            "tiny", "solo sampled run", max_new_tokens=12,
            temperature=0.7, seed=51, stop_at_eos=False,
        )
    )
    assert res.generated_tokens == 12
    assert res.extras["spec"]["source"] == "model"
    assert res.extras["spec_rounds"] >= 1
    assert res.extras["spec_accepted"] == res.extras["spec"]["accepted"]

    hot = eng.generate(
        GenerationRequest(
            "tiny", "hot solo run", max_new_tokens=8, temperature=1.5,
            seed=52,
        )
    )
    assert "spec" not in (hot.extras or {})
