"""Live prefill/decode row migration (ISSUE 18).

Three layers pinned here:

- the bundle codec (serve/migrate.py): JSON-wire-safe round-trips,
  export refusals for rows that must not leave their replica;
- the router's disagg pipeline + drain evacuation + fallback machinery
  over hermetic ``FakeBackend`` fleets: role-aware dispatch, one
  uninterrupted client stream with exact token parity, the retry/
  wasted-energy accounting, and the never-drop-a-ticket guarantees;
- the real engine at session level: a row preempted on one engine,
  shipped through the wire codec and seated on ANOTHER engine's
  session produces the bit-exact solo token stream on every cache
  layout, with both pools' page free counts restored exactly.
"""

import json
import threading
import time

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    MIGRATE_BYTES_C,
    MIGRATE_ROWS_C,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import (
    router as router_mod,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.migrate import (
    MigrateRefused,
    bundle_nbytes,
    export_bundle,
    import_bundle,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
    LocalReplica,
    Router,
)

def _req(prompt="migrate me", n=24, **kw):
    return GenerationRequest("m", prompt, max_new_tokens=n, **kw)


def _reference_tokens(request):
    """Single-replica reference stream for exact-parity checks."""
    ref = LocalReplica("ref", FakeBackend())
    router = Router([ref], probe_interval_s=999)
    try:
        return [
            t
            for ch in router.dispatch_stream(request)
            if not ch.done
            for t in ch.tokens
        ]
    finally:
        router.stop()


def _collect(router, request):
    toks, final = [], None
    for ch in router.dispatch_stream(request):
        if ch.done:
            final = ch.result
        else:
            toks.extend(ch.tokens)
    return toks, final


def _rows(reason):
    return MIGRATE_ROWS_C.labels(reason=reason).value


def _bytes(direction):
    return MIGRATE_BYTES_C.labels(direction=direction).value


# -- bundle codec --------------------------------------------------------------


def test_fake_bundle_json_roundtrip():
    backend = FakeBackend()
    req = _req(n=16, seed=3)
    result = backend._result(req)
    pr = {
        "request": req,
        "row": {"streamed": 4},
        "generated": result.tokens[:9],
        "prompt_len": 5,
        "policy": "swap",
        "host_bytes": 123,
    }
    bundle = json.loads(json.dumps(export_bundle(pr, reason="disagg")))
    assert bundle["kind"] == "fake" and bundle_nbytes(bundle) == 123
    # disagg primes override the stream watermark to 0 explicitly
    assert bundle["streamed"] == 4
    pr2 = import_bundle(bundle, backend)
    assert pr2["generated"] == result.tokens[:9]
    assert pr2["row"]["streamed"] == 4
    assert pr2["host_bytes"] == 0 and pr2["discharged"]


def test_export_refuses_shared_prefix_and_spec_rows():
    class _Stub:
        shared_pages = [1, 2]
        draft_blob = None

    with pytest.raises(MigrateRefused):
        export_bundle(_Stub())

    class _Spec:
        shared_pages = []
        draft_blob = object()

    with pytest.raises(MigrateRefused):
        export_bundle(_Spec())


def test_import_rejects_unknown_version():
    with pytest.raises(ValueError):
        import_bundle({"version": 99, "kind": "fake"})


# -- role-aware dispatch -------------------------------------------------------


def test_decode_only_fleet_refuses_fresh_work():
    router = Router(
        [LocalReplica("d0", FakeBackend(), role="decode")],
        probe_interval_s=999,
    )
    try:
        with pytest.raises(RuntimeError, match="no healthy replica"):
            router.dispatch(_req())
    finally:
        router.stop()


def test_fresh_work_never_lands_on_decode_replica():
    mixed = LocalReplica("mx", FakeBackend())
    dec = LocalReplica("dc", FakeBackend(), role="decode")
    router = Router([mixed, dec], probe_interval_s=999)
    try:
        before = router_mod._DISPATCH_C.labels(
            replica="dc", policy=router.policy
        ).value
        for i in range(6):
            router.dispatch(_req(prompt=f"p{i}", n=4))
        after = router_mod._DISPATCH_C.labels(
            replica="dc", policy=router.policy
        ).value
        # the decode replica services migrate-ins only; every fresh
        # ticket of a pure-generate workload lands elsewhere. (A
        # prefill+decode fleet WOULD dispatch to it via the relay —
        # that path increments on the migrate seat, tested below.)
        assert after == before
        roles = router.health_state()["replica_roles"]
        assert roles == {"mixed": 1, "decode": 1}
    finally:
        router.stop()


def test_replica_role_validation():
    with pytest.raises(ValueError):
        LocalReplica("bad", FakeBackend(), role="bogus")


# -- disagg pipeline (fake fleet) ----------------------------------------------


def test_disagg_fleet_exact_parity_and_attribution():
    """1 prefill + 1 decode: the client sees ONE uninterrupted stream
    with the exact single-replica token sequence; attribution says the
    row migrated; the energy ledger charged the transfer at 2x bundle
    bytes; the byte counters are symmetric."""
    req = _req(prompt="disagg parity probe", n=40, seed=11)
    expect = _reference_tokens(req)
    rows0, out0, in0 = _rows("disagg"), _bytes("out"), _bytes("in")
    router = Router(
        [
            LocalReplica("p", FakeBackend(), role="prefill"),
            LocalReplica("d", FakeBackend(), role="decode"),
        ],
        probe_interval_s=999,
    )
    try:
        toks, final = _collect(router, req)
        assert toks == expect and final is not None
        ex = final.extras or {}
        assert ex["router"]["replica"] == "d"
        assert ex["sched"]["migrated"] is True
        wasted = ex["energy"]["wasted_J"]["migration"]
        moved_out, moved_in = _bytes("out") - out0, _bytes("in") - in0
        assert moved_out == moved_in > 0
        assert wasted == pytest.approx(2.0 * moved_out * 1e-9)
        assert _rows("disagg") == rows0 + 1
    finally:
        router.stop()


def test_receiver_death_falls_back_to_source_local_decode():
    """The decode replica dies at seat time: the primed row decodes
    locally on the prefill replica — exact parity, one migrate_failed
    retry, never a dropped ticket."""
    req = _req(prompt="fallback probe", n=24, seed=5)
    expect = _reference_tokens(req)
    dead = FakeBackend()
    dead.fail_decode_open = True
    retries0 = router_mod._RETRIES_C.labels(reason="migrate_failed").value
    router = Router(
        [
            LocalReplica("p", FakeBackend(), role="prefill"),
            LocalReplica("d", dead, role="decode"),
        ],
        probe_interval_s=999,
    )
    try:
        toks, final = _collect(router, req)
        assert toks == expect
        assert final.extras["router"]["replica"] == "p"
        assert (
            router_mod._RETRIES_C.labels(reason="migrate_failed").value
            == retries0 + 1
        )
    finally:
        router.stop()


def test_drain_migrate_evacuates_mid_stream_cursor_survives():
    """``drain(migrate=True)`` mid-stream: the in-flight row moves to
    the survivor and the CLIENT's stream continues where it stopped —
    the spliced stream is the exact uninterrupted sequence."""
    req = _req(prompt="drain evacuation probe", n=60, seed=9)
    expect = _reference_tokens(req)
    rows0 = _rows("drain")
    fleet = [
        LocalReplica(
            "a", FakeBackend(tokens_per_s=200.0, simulate_delay=True)
        ),
        LocalReplica(
            "b", FakeBackend(tokens_per_s=200.0, simulate_delay=True)
        ),
    ]
    router = Router(fleet, probe_interval_s=999)
    toks, final, err = [], [None], [None]

    def consume():
        try:
            for ch in router.dispatch_stream(req):
                if ch.done:
                    final[0] = ch.result
                else:
                    toks.extend(ch.tokens)
        except BaseException as exc:  # noqa: BLE001
            err[0] = exc

    t = threading.Thread(target=consume)
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while len(toks) < 10 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(toks) >= 10, "stream never started"
        victim = next(r.name for r in fleet if r.outstanding > 0)
        survivor = next(r.name for r in fleet if r.name != victim)
        assert router.drain(victim, timeout_s=20.0, migrate=True)
        t.join(timeout=30.0)
        assert not t.is_alive() and err[0] is None
        assert toks == expect, "spliced stream is not the solo sequence"
        assert final[0].extras["router"]["replica"] == survivor
        assert final[0].extras["sched"]["migrated"] is True
        assert _rows("drain") == rows0 + 1
        assert victim not in [r.name for r in router.replicas()]
    finally:
        t.join(timeout=5.0)
        router.stop()


def test_spec_active_prime_decays_to_local_stream():
    """A speculating session never exports (draft state is engine-
    bound): the prime decays to a normal local stream on the prefill
    replica — full answer, no migration counters moved."""
    req = _req(prompt="spec prime decay", n=24, seed=2)
    ref = LocalReplica("sref", FakeBackend(spec_k=2))
    ref_router = Router([ref], probe_interval_s=999)
    try:
        expect = [
            t
            for ch in ref_router.dispatch_stream(req)
            if not ch.done
            for t in ch.tokens
        ]
    finally:
        ref_router.stop()
    rows0 = _rows("disagg")
    router = Router(
        [
            LocalReplica("p", FakeBackend(spec_k=2), role="prefill"),
            LocalReplica("d", FakeBackend(spec_k=2), role="decode"),
        ],
        probe_interval_s=999,
    )
    try:
        toks, final = _collect(router, req)
        assert toks == expect
        assert final.extras["router"]["replica"] == "p"
        assert "migrated" not in (final.extras.get("sched") or {})
        assert _rows("disagg") == rows0
    finally:
        router.stop()


# -- real engine: cross-engine seating parity ----------------------------------


@pytest.fixture(scope="module")
def engines():
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    cache = {}

    def get(tag, paged, kvq):
        key = (tag, paged, kvq)
        if key not in cache:
            cache[key] = JaxEngine(
                registry=dict(registry),
                dtype=jnp.float32,
                paged_kv=paged,
                kv_quantize=kvq,
            )
        return cache[key]

    return get


LAYOUTS = [
    pytest.param(False, None, id="contig-bf16"),
    pytest.param(False, "int8", id="contig-int8"),
    pytest.param(True, None, id="paged-bf16"),
    pytest.param(True, "int8", id="paged-int8"),
]


def _drain_into(sess, results):
    # keyed by prompt: a migrated row's request is REBUILT from the
    # bundle's wire form, so object identity does not survive the trip
    while sess.active:
        for res in sess.step(8):
            results[res.request.prompt] = res


@pytest.mark.parametrize("paged,kvq", LAYOUTS)
def test_real_migrate_token_parity_all_layouts(engines, paged, kvq):
    """A row preempted on the SOURCE engine, shipped through the JSON
    wire codec and seated on a DIFFERENT engine's session finishes
    with the bit-exact solo token stream; page free counts restore
    exactly on BOTH pools; the source's swap ledger settles at export
    (the import is charge-free)."""
    src = engines("src", paged, kvq)
    dst = engines("dst", paged, kvq)
    anchor_s = GenerationRequest(
        "tiny", "source anchor decodes on", max_new_tokens=24,
        stop_at_eos=False,
    )
    anchor_d = GenerationRequest(
        "tiny", "destination anchor row", max_new_tokens=24,
        stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "the migrating row", max_new_tokens=20,
        stop_at_eos=False, seed=13, priority=0,
    )
    solo = src.generate(victim).tokens
    s_sess = src.decode_open([anchor_s, victim], reserve_rows=4)
    d_sess = dst.decode_open([anchor_d], reserve_rows=4)
    s_idle = s_sess.pool.n_pages - 1 if paged else None
    d_idle = d_sess.pool.n_pages - 1 if paged else None
    s_sess.step(4)
    d_sess.step(2)
    free_s = s_sess.pool.free_pages if paged else None
    free_d = d_sess.pool.free_pages if paged else None

    pr = s_sess.preempt(victim, policy="swap")
    assert pr is not None
    bundle = export_bundle(pr, reason="disagg", streamed=0)
    s_sess.resume_discard(pr)  # the SOURCE settles the swap ledger
    if paged:
        # every page the victim held is back on the source free list
        assert s_sess.pool.free_pages == free_s + pr.n_own_pages

    # the wire trip: the bundle must survive JSON serialization intact
    bundle = json.loads(json.dumps(bundle))
    assert bundle["kind"] == "real" and bundle_nbytes(bundle) > 0
    pr2 = import_bundle(bundle)
    assert pr2.host_bytes == 0 and pr2.discharged
    assert d_sess.can_resume(pr2)
    pend = d_sess.resume_begin(pr2, 64)
    while not d_sess.join_step(pend):
        pass
    d_sess.join_commit(pend)
    if paged:
        assert d_sess.pool.free_pages < free_d  # pages actually seated

    results_s, results_d = {}, {}
    _drain_into(s_sess, results_s)
    _drain_into(d_sess, results_d)
    assert results_d[victim.prompt].tokens == solo
    assert results_s[anchor_s.prompt].tokens == src.generate(anchor_s).tokens
    s_sess.close()
    d_sess.close()
    if paged:
        assert s_sess.pool.free_pages == s_idle
        assert d_sess.pool.free_pages == d_idle


def test_real_receiver_failure_falls_back_to_source_seat(engines):
    """Receiver dies mid-transfer: the destination pool never moves,
    and the exported bundle seats back on the SOURCE session (the
    router's fallback path) — exact parity, both pools restored."""
    src = engines("src", True, None)
    dst = engines("dst", True, None)
    anchor = GenerationRequest(
        "tiny", "anchor keeps the session open", max_new_tokens=28,
        stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "fallback migrating row", max_new_tokens=18,
        stop_at_eos=False, seed=21, priority=0,
    )
    solo = src.generate(victim).tokens
    s_sess = src.decode_open([anchor, victim], reserve_rows=4)
    d_sess = dst.decode_open(
        [
            GenerationRequest(
                "tiny", "destination anchor", max_new_tokens=8,
                stop_at_eos=False,
            )
        ],
        reserve_rows=4,
    )
    s_idle = s_sess.pool.n_pages - 1
    s_sess.step(4)
    free_d = d_sess.pool.free_pages

    pr = s_sess.preempt(victim, policy="swap")
    bundle = json.loads(json.dumps(export_bundle(pr, reason="disagg")))
    s_sess.resume_discard(pr)

    # receiver "dies": nothing is ever seated on the destination
    assert d_sess.pool.free_pages == free_d

    pr_back = import_bundle(bundle)
    assert s_sess.can_resume(pr_back)
    pend = s_sess.resume_begin(pr_back, 64)
    while not s_sess.join_step(pend):
        pass
    s_sess.join_commit(pend)
    results = {}
    _drain_into(s_sess, results)
    assert results[victim.prompt].tokens == solo
    s_sess.close()
    d_sess.close()
    assert s_sess.pool.free_pages == s_idle
    assert d_sess.pool.free_pages == d_sess.pool.n_pages - 1
