"""Model configs and the shared transformer: shapes, variants, prefill/decode parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    MODEL_REGISTRY,
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tokenizer import ByteTokenizer
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
    forward,
    logits_for,
)

REFERENCE_MODELS = [
    "qwen2:1.5b",
    "gemma:2b",
    "phi3:3.8b",
    "gemma:7b",
    "qwen2:7b",
    "mistral:7b",
    "llama3.1:8b",
]


def test_registry_covers_the_reference_sweep():
    # experiment/RunnerConfig.py:80 — the 7 Ollama models (the registry may
    # carry extra families beyond the reference sweep, e.g. the MoE one)
    assert set(REFERENCE_MODELS) <= set(MODEL_REGISTRY)


def test_param_counts_near_nameplate():
    """Architectural sanity: param counts should be close to the model names."""
    expected_b = {
        "qwen2:1.5b": 1.5,
        "gemma:2b": 2.5,
        "phi3:3.8b": 3.8,
        "gemma:7b": 8.5,
        "qwen2:7b": 7.6,
        "mistral:7b": 7.2,
        "llama3.1:8b": 8.0,
    }
    for name, exp in expected_b.items():
        got = get_model_config(name).params_count / 1e9
        assert abs(got - exp) / exp < 0.25, f"{name}: {got:.2f}B vs ~{exp}B"


def test_unknown_model_raises():
    with pytest.raises(KeyError, match="unknown model"):
        get_model_config("gpt5:900b")


@pytest.fixture(scope="module")
def tiny():
    cfg = get_model_config("qwen2:1.5b").tiny()
    return Transformer.initialise(cfg, seed=0, dtype=jnp.float32)


def test_forward_shapes_and_cache_update(tiny):
    cfg = tiny.cfg
    k_cache, v_cache = tiny.init_cache(1, 32, dtype=jnp.float32)
    tokens = jnp.array([[1, 5, 9, 13]], dtype=jnp.int32)
    hidden, k_cache, v_cache = tiny(tokens, jnp.int32(0), k_cache, v_cache)
    assert hidden.shape == (1, 4, cfg.d_model)
    assert k_cache.shape == (cfg.n_layers, 1, cfg.n_kv_heads, 32, cfg.d_head)
    # cache slots 0..3 written, rest untouched (zeros)
    assert not np.allclose(np.asarray(k_cache[:, :, :, :4]), 0.0)
    np.testing.assert_allclose(np.asarray(k_cache[:, :, :, 4:]), 0.0)
    logits = logits_for(tiny.params, cfg, hidden[:, -1])
    assert logits.shape == (1, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_incremental_decode_matches_full_prefill(tiny):
    """The golden parity test: feeding tokens one at a time through the cache
    must reproduce the hidden states of a single full-prompt pass."""
    cfg = tiny.cfg
    toks = jnp.array([[3, 7, 11, 2, 19, 23]], dtype=jnp.int32)
    s = toks.shape[1]

    k_full, v_full = tiny.init_cache(1, 16, dtype=jnp.float32)
    hidden_full, _, _ = tiny(toks, jnp.int32(0), k_full, v_full)

    k_inc, v_inc = tiny.init_cache(1, 16, dtype=jnp.float32)
    last_hidden = []
    for i in range(s):
        h, k_inc, v_inc = tiny(toks[:, i : i + 1], jnp.int32(i), k_inc, v_inc)
        last_hidden.append(h[:, 0])
    np.testing.assert_allclose(
        np.stack([np.asarray(h) for h in last_hidden], axis=1),
        np.asarray(hidden_full),
        atol=1e-4,
    )


def test_chunked_prefill_matches_full(tiny):
    """Prefill in two chunks (offset continuation) == one-shot prefill."""
    toks = jnp.array([[3, 7, 11, 2, 19, 23, 29, 31]], dtype=jnp.int32)
    k1, v1 = tiny.init_cache(1, 16, dtype=jnp.float32)
    full, _, _ = tiny(toks, jnp.int32(0), k1, v1)
    k2, v2 = tiny.init_cache(1, 16, dtype=jnp.float32)
    h_a, k2, v2 = tiny(toks[:, :5], jnp.int32(0), k2, v2)
    h_b, k2, v2 = tiny(toks[:, 5:], jnp.int32(5), k2, v2)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(h_a), np.asarray(h_b)], axis=1),
        np.asarray(full),
        atol=1e-4,
    )


@pytest.mark.parametrize("name", ["gemma:2b", "mistral:7b", "qwen2:7b"])
def test_all_family_variants_run(name):
    """Each family's structural quirks (GQA/MQA, gelu, gemma norm, qkv bias,
    tied embeddings) execute and produce finite outputs."""
    cfg = get_model_config(name).tiny()
    tf = Transformer.initialise(cfg, seed=1, dtype=jnp.float32)
    k_cache, v_cache = tf.init_cache(1, 8, dtype=jnp.float32)
    tokens = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    hidden, k_cache, v_cache = tf(tokens, jnp.int32(0), k_cache, v_cache)
    logits = logits_for(tf.params, cfg, hidden[:, -1])
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_tokenizer_round_trip():
    tok = ByteTokenizer()
    text = "In 100 words, please give me information about TPUs. émojis: ✨"
    ids = tok.encode(text)
    assert ids[0] == ByteTokenizer.BOS_ID
    assert tok.decode(ids) == text
    assert max(ids) < tok.vocab_size


def test_forward_per_sequence_offsets_match_single_rows():
    """Batched decode with a [B] offset vector must equal running each row
    alone with its scalar offset (the property generate_batch builds on)."""
    import dataclasses as _dc

    import numpy as np
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        Transformer,
        forward,
    )

    cfg = get_model_config("qwen2:1.5b").tiny()
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    t_len = 24
    rng = jax.random.PRNGKey(0)
    offsets = [3, 7, 11]
    b = len(offsets)

    # per-row caches with distinct valid prefixes
    kc, vc = tf.init_cache(b, t_len, dtype=jnp.float32)
    kc = jax.random.normal(rng, kc.shape, dtype=jnp.float32) * 0.1
    vc = jax.random.normal(jax.random.PRNGKey(1), vc.shape, dtype=jnp.float32) * 0.1
    tokens = jnp.asarray([[5], [9], [13]], dtype=jnp.int32)

    hidden_b, kb, vb = forward(
        tf.params, cfg, tokens, jnp.asarray(offsets, dtype=jnp.int32), kc, vc
    )

    for r, off in enumerate(offsets):
        hidden_1, k1, v1 = forward(
            tf.params,
            cfg,
            tokens[r : r + 1],
            jnp.int32(off),
            kc[:, r : r + 1],
            vc[:, r : r + 1],
        )
        np.testing.assert_allclose(
            np.asarray(hidden_b[r]), np.asarray(hidden_1[0]), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(kb[:, r]), np.asarray(k1[:, 0]), rtol=2e-5, atol=2e-5
        )
