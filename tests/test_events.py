"""Event bus: ordered multi-subscriber dispatch and data merging."""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.events import (
    EventBus,
    LifecycleEvent as E,
)


def test_multi_subscriber_order_preserved():
    bus = EventBus()
    calls = []
    bus.subscribe(E.START_RUN, lambda: calls.append("first"))
    bus.subscribe(E.START_RUN, lambda: calls.append("second"))
    results = bus.raise_event(E.START_RUN)
    assert calls == ["first", "second"]
    assert len(results) == 2


def test_unsubscribed_event_returns_empty_list():
    assert EventBus().raise_event(E.INTERACT) == []


def test_args_passed_through():
    bus = EventBus()
    seen = []
    bus.subscribe(E.BEFORE_RUN, lambda ctx: seen.append(ctx))
    bus.raise_event(E.BEFORE_RUN, "ctx-sentinel")
    assert seen == ["ctx-sentinel"]


def test_unsubscribe():
    bus = EventBus()
    cb = lambda: "x"  # noqa: E731
    bus.subscribe(E.INTERACT, cb)
    bus.unsubscribe(E.INTERACT, cb)
    assert bus.raise_event(E.INTERACT) == []


def test_raise_and_merge_later_wins():
    bus = EventBus()
    bus.subscribe(E.POPULATE_RUN_DATA, lambda: {"a": 1, "b": 1})
    bus.subscribe(E.POPULATE_RUN_DATA, lambda: None)
    bus.subscribe(E.POPULATE_RUN_DATA, lambda: {"b": 2})
    assert bus.raise_and_merge(E.POPULATE_RUN_DATA) == {"a": 1, "b": 2}


def test_raise_and_merge_all_none_is_none():
    bus = EventBus()
    bus.subscribe(E.POPULATE_RUN_DATA, lambda: None)
    assert bus.raise_and_merge(E.POPULATE_RUN_DATA) is None


def test_raise_and_merge_rejects_non_dict():
    bus = EventBus()
    bus.subscribe(E.POPULATE_RUN_DATA, lambda: 42)
    with pytest.raises(TypeError, match="expected dict"):
        bus.raise_and_merge(E.POPULATE_RUN_DATA)
