"""Generation backends: fake determinism, JAX engine end-to-end on tiny models."""

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    GEN_BUCKETS,
    PROMPT_BUCKETS,
    JaxEngine,
    _bucket,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)


def test_fake_backend_is_deterministic():
    be = FakeBackend()
    req = GenerationRequest(model="m", prompt="hello", max_new_tokens=16)
    r1, r2 = be.generate(req), be.generate(req)
    assert r1.tokens == r2.tokens and r1.text == r2.text
    r3 = be.generate(
        GenerationRequest(model="m", prompt="hello", max_new_tokens=16, seed=1)
    )
    assert r3.tokens != r1.tokens
    assert r1.generated_tokens == 16
    assert r1.tokens_per_s > 0


def test_bucket_rounding():
    assert _bucket(1, PROMPT_BUCKETS) == 32
    assert _bucket(33, PROMPT_BUCKETS) == 64
    assert _bucket(2048, GEN_BUCKETS) == 2048
    with pytest.raises(ValueError, match="exceeds"):
        _bucket(99999, GEN_BUCKETS)


@pytest.fixture(scope="module")
def engine():
    registry = {
        "tiny-a": get_model_config("qwen2:1.5b").tiny(),
        "tiny-gemma": get_model_config("gemma:2b").tiny(),
    }
    return JaxEngine(registry=registry, dtype=jnp.float32)


def test_jax_engine_generates(engine):
    req = GenerationRequest(model="tiny-a", prompt="hello tpu", max_new_tokens=12)
    result = engine.generate(req)
    assert result.generated_tokens <= 12
    assert len(result.tokens) == result.generated_tokens
    assert result.prompt_tokens == len("hello tpu".encode()) + 1
    assert result.prefill_s > 0 and result.decode_s > 0
    assert all(0 <= t < engine.registry["tiny-a"].vocab_size for t in result.tokens)


def test_jax_engine_greedy_is_deterministic(engine):
    req = GenerationRequest(model="tiny-a", prompt="abc", max_new_tokens=10)
    assert engine.generate(req).tokens == engine.generate(req).tokens


def test_jax_engine_seed_changes_sampled_output(engine):
    r0 = engine.generate(
        GenerationRequest("tiny-a", "abc", 24, temperature=1.5, seed=0)
    )
    r1 = engine.generate(
        GenerationRequest("tiny-a", "abc", 24, temperature=1.5, seed=1)
    )
    assert r0.tokens != r1.tokens


def test_jax_engine_compile_cache_reused(engine):
    # same buckets → same compiled callables
    engine.generate(GenerationRequest("tiny-a", "xy", 10))
    n_prefill = len(engine._prefill_cache)
    n_decode = len(engine._decode_cache)
    engine.generate(GenerationRequest("tiny-a", "different prompt!", 12))
    assert len(engine._prefill_cache) == n_prefill
    assert len(engine._decode_cache) == n_decode
    # a not-yet-seen generation bucket compiles one more decode fn
    engine.generate(GenerationRequest("tiny-a", "xy", 60))
    assert len(engine._decode_cache) == n_decode + 1


def test_jax_engine_multiple_families(engine):
    r = engine.generate(GenerationRequest("tiny-gemma", "hi", 8))
    assert r.generated_tokens <= 8


def test_jax_engine_generates_exactly_max_new_without_eos(engine):
    """The decode loop must run exactly the requested steps, not the bucket
    (timing/energy would otherwise include unrequested work)."""
    r = engine.generate(
        GenerationRequest("tiny-a", "count", 11, stop_at_eos=False)
    )
    assert r.generated_tokens == 11


def test_jax_engine_rejects_overflowing_cache(engine):
    with pytest.raises(ValueError, match="max_seq_len"):
        # tiny max_seq_len is 256; 32-prompt + 256-gen buckets exceed it
        engine.generate(GenerationRequest("tiny-a", "x", 250))


def test_warmup_compiles_once_and_resets_on_unload():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config as gmc,
    )

    eng = JaxEngine(registry={"t": gmc("qwen2:1.5b").tiny()}, dtype=jnp.float32)
    req = GenerationRequest("t", "warm me", 10)
    eng.warmup(req)
    assert len(eng._warmed) == 1
    eng.warmup(req)  # no-op
    assert len(eng._warmed) == 1
    eng.unload_all()
    assert len(eng._warmed) == 0  # fresh load must re-warm


def test_jax_engine_unload(engine_factory=None):
    registry = {"tiny-a": get_model_config("qwen2:1.5b").tiny()}
    eng = JaxEngine(registry=registry, dtype=jnp.float32)
    eng.generate(GenerationRequest("tiny-a", "x", 8))
    assert eng._models
    eng.unload_all()
    assert not eng._models and not eng._decode_cache


def test_generate_stream_matches_generate_greedy(engine):
    req = GenerationRequest("tiny-a", "stream me", max_new_tokens=20)
    mono = engine.generate(req)
    chunks = list(engine.generate_stream(req, chunk_tokens=4))
    assert chunks[-1].done and chunks[-1].result is not None
    streamed_tokens = [t for c in chunks[:-1] for t in c.tokens]
    assert streamed_tokens == mono.tokens
    assert chunks[-1].result.tokens == mono.tokens
    assert chunks[-1].result.text == mono.text
    # multiple incremental chunks actually happened
    assert len(chunks) >= 2


def test_generate_stream_matches_generate_sampled(engine):
    # rng threads through chunk boundaries → identical sample path
    req = GenerationRequest(
        "tiny-a", "abc", max_new_tokens=16, temperature=1.2, seed=3
    )
    mono = engine.generate(req)
    chunks = list(engine.generate_stream(req, chunk_tokens=5))
    assert [t for c in chunks[:-1] for t in c.tokens] == mono.tokens


def test_generate_with_top_p_and_repeat_penalty(engine):
    req = GenerationRequest(
        "tiny-a",
        "abc",
        max_new_tokens=12,
        temperature=1.0,
        top_p=0.9,
        repeat_penalty=1.3,
        seed=0,
    )
    r1, r2 = engine.generate(req), engine.generate(req)
    assert r1.tokens == r2.tokens  # deterministic under a fixed seed
    assert r1.generated_tokens >= 1
    # the static-flag variants get their own compiled decode entries
    assert any(k[3] or k[4] for k in engine._decode_cache)


def test_repeat_penalty_reduces_repetition(engine):
    base = GenerationRequest("tiny-a", "zzz", max_new_tokens=32)
    plain = engine.generate(base)
    penalised = engine.generate(
        GenerationRequest(
            "tiny-a", "zzz", max_new_tokens=32, repeat_penalty=1.8
        )
    )
    # greedy decode on random weights tends to cycle; the penalty must
    # produce at least as many distinct tokens
    assert len(set(penalised.tokens)) >= len(set(plain.tokens))


def test_warmup_compiles_stream_decode_bucket(engine):
    req = GenerationRequest("tiny-gemma", "warm", max_new_tokens=40)
    engine.warmup(req)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        DEFAULT_STREAM_CHUNK,
    )

    keys = {k[:2] for k in engine._decode_cache if k[0] == "tiny-gemma"}
    assert ("tiny-gemma", 64) in keys  # monolithic g_bucket
    assert ("tiny-gemma", DEFAULT_STREAM_CHUNK) in keys  # stream chunk bucket


def test_generate_batch_matches_single_greedy(engine):
    reqs = [
        GenerationRequest("tiny-a", "first prompt", max_new_tokens=10),
        GenerationRequest("tiny-a", "a second, rather longer prompt here", max_new_tokens=14),
        GenerationRequest("tiny-a", "3rd", max_new_tokens=6),
    ]
    singles = [engine.generate(r) for r in reqs]
    batch = engine.generate_batch(reqs)
    assert len(batch) == 3
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens
        assert b.text == s.text
        assert b.prompt_tokens == s.prompt_tokens


def test_generate_batch_matches_single_sampled(engine):
    reqs = [
        GenerationRequest(
            "tiny-a", "alpha", max_new_tokens=12, temperature=1.1, seed=5
        ),
        GenerationRequest(
            "tiny-a", "beta beta", max_new_tokens=12, temperature=0.8, seed=9
        ),
    ]
    singles = [engine.generate(r) for r in reqs]
    batch = engine.generate_batch(reqs)
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens


def test_generate_batch_mixed_knobs(engine):
    reqs = [
        GenerationRequest(
            "tiny-a", "x", max_new_tokens=8, temperature=1.0,
            top_p=0.9, seed=1,
        ),
        GenerationRequest(
            "tiny-a", "yy", max_new_tokens=8, temperature=0.0,
            repeat_penalty=1.5,
        ),
    ]
    singles = [engine.generate(r) for r in reqs]
    batch = engine.generate_batch(reqs)
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens


def test_generate_batch_validates_inputs(engine):
    with pytest.raises(ValueError, match="one model"):
        engine.generate_batch(
            [
                GenerationRequest("tiny-a", "x", max_new_tokens=4),
                GenerationRequest("tiny-gemma", "y", max_new_tokens=4),
            ]
        )
    with pytest.raises(ValueError, match="one top_k"):
        engine.generate_batch(
            [
                GenerationRequest("tiny-a", "x", max_new_tokens=4, top_k=3),
                GenerationRequest("tiny-a", "y", max_new_tokens=4, top_k=5),
            ]
        )
    assert engine.generate_batch([]) == []


def test_generate_batch_chunks_oversized_fleets(engine, monkeypatch):
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    # Force the memory-bounded width down to the floor so the seam logic
    # is exercised without compiling a 256-row loop on CPU.
    monkeypatch.setattr(je, "BATCH_KV_BUDGET_BYTES", 1)
    seam = je.BATCH_MIN_SPLIT_ROWS
    n = seam + 3
    reqs = [
        GenerationRequest("tiny-a", f"p{i}", max_new_tokens=4, seed=i)
        for i in range(n)
    ]
    batch = engine.generate_batch(reqs)
    assert len(batch) == n
    # spot-check parity at the chunk seam
    for i in (0, seam - 1, seam, n - 1):
        assert batch[i].tokens == engine.generate(reqs[i]).tokens
    # the two chunks decoded in separate, explicitly-tagged windows
    assert len({r.extras["decode_window"] for r in batch}) == 2


def test_generate_batch_width_is_memory_bounded(engine):
    """The sub-batch width tracks the estimated KV-cache footprint: tiny
    rows fit hundreds wide; max-context rows fall back to the known-safe
    floor (the round-3-era hard cap)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    engine.load_model("tiny-a")
    cfg = engine._models["tiny-a"].cfg
    short = [GenerationRequest("tiny-a", "p", max_new_tokens=4)] * 64
    ids = [[1, 2, 3]] * 64
    assert engine._max_batch_rows(cfg, short, ids) == je.BATCH_BUCKETS[-1]

    # a synthetic huge config: one row's cache alone exceeds the budget →
    # the floor wins (never refuse, never split below the known-safe cap)
    import dataclasses

    big = dataclasses.replace(
        cfg, n_layers=4000, d_head=4096, max_seq_len=100000
    )
    long_req = [GenerationRequest("tiny-a", "p", max_new_tokens=2048)]
    assert (
        engine._max_batch_rows(big, long_req, [[1] * 900])
        == je.BATCH_MIN_SPLIT_ROWS
    )


def test_max_batch_rows_paged_estimates_are_mode_aware(monkeypatch):
    """The paged estimate differs by mode and must not over-bill: the
    first dual-engine bench used one conservative factor for both paged
    modes, billed stacked rows ~3× their real bytes, and silently split
    a '128-row' fleet at 64 — re-creating the decode-window artifact in
    a fresh measurement (docs/PERF.md)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    paged = je.JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    paged.load_model("tiny")
    cfg = paged._models["tiny"].cfg
    reqs = [GenerationRequest("tiny", "p", max_new_tokens=16)] * 8
    ids = [[1, 2, 3]] * 8

    legacy = paged._max_batch_rows(cfg, reqs, ids)  # CPU: no kernels
    monkeypatch.setattr(
        je.JaxEngine,
        "_paged_decode_attention",
        lambda self, c=None: (lambda *a, **k: None),
    )
    stacked = paged._max_batch_rows(cfg, reqs, ids)
    # tiny shapes: everything fits the widest bucket in every mode
    assert legacy == stacked == je.BATCH_BUCKETS[-1]

    # The estimate now bills each mode its ACTUAL allocation
    # (per-row pages, chunk-level pow2 pool rounding — PR 1): a budget
    # set exactly between a mode's own 64- and 128-row chunk needs must
    # admit exactly 64 in that mode. Checked for BOTH modes — stacked
    # bills prompt-only pages (at the lane-padded head dim) + side
    # columns, legacy bills prompt + budget pages at the raw head dim.
    wide = [
        GenerationRequest("tiny", "p", max_new_tokens=128)
    ] * 128
    wide_ids = [[1, 2, 3]] * 128
    g_bucket = je._bucket(128, je.GEN_BUCKETS)
    for is_stacked in (True, False):
        pages_per_row = 1 if is_stacked else -(-(3 + 128) // 128)
        rows_pages = [pages_per_row] * 128
        need64 = paged._paged_chunk_bytes(
            cfg, rows_pages[:64], 64, g_bucket, is_stacked
        )
        need128 = paged._paged_chunk_bytes(
            cfg, rows_pages, 128, g_bucket, is_stacked
        )
        assert need64 < need128
        monkeypatch.setattr(
            je, "BATCH_KV_BUDGET_BYTES", (need64 + need128) // 2
        )
        monkeypatch.setattr(
            je.JaxEngine,
            "_paged_decode_attention",
            (lambda self, c=None: (lambda *a, **k: None))
            if is_stacked
            else (lambda self, c=None: None),
        )
        assert paged._max_batch_rows(cfg, wide, wide_ids) == 64, is_stacked


def test_generate_batch_mixed_top_p_rows_stay_bit_identical(engine):
    # a sampled row with top_p disabled next to a top_p row: the disabled
    # row's draw must not be perturbed by the batch-wide nucleus filter
    reqs = [
        GenerationRequest(
            "tiny-a", "nucleus", max_new_tokens=10, temperature=1.0,
            top_p=0.8, seed=2,
        ),
        GenerationRequest(
            "tiny-a", "free", max_new_tokens=10, temperature=1.3, seed=7,
        ),  # top_p = 1.0 (disabled)
    ]
    singles = [engine.generate(r) for r in reqs]
    batch = engine.generate_batch(reqs)
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens


def test_chunked_prefill_matches_single_chunk(monkeypatch):
    """Force tiny prefill chunks: output must be identical to the
    single-chunk path (the flash/jnp prefill handles offset > 0)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    registry = {"tiny-c": get_model_config("qwen2:1.5b").tiny(max_seq_len=512)}
    prompt = "a moderately long prompt " * 8  # ~200 byte-tokens
    req = GenerationRequest("tiny-c", prompt, max_new_tokens=12)

    plain = JaxEngine(registry=registry, dtype=jnp.float32).generate(req)
    monkeypatch.setattr(je, "PREFILL_CHUNK", 64)
    chunked_engine = JaxEngine(registry=registry, dtype=jnp.float32)
    chunked = chunked_engine.generate(req)
    assert chunked.tokens == plain.tokens
    assert chunked.text == plain.text
    # several prefill chunk compilations actually happened
    assert len(chunked_engine._prefill_cache) >= 2


def test_long_prompt_beyond_largest_bucket(monkeypatch):
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    monkeypatch.setattr(je, "PREFILL_CHUNK", 64)
    registry = {"tiny-c": get_model_config("qwen2:1.5b").tiny(max_seq_len=512)}
    engine = JaxEngine(registry=registry, dtype=jnp.float32)
    prompt = "x" * 300  # > PREFILL_CHUNK once chunking is forced
    r = engine.generate(
        GenerationRequest("tiny-c", prompt, max_new_tokens=8)
    )
    assert r.prompt_tokens == 301  # bos + 300 bytes
    assert r.generated_tokens >= 1


def test_prefix_cache_exact_and_partial_hits():
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    cold = JaxEngine(registry=registry, dtype=jnp.float32)
    warm = JaxEngine(registry=registry, dtype=jnp.float32, prefix_cache_size=4)

    sys_prompt = "You are a helpful assistant. "
    r_a = GenerationRequest("tiny-p", sys_prompt + "Question A?", max_new_tokens=10)
    r_b = GenerationRequest("tiny-p", sys_prompt + "Question A? And B too?", max_new_tokens=10)

    # identical outputs with and without the cache, for exact re-ask and
    # prefix-extension
    assert warm.generate(r_a).tokens == cold.generate(r_a).tokens
    assert warm.generate(r_a).tokens == cold.generate(r_a).tokens  # exact hit
    assert warm.generate(r_b).tokens == cold.generate(r_b).tokens  # partial hit
    assert len(warm._prefix_cache["tiny-p"]) >= 2


def test_prefix_cache_lru_eviction():
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(registry=registry, dtype=jnp.float32, prefix_cache_size=2)
    for i in range(4):
        engine.generate(
            GenerationRequest("tiny-p", f"prompt number {i}", max_new_tokens=4)
        )
    assert len(engine._prefix_cache["tiny-p"]) == 2


def test_prefix_cache_byte_cap_evicts_lru(monkeypatch):
    """The prefix cache is capped by BYTES across all models (VERDICT
    round-2 item 6): cached KV is device memory and an entry count says
    nothing about its size."""
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(
        registry=registry, dtype=jnp.float32, prefix_cache_size=8
    )
    # measure with a prompt of the same length as the test prompts below
    # (entry bytes scale with prompt tokens)
    engine.generate(
        GenerationRequest("tiny-p", "prompt number 9", max_new_tokens=4)
    )
    one_entry = engine._prefix_bytes()
    assert one_entry > 0
    # cap at ~2.5 entries: storing 4 must keep only 2
    engine2 = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        prefix_cache_size=8,
        prefix_cache_bytes=int(2.5 * one_entry),
    )
    for i in range(4):
        engine2.generate(
            GenerationRequest("tiny-p", f"prompt number {i}", max_new_tokens=4)
        )
    assert engine2._prefix_bytes() <= int(2.5 * one_entry)
    kept = list(engine2._prefix_cache["tiny-p"])
    assert len(kept) == 2
    # the survivors are the most recently used (LRU went first)
    tok = engine2._tokenizer_for("tiny-p")
    assert kept == [
        tuple(tok.encode("prompt number 2")),
        tuple(tok.encode("prompt number 3")),
    ]


def test_prefix_kv_evicted_before_model_load_exceeds_budget(monkeypatch):
    """Allocation accounting sees cached prompt KV: a model load that
    would exceed the budget evicts prefix entries FIRST (pure recompute),
    and only then resident weights (VERDICT round-2 item 6)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import memory as mem
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    registry = {
        "a": get_model_config("qwen2:1.5b").tiny(),
        "b": get_model_config("gemma:2b").tiny(),
    }
    one = estimate_weight_bytes(registry["a"], None, 4)
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)
    eng = JaxEngine(
        registry=registry, dtype=jnp.float32, prefix_cache_size=8
    )
    # a long prompt → a large cached-prefix KV entry (121 ids → bucket 128;
    # within tiny()'s max_seq_len alongside the 16-token generation bucket)
    eng.generate(
        GenerationRequest("a", "x" * 120, max_new_tokens=4)
    )
    prefix_bytes = eng._prefix_bytes()
    assert prefix_bytes > 0
    # budget: both models' weights fit ONLY if the prefix KV goes
    both = one + estimate_weight_bytes(registry["b"], None, 4)
    monkeypatch.setenv(
        "TPU_ALLOC_BUDGET_BYTES", str(both + prefix_bytes // 2)
    )
    eng.load_model("b")
    # prefix evicted, BOTH models still resident (weights were spared)
    assert eng._prefix_bytes() < prefix_bytes
    assert "a" in eng._models and "b" in eng._models


def test_prefix_cache_byte_cap_alone_enables_cache():
    """A byte cap without an entry cap must still enable the cache (not
    be silently inert)."""
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        prefix_cache_bytes=64 * 1024 * 1024,
    )
    engine.generate(GenerationRequest("tiny-p", "hello", max_new_tokens=4))
    assert engine._prefix_bytes() > 0


def test_prefix_cache_disabled_by_default():
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(registry=registry, dtype=jnp.float32)
    engine.generate(GenerationRequest("tiny-p", "hello", max_new_tokens=4))
    assert engine._prefix_cache == {}


def test_prefix_cache_partial_hit_near_cache_boundary():
    """Review repro: a cached 60-token prompt extended by 2 tokens would
    re-chunk past cache_len (tail bucket rounding) and the clamped write
    would corrupt the prefix — the hit must shrink instead."""
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    cold = JaxEngine(registry=registry, dtype=jnp.float32)
    warm = JaxEngine(registry=registry, dtype=jnp.float32, prefix_cache_size=4)
    p60 = "x" * 59  # +BOS = 60 tokens
    p62 = "x" * 61  # +BOS = 62 tokens, shares the 60-token prefix
    r60 = GenerationRequest("tiny-p", p60, max_new_tokens=16)
    r62 = GenerationRequest("tiny-p", p62, max_new_tokens=16)
    warm.generate(r60)  # seeds the cache with the 60-token prefix
    assert warm.generate(r62).tokens == cold.generate(r62).tokens


def test_prefix_cache_rejects_negative_size():
    with pytest.raises(ValueError, match="prefix_cache_size"):
        JaxEngine(prefix_cache_size=-1)


def test_prefix_cache_entries_store_only_prompt_region():
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(registry=registry, dtype=jnp.float32, prefix_cache_size=2)
    engine.generate(GenerationRequest("tiny-p", "abcde", max_new_tokens=64))
    (k, v, _, _stamp), = engine._prefix_cache["tiny-p"].values()
    assert k.shape[3] == 6  # bos + 5 bytes, not prompt_bucket + gen_bucket


def test_stop_strings_truncate_output(engine):
    # find a sampled generation with enough text to cut (random weights can
    # emit ids that decode to nothing)
    base = full = None
    for seed in range(8):
        cand = GenerationRequest(
            "tiny-a", "halt here", max_new_tokens=24, temperature=0.8,
            seed=seed,
        )
        r = engine.generate(cand)
        if len(r.text) >= 4:
            base, full = cand, r
            break
    assert full is not None, "no seed produced 4+ chars of text"
    stop_str = full.text[2:4]
    import dataclasses as _dc

    stopped = engine.generate(_dc.replace(base, stop=(stop_str,)))
    assert stop_str not in stopped.text
    assert stopped.text == full.text[: full.text.find(stop_str)]
    assert stopped.generated_tokens == len(stopped.tokens)
    # streamed output agrees with the non-streamed stop cut
    chunks = list(
        engine.generate_stream(_dc.replace(base, stop=(stop_str,)), chunk_tokens=4)
    )
    streamed = "".join(c.text for c in chunks[:-1])
    assert streamed == stopped.text
    assert chunks[-1].result.text == stopped.text


def test_stop_strings_no_match_is_identity(engine):
    req = GenerationRequest(
        "tiny-a", "no stops", max_new_tokens=12, stop=(" NEVER ",)
    )
    plain = engine.generate(
        GenerationRequest("tiny-a", "no stops", max_new_tokens=12)
    )
    assert engine.generate(req).tokens == plain.tokens


def test_stop_string_spanning_chunks_does_not_leak_prefix(engine):
    """A stop string split across chunk boundaries must not leak its first
    characters into the stream (prefix holdback)."""
    import dataclasses as _dc

    base = None
    for seed in range(10):
        cand = GenerationRequest(
            "tiny-a", "span", max_new_tokens=24, temperature=0.9, seed=seed
        )
        r = engine.generate(cand)
        if len(r.text) >= 8:
            base, full = cand, r
            break
    assert base is not None
    stop_str = full.text[4:7]  # 3 chars, will straddle chunk_tokens=2 decode
    stopped = engine.generate(_dc.replace(base, stop=(stop_str,)))
    chunks = list(
        engine.generate_stream(_dc.replace(base, stop=(stop_str,)), chunk_tokens=2)
    )
    streamed = "".join(c.text for c in chunks[:-1])
    assert streamed == stopped.text == chunks[-1].result.text
    assert stop_str not in streamed


def test_stop_request_does_not_burn_full_budget(engine):
    """generate() with a stop hit must not decode the whole token budget
    (it would measure energy for discarded work)."""
    import dataclasses as _dc

    base = None
    for seed in range(10):
        cand = GenerationRequest(
            "tiny-a", "budget", max_new_tokens=128, temperature=0.9, seed=seed
        )
        r = engine.generate(cand)
        if len(r.text) >= 6:
            base, full = cand, r
            break
    assert base is not None
    stop_str = full.text[2:4]
    stopped = engine.generate(_dc.replace(base, stop=(stop_str,)))
    # streaming chunk granularity: the decode stops within ~2 chunks of
    # the hit, nowhere near the 128-token budget
    assert stopped.generated_tokens < 64


def test_empty_stop_string_rejected():
    with pytest.raises(ValueError, match="stop"):
        GenerationRequest("m", "x", max_new_tokens=4, stop=("",))


def test_empty_prompt_encoding_rejected(engine):
    """A tokenizer that yields zero prompt ids (HF checkpoint with no BOS +
    empty prompt) must fail cleanly, not sample from an all-pad prefill."""

    class NoBosTokenizer:
        pad_id = 0
        eos_id = 2
        vocab_size = 16

        def encode(self, text, add_bos=True):
            return []  # no BOS, empty prompt

        def decode(self, ids):
            return ""

    engine.load_model("tiny-a")
    engine._tokenizers["tiny-a"] = NoBosTokenizer()
    try:
        with pytest.raises(ValueError, match="zero tokens"):
            engine.generate(GenerationRequest("tiny-a", "", max_new_tokens=4))
    finally:
        del engine._tokenizers["tiny-a"]


def test_protocol_num_predict_cap_matches_engine_buckets():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol

    assert protocol.MAX_NUM_PREDICT == GEN_BUCKETS[-1]


def test_apply_stop_binary_search_matches_linear_scan():
    """The binary-searched token cut must equal the original linear scan's
    (smallest prefix whose decode covers the kept text) for a prefix-stable
    tokenizer, across cut positions."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        _apply_stop,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tokenizer import (
        ByteTokenizer,
    )

    tok = ByteTokenizer()
    text = "the quick brown fox jumps over the lazy dog"
    tokens = tok.encode(text, add_bos=False)
    assert tok.decode(tokens) == text
    for stop_str in ("quick", " fox", "dog", "t", "o"):
        got_tokens, got_text = _apply_stop(list(tokens), text, tok, (stop_str,))
        kept = text[: text.find(stop_str)]
        assert got_text == kept
        # linear-scan reference
        k, acc = 0, ""
        while k < len(tokens) and len(acc) < len(kept):
            k += 1
            acc = tok.decode(tokens[:k])
        assert got_tokens == tokens[:k]


def test_apply_stop_fixup_repairs_non_monotone_decode():
    """Cleanup/merging tokenizers make decode length only approximately
    monotone in prefix length — the bisect can land positions off. The
    bounded fix-up must restore the smallest covering prefix (ADVICE
    round-2: wire-visible token counts were drifting)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        _apply_stop,
    )

    class WeirdTok:
        # decode length by prefix length: dips at 2 and 4 steer the bisect
        # to land at 5; the true smallest covering prefix (len >= 2) is 3.
        lens = [0, 1, 1, 4, 1, 4]

        def decode(self, ids):
            return "abZd"[: self.lens[len(ids)]]

    tokens = [10, 11, 12, 13, 14]
    text = "abZd"  # full decode; stop at index 2 → kept = "ab"
    got_tokens, got_text = _apply_stop(tokens, text, WeirdTok(), ("Z",))
    assert got_text == "ab"
    assert got_tokens == tokens[:3]


def test_per_model_quantize_dict():
    """One engine can serve different models at different quant modes
    (small = int8 for speed, large = int4 for capacity)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        is_quantized,
    )

    registry = {
        "tiny-a": get_model_config("qwen2:1.5b").tiny(),
        "tiny-gemma": get_model_config("gemma:2b").tiny(),
    }
    eng = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        quantize={"tiny-a": "int8", "default": None},
    )
    assert eng._quant_mode("tiny-a") == "int8"
    assert eng._quant_mode("tiny-gemma") is None
    eng.load_model("tiny-a")
    eng.load_model("tiny-gemma")
    assert is_quantized(eng._models["tiny-a"].params["wq"])
    assert not is_quantized(eng._models["tiny-gemma"].params["wq"])
    r = eng.generate(GenerationRequest("tiny-a", "hi", max_new_tokens=6))
    assert r.generated_tokens <= 6
    with pytest.raises(ValueError, match="unsupported quantize"):
        JaxEngine(registry=registry, quantize={"tiny-a": "int3"})


def test_install_model_reinstall_evicts_stale_state():
    """Re-installing a model name must drop compiled fns, prefix KV and
    warm markers derived from the old weights/config."""
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        init_params,
    )

    cfg_old = get_model_config("qwen2:1.5b").tiny()
    cfg_new = get_model_config("gemma:2b").tiny()  # different architecture
    eng = JaxEngine(registry={}, dtype=jnp.float32, prefix_cache_size=2)
    eng.install_model(
        "m", cfg_old, init_params(cfg_old, jax.random.PRNGKey(0), jnp.float32)
    )
    r_old = eng.generate(GenerationRequest("m", "same prompt", 8))
    assert eng._prefill_cache and eng._prefix_cache.get("m")
    eng.install_model(
        "m", cfg_new, init_params(cfg_new, jax.random.PRNGKey(1), jnp.float32)
    )
    assert not eng._prefix_cache.get("m")
    assert not [k for k in eng._prefill_cache if "m" in k]
    assert not [k for k in eng._decode_cache if "m" in k]
    r_new = eng.generate(GenerationRequest("m", "same prompt", 8))
    # different config + weights → decode runs the NEW architecture
    assert eng._models["m"].cfg == cfg_new
    assert r_new.tokens != r_old.tokens


def test_lru_weight_eviction_under_allocation_budget(monkeypatch):
    """When total resident weights would overflow the allocation budget,
    the least-recently-used model's weights are evicted; compiled state
    survives, so a reload serves the same compiled fns."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import memory as mem
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    registry = {
        "a": get_model_config("qwen2:1.5b").tiny(),
        "b": get_model_config("gemma:2b").tiny(),
    }
    one = estimate_weight_bytes(registry["a"], None, 4)
    # headroom dwarfs tiny models; shrink it so the budget math is exact
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)
    monkeypatch.setenv("TPU_ALLOC_BUDGET_BYTES", str(int(1.7 * one)))
    eng = JaxEngine(registry=registry, dtype=jnp.float32)
    eng.generate(GenerationRequest("a", "warm a", 6))
    n_decode = len(eng._decode_cache)
    eng.load_model("b")  # must evict a's weights to fit
    assert "a" not in eng._models and "b" in eng._models
    assert len(eng._decode_cache) == n_decode  # compiled state kept
    # transparent reload: generating on the evicted model works and reuses
    # the compiled decode fn (no new cache entries)
    r = eng.generate(GenerationRequest("a", "warm a", 6))
    assert r.generated_tokens == 6
    assert len(eng._decode_cache) == n_decode
    assert "b" not in eng._models  # b became the LRU victim in turn


def test_lru_recency_updated_on_use(monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import memory as mem
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    registry = {
        "a": get_model_config("qwen2:1.5b").tiny(),
        "b": get_model_config("gemma:2b").tiny(),
        "c": get_model_config("phi3:3.8b").tiny(),
    }
    one = estimate_weight_bytes(registry["a"], None, 4)
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)
    monkeypatch.setenv("TPU_ALLOC_BUDGET_BYTES", str(int(2.9 * one)))
    eng = JaxEngine(registry=registry, dtype=jnp.float32)
    eng.load_model("a")
    eng.load_model("b")
    eng.load_model("a")  # touch a → b becomes LRU
    eng.load_model("c")  # must evict b, not a
    assert "a" in eng._models and "c" in eng._models
    assert "b" not in eng._models


def test_auto_policy_engages_specialised_kernels_on_tpu(monkeypatch):
    """The "auto" attention policy's TPU side (unreachable on the CPU
    suite without a mock): specialised kernels engage for the int8-KV
    and paged cache representations while the plain path stays on XLA's
    fused attention (decode_attention None) — the measured round-4
    policy, docs/PERF.md."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    monkeypatch.setattr(
        JaxEngine, "_on_tpu_backend", staticmethod(lambda: True)
    )
    plain = JaxEngine(registry={"t": get_model_config("qwen2:1.5b").tiny()})
    assert plain._auto_attention
    assert plain.decode_attention is None  # plain cache: XLA fused
    assert plain._specialised_kernels_enabled()
    assert plain._paged_decode_attention() is not None

    kv = JaxEngine(kv_quantize="int8")
    assert (
        kv._decode_attention_for_cache(get_model_config("qwen2:1.5b"))
        is not None  # d_head 128: int8 kernel
    )
    assert (
        kv._decode_attention_for_cache(get_model_config("phi3:3.8b"))
        is not None  # d_head 96 engages too since the round-5 scales
        # BlockSpec fix (the round-4 trace abort was never the head dim)
    )


def _spy_prefill_calls(monkeypatch, engine):
    """Count invocations of compiled prefill fns (one per chunk/group)."""
    calls = []
    orig = engine._prefill_fn

    def spy(model, bucket, cache_len):
        fn = orig(model, bucket, cache_len)

        def wrapped(*a, **k):
            calls.append((bucket, cache_len))
            return fn(*a, **k)

        return wrapped

    monkeypatch.setattr(engine, "_prefill_fn", spy)
    return calls


def test_generate_batch_groups_same_bucket_prefills(monkeypatch, engine):
    """VERDICT round-4 missing #3: same-bucket prompts prefill as ONE
    padded [G, S] forward, not G sequential dispatches — while every
    row's tokens stay bit-identical to its solo generate()."""
    reqs = [
        GenerationRequest(
            "tiny-a", f"prompt number {i}", max_new_tokens=8,
            temperature=0.9, seed=100 + i,
        )
        for i in range(4)
    ]
    singles = [engine.generate(r) for r in reqs]
    calls = _spy_prefill_calls(monkeypatch, engine)
    batch = engine.generate_batch(reqs)
    assert len(calls) == 1  # one grouped prefill for all four rows
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens
    # grouped rows share the group's prefill window (the decode_s
    # convention applied to prefill)
    assert len({b.prefill_s for b in batch}) == 1


def test_generate_batch_mixed_buckets_one_prefill_per_group(
    monkeypatch, engine
):
    """Prompts spanning two buckets become two grouped prefills (not
    four solo ones), each row still solo-identical."""
    short = "tok " * 4
    long = "tok " * 12  # beyond the 32-token bucket, inside 64
    reqs = [
        GenerationRequest("tiny-a", short + "a", max_new_tokens=6),
        GenerationRequest("tiny-a", long + "b", max_new_tokens=6),
        GenerationRequest("tiny-a", short + "c", max_new_tokens=6),
        GenerationRequest("tiny-a", long + "d", max_new_tokens=6),
    ]
    singles = [engine.generate(r) for r in reqs]
    calls = _spy_prefill_calls(monkeypatch, engine)
    batch = engine.generate_batch(reqs)
    assert len(calls) == 2  # one per prompt bucket
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens


def test_generate_batch_grouped_prefill_with_prefix_cache():
    """Prefix-cache engines still produce solo-identical batches: hit
    rows take the solo path (device-copy prefill), misses group — and a
    grouped prefill does not populate the prefix cache (documented
    trade-off in _batch_states)."""
    registry = {"tiny-p": get_model_config("qwen2:1.5b").tiny()}
    warm = JaxEngine(registry=registry, dtype=jnp.float32, prefix_cache_size=4)
    cold = JaxEngine(registry=registry, dtype=jnp.float32)

    seed_req = GenerationRequest("tiny-p", "shared system prompt", max_new_tokens=4)
    warm.generate(seed_req)  # stores the prefix solo
    n_entries = len(warm._prefix_cache["tiny-p"])

    reqs = [
        GenerationRequest("tiny-p", "shared system prompt", max_new_tokens=6),
        GenerationRequest("tiny-p", "a fresh question", max_new_tokens=6),
        GenerationRequest("tiny-p", "another new ask", max_new_tokens=6),
    ]
    singles = [cold.generate(r) for r in reqs]
    batch = warm.generate_batch(reqs)
    for s, b in zip(singles, batch):
        assert b.tokens == s.tokens
    # grouped (miss) rows did not store prefixes; the solo hit row re-stored
    assert len(warm._prefix_cache["tiny-p"]) <= n_entries + 1


def test_batch_results_carry_explicit_decode_window_ids(engine):
    """Every generate_batch result carries extras["decode_window"] — the
    contract bench.py's distinct-window accounting relies on (float
    equality of decode_s silently miscounts windows; docs/PERF.md)."""
    reqs = [
        GenerationRequest("tiny-a", f"w{i}", max_new_tokens=4, seed=i)
        for i in range(3)
    ]
    batch = engine.generate_batch(reqs)
    wids = {r.extras["decode_window"] for r in batch}
    assert len(wids) == 1  # one chunk → one shared window id
    again = engine.generate_batch(reqs)
    assert {r.extras["decode_window"] for r in again} != wids  # fresh id


def test_assemble_rows_matches_naive_assembly_randomized():
    """Property test for the fused row assembly: for random mixtures of
    grouped and solo states, group sizes, member orderings and padding,
    _assemble_rows' gather+permutation output must equal the naive
    per-row construction (the pre-round-5 slice-and-concat semantics).
    The identity-skip fast paths make this worth fuzzing: they engage
    only for full in-order groups, and a wrong skip would scramble rows
    silently."""
    import numpy as np

    registry = {"tiny-a": get_model_config("qwen2:1.5b").tiny()}
    eng = JaxEngine(registry=registry, dtype=jnp.float32)
    rng = np.random.default_rng(7)

    for trial in range(12):
        n_groups = int(rng.integers(0, 3))
        groups = []
        for g in range(n_groups):
            gb = int(rng.choice([2, 4]))
            shared = {
                "first": jnp.asarray(
                    rng.integers(0, 99, gb), jnp.int32
                ),
                "presence": jnp.asarray(rng.random((gb, 5)) < 0.5),
                "rng": jnp.asarray(
                    rng.integers(0, 2**31, (gb, 2)), jnp.uint32
                ),
            }
            members = list(rng.permutation(gb))[: int(rng.integers(1, gb + 1))]
            groups.append((shared, members))
        n_solo = int(rng.integers(0 if n_groups else 1, 3))
        solo_vals = []
        for s in range(n_solo):
            solo_vals.append(
                {
                    "first": jnp.asarray(
                        rng.integers(0, 99, 1), jnp.int32
                    ),
                    "presence": jnp.asarray(rng.random((1, 5)) < 0.5),
                    "rng": jnp.asarray(
                        rng.integers(0, 2**31, 2), jnp.uint32
                    ),
                }
            )
        # interleave grouped and solo rows in a random global order
        entries = []
        for gi_, (shared, members) in enumerate(groups):
            for m in members:
                entries.append(("g", gi_, m))
        for si in range(n_solo):
            entries.append(("s", si, None))
        order = rng.permutation(len(entries))
        states = []
        for idx in order:
            kind, a, b_ = entries[idx]
            if kind == "g":
                states.append({"group": groups[a][0], "gi": int(b_)})
            else:
                states.append(dict(solo_vals[a]))
        n = len(states)
        b_bucket = _bucket(n, (1, 2, 4, 8, 16))
        asm = eng._assemble_rows(
            states, b_bucket, eng._row_field_specs(states)
        )
        # naive reference: per-row values + row-0 padding
        def naive(field, solo_key):
            rows = []
            for st in states:
                if "group" in st:
                    rows.append(np.asarray(st["group"][field])[st["gi"]])
                else:
                    v = np.asarray(st[solo_key])
                    rows.append(v[0] if field != "rng" else v)
            rows += [rows[0]] * (b_bucket - n)
            return np.stack(rows)

        np.testing.assert_array_equal(
            np.asarray(asm["first"]), naive("first", "first")
        )
        np.testing.assert_array_equal(
            np.asarray(asm["presence"]), naive("presence", "presence")
        )
        np.testing.assert_array_equal(
            np.asarray(asm["rng"]), naive("rng", "rng")
        )


def test_assemble_rows_identity_fast_paths():
    """Deterministic pin for _assemble_rows' two zero-copy skips, which
    the randomized trials rarely generate: ONE full group whose members
    appear in gi-order and fill the batch bucket exactly engages both
    the identity gather (members == range(gb)) and the identity take
    (perm == arange, no padding). A wrong skip scrambles rows silently —
    so the output is checked value-for-value, not just for shape."""
    import numpy as np

    registry = {"tiny-a": get_model_config("qwen2:1.5b").tiny()}
    eng = JaxEngine(registry=registry, dtype=jnp.float32)
    gb = 4
    shared = {
        "first": jnp.asarray([10, 11, 12, 13], jnp.int32),
        "presence": jnp.asarray(np.arange(gb * 5).reshape(gb, 5) % 3 == 0),
        "rng": jnp.asarray(
            np.arange(gb * 2).reshape(gb, 2), jnp.uint32
        ),
    }
    states = [{"group": shared, "gi": i} for i in range(gb)]
    asm = eng._assemble_rows(states, gb, eng._row_field_specs(states))
    np.testing.assert_array_equal(
        np.asarray(asm["first"]), np.asarray(shared["first"])
    )
    np.testing.assert_array_equal(
        np.asarray(asm["presence"]), np.asarray(shared["presence"])
    )
    np.testing.assert_array_equal(
        np.asarray(asm["rng"]), np.asarray(shared["rng"])
    )

    # and the NEAR-miss: same group with members reversed must NOT take
    # the identity path — rows come back in the reversed request order
    rev = [{"group": shared, "gi": gb - 1 - i} for i in range(gb)]
    asm_rev = eng._assemble_rows(rev, gb, eng._row_field_specs(rev))
    np.testing.assert_array_equal(
        np.asarray(asm_rev["first"]),
        np.asarray(shared["first"])[::-1],
    )
