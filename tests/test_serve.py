"""Serving layer: HTTP server + client backend round trips.

The reference has no loopback harness at all (SURVEY.md §4 — its "remote"
treatment needs a real second machine); these tests run the full
client→HTTP→server→backend path hermetically on localhost.
"""

import json
import threading

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
    RemoteHTTPBackend,
    RemoteServerError,
    backend_from_env,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


@pytest.fixture()
def server():
    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        models=["qwen2:1.5b", "gemma:2b"],
        quiet=True,
    )
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")


def test_protocol_round_trip():
    req = GenerationRequest(
        "m", "hello", max_new_tokens=7, temperature=0.5, top_k=3, seed=9
    )
    assert protocol.request_from_wire(protocol.request_to_wire(req)) == req
    result = FakeBackend().generate(req)
    back = protocol.result_from_wire(protocol.result_to_wire(result), req)
    assert back.tokens == result.tokens
    assert back.text == result.text
    assert back.generated_tokens == result.generated_tokens
    assert back.prefill_s == pytest.approx(result.prefill_s, abs=1e-6)
    assert back.decode_s == pytest.approx(result.decode_s, abs=1e-6)


def test_request_from_wire_defaults():
    req = protocol.request_from_wire({"model": "m", "prompt": "p"})
    assert req.max_new_tokens == 128
    assert req.temperature == 0.0
    with pytest.raises(ValueError):
        protocol.request_from_wire({"prompt": "no model"})


def test_health_and_tags(server, client):
    assert client.health()
    assert client.list_models() == ["qwen2:1.5b", "gemma:2b"]


def test_healthz_reports_scheduler_kind_and_inflight():
    """ISSUE 12 satellite: /healthz is the router's probe target — it
    must carry the scheduler kind and live queue/inflight counts."""
    import urllib.request

    srv = GenerationServer(
        FakeBackend(tokens_per_s=150.0, simulate_delay=True),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def healthz():
            with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
                return json.loads(r.read())

        idle = healthz()
        assert idle["status"] == "ok"
        assert idle["scheduler"] == "continuous"
        assert idle["inflight_rows"] == 0 and idle["queue_depth"] == 0
        assert idle["backend"] == "FakeBackend"
        # one long request in flight: the count rises, then drains
        cl = RemoteHTTPBackend(base)
        t = threading.Thread(
            target=lambda: cl.generate(
                GenerationRequest("m", "busy", max_new_tokens=96)
            )
        )
        t.start()
        import time as _time

        saw_inflight = False
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline and not saw_inflight:
            saw_inflight = healthz()["inflight_rows"] > 0
            _time.sleep(0.005)
        t.join(timeout=30)
        assert saw_inflight
        assert healthz()["inflight_rows"] == 0
    finally:
        srv.stop()


def test_healthz_works_under_telemetry_kill_switch(monkeypatch):
    """/healthz must answer while /metrics and /debug/* 404 (liveness
    cannot depend on observability)."""
    import urllib.error
    import urllib.request

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
        metrics as obs_metrics,
    )

    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    monkeypatch.setattr(obs_metrics, "_enabled", False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["status"] == "ok"
        assert body["scheduler"] == "continuous"
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"{base}/metrics", timeout=5)
        assert exc_info.value.code == 404
    finally:
        monkeypatch.setattr(obs_metrics, "_enabled", True)
        srv.stop()


def test_generate_round_trip(client):
    req = GenerationRequest("qwen2:1.5b", "In 100 words, tell me", 32)
    result = client.generate(req)
    # Same deterministic tokens the fake produces locally
    assert result.tokens == FakeBackend().generate(req).tokens
    assert result.generated_tokens == 32
    assert result.total_s > 0  # client wall time, not server-reported
    assert result.decode_s > 0


def test_unknown_model_is_404(client):
    with pytest.raises(RemoteServerError) as exc_info:
        client.generate(GenerationRequest("nope:13b", "hi", 4))
    assert exc_info.value.status == 404


def test_load_and_warmup(server, client):
    client.load_model("gemma:2b")
    assert server.backend.loaded.get("gemma:2b")
    client.warmup(GenerationRequest("gemma:2b", "warm", 4))  # no error


def test_bad_json_is_400():
    import urllib.error
    import urllib.request

    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/api/generate",
            data=b"not json",
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 400
    finally:
        srv.stop()


def test_concurrent_requests_serialised(server):
    """Generation is locked — concurrent posts all succeed (no interleaved
    backend state), matching the one-accelerator serving model."""
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    results = {}

    def go(seed):
        req = GenerationRequest("qwen2:1.5b", "topic", 16, seed=seed)
        results[seed] = client.generate(req)

    threads = [threading.Thread(target=go, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4
    for seed, result in results.items():
        expected = FakeBackend().generate(
            GenerationRequest("qwen2:1.5b", "topic", 16, seed=seed)
        )
        assert result.tokens == expected.tokens


def test_concurrent_mixed_length_requests_through_paged_batching():
    """End-to-end serving path of the paged KV pool: concurrent
    mixed-length HTTP posts coalesce through the scheduler into one paged
    batched decode, and each response equals a lone generate (the paged
    batch is token-identical per row)."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    backend = JaxEngine(registry=dict(registry), dtype=jnp.float32, paged_kv=True)
    srv = GenerationServer(
        backend,
        host="127.0.0.1",
        port=0,
        quiet=True,
        batch_window_ms=150,
        max_batch=4,
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        cases = [("short", 6), ("a much longer prompt here", 20), ("third", 12)]
        results = {}

        def go(i, prompt, n):
            results[i] = client.generate(
                GenerationRequest("tiny", prompt, max_new_tokens=n)
            )

        threads = [
            threading.Thread(target=go, args=(i, p, n))
            for i, (p, n) in enumerate(cases)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        solo = JaxEngine(registry=dict(registry), dtype=jnp.float32)
        for i, (p, n) in enumerate(cases):
            want = solo.generate(
                GenerationRequest("tiny", p, max_new_tokens=n)
            )
            assert results[i].tokens == want.tokens
    finally:
        srv.stop()


def test_concurrent_requests_served_through_grouped_prefill():
    """VERDICT round-5 directive #3 e2e: concurrent mixed-length requests
    coalescing in the server's continuous batching hit the GROUPED
    prefill (same-bucket rows prefill as one padded forward — counted
    via a prefill spy), and every response still equals a lone
    generate."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    backend = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    group_sizes = []
    orig = backend._prefill_fn

    def spy(model, bucket, cache_len):
        fn = orig(model, bucket, cache_len)

        def wrapped(params, tokens, *a, **k):
            group_sizes.append(int(tokens.shape[0]))
            return fn(params, tokens, *a, **k)

        return wrapped

    backend._prefill_fn = spy
    srv = GenerationServer(
        backend,
        host="127.0.0.1",
        port=0,
        quiet=True,
        batch_window_ms=300,
        max_batch=4,
        # window pinned: this test asserts the WINDOW path's grouped
        # prefill (all rows collected before one dispatch); under the
        # continuous default, companions arriving after the anchor's
        # session opens join via solo prefill — a different, also
        # parity-tested path (tests/test_stepped.py)
        scheduler="window",
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        # all four prompts land in the same 32-token bucket
        cases = [(f"question number {i}", 6 + 2 * i) for i in range(4)]
        results = {}

        def go(i, prompt, n):
            results[i] = client.generate(
                GenerationRequest("tiny", prompt, max_new_tokens=n)
            )

        threads = [
            threading.Thread(target=go, args=(i, p, n))
            for i, (p, n) in enumerate(cases)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        solo = JaxEngine(registry=dict(registry), dtype=jnp.float32)
        for i, (p, n) in enumerate(cases):
            want = solo.generate(
                GenerationRequest("tiny", p, max_new_tokens=n)
            )
            assert results[i].tokens == want.tokens
        # the batching window coalesced rows AND their prefill grouped:
        # at least one multi-row prefill ran (group of >= 2)
        assert max(group_sizes) >= 2, group_sizes
    finally:
        srv.stop()


def test_load_falls_back_to_generate_on_plain_ollama(server):
    """Against a server with no /api/load (real Ollama), load/warmup degrade
    to a 1-token generate instead of failing the run."""
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    orig = client._post

    def post_no_load(path, payload, timeout_s):
        if path == protocol.LOAD_PATH:
            raise RemoteServerError(404, "page not found")
        return orig(path, payload, timeout_s)

    client._post = post_no_load
    client.load_model("qwen2:1.5b")  # no raise
    client.warmup(GenerationRequest("qwen2:1.5b", "warm", 4))  # no raise


def test_remote_http_flops_use_local_registry(server, tmp_path):
    """Energy modelling for HTTP-remote runs uses the local model registry
    (a remote backend has no registry; flops must not degrade to 0)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import (
        RunContext,
    )

    url = f"http://127.0.0.1:{server.port}"
    config = LlmEnergyConfig(
        models=["qwen2:1.5b"],
        locations=["remote"],
        lengths=[100],
        repetitions=1,
        results_output_path=tmp_path,
        backends={"remote": RemoteHTTPBackend(url)},
    )
    context = RunContext(
        run_id="run_0_repetition_0",
        run_nr=1,
        total_runs=1,
        variation={"model": "qwen2:1.5b", "location": "remote", "length": 100},
        run_dir=tmp_path / "run_0_repetition_0",
        experiment_dir=tmp_path,
    )
    config.start_run(context)
    config.interact(context)
    assert context.scratch["generation_stats"]["flops"] > 0


def test_backend_from_env(monkeypatch, tmp_path):
    monkeypatch.chdir(tmp_path)
    # Register SERVER_IP with monkeypatch FIRST so teardown restores the
    # pre-test state even though load_dotenv mutates os.environ directly.
    monkeypatch.setenv("SERVER_IP", "placeholder")
    monkeypatch.delenv("SERVER_IP")
    assert backend_from_env() is None
    (tmp_path / ".env").write_text("SERVER_IP=10.0.0.5\n")
    backend = backend_from_env()
    assert backend is not None
    assert backend.base_url == "http://10.0.0.5:11434"
    monkeypatch.setenv("SERVER_IP", "http://host.example:9999")
    assert backend_from_env().base_url == "http://host.example:9999"


def test_experiment_remote_over_http(server, tmp_path):
    """End-to-end: the study config's remote treatment fetches over a real
    (loopback) HTTP boundary — the reference's architecture, hermetically."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.controller import (
        ExperimentController,
    )

    url = f"http://127.0.0.1:{server.port}"
    config = LlmEnergyConfig(
        models=["qwen2:1.5b"],
        locations=["remote"],
        lengths=[100],
        repetitions=1,
        results_output_path=tmp_path,
        cooldown_ms=0,
        backends={"remote": RemoteHTTPBackend(url)},
        shuffle=False,
    )
    ExperimentController(config).do_experiment()
    table = (config.experiment_path / "run_table.csv").read_text()
    assert "DONE" in table
    assert "remote" in table


def test_remote_url_constructor_builds_http_backend(server, tmp_path):
    """remote_url wires the HTTP client in before_experiment (health-checked,
    no generation)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )

    url = f"http://127.0.0.1:{server.port}"
    config = LlmEnergyConfig(
        models=["qwen2:1.5b"],
        locations=["remote"],
        lengths=[100],
        repetitions=1,
        results_output_path=tmp_path,
        remote_url=url,
    )
    config.before_experiment()
    backend = config._backends["remote"]
    assert isinstance(backend, RemoteHTTPBackend)
    assert backend.base_url == url


def test_unreachable_remote_url_fails_fast(tmp_path):
    """An unreachable serving host aborts in before_experiment, not hours
    into the sweep (127.0.0.1:9 is a closed port — connection refused)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import (
        ExperimentError,
    )

    config = LlmEnergyConfig(
        models=["qwen2:1.5b"],
        locations=["remote"],
        lengths=[100],
        repetitions=1,
        results_output_path=tmp_path,
        remote_url="http://127.0.0.1:9",
    )
    with pytest.raises(ExperimentError, match="unreachable"):
        config.before_experiment()


def test_load_respects_model_allowlist(server, client):
    """/api/load enforces --models like /api/generate (no loading excluded
    models into HBM via the load path). The rejection is 403, not 404 —
    the client reads a 404 from /api/load as "plain Ollama without this
    endpoint" and would fall back to a warm-up generate."""
    with pytest.raises(RemoteServerError) as exc_info:
        client.load_model("llama3.1:8b")  # not in server.models
    assert exc_info.value.status == 403


def test_stop_without_start_does_not_deadlock():
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.stop()  # must return, not block on the serve loop's shutdown event


def test_streaming_generate_round_trip(server, client):
    req = GenerationRequest("qwen2:1.5b", "stream please", max_new_tokens=12)
    mono = client.generate(req)
    chunks = list(client.generate_stream(req))
    assert chunks[-1].done and chunks[-1].result is not None
    final = chunks[-1].result
    assert "".join(c.text for c in chunks[:-1]) == mono.text
    assert final.text == mono.text
    assert final.generated_tokens == mono.generated_tokens
    assert final.tokens == mono.tokens
    assert final.total_s > 0


def test_streaming_unknown_model_is_clean_http_error(server, client):
    req = GenerationRequest("nope", "x", max_new_tokens=4)
    with pytest.raises(RemoteServerError) as exc_info:
        list(client.generate_stream(req))
    assert exc_info.value.status == 404


def test_protocol_round_trip_new_options():
    req = GenerationRequest(
        "m", "hello", max_new_tokens=7, temperature=0.5,
        top_k=3, top_p=0.85, repeat_penalty=1.2, seed=9,
    )
    assert protocol.request_from_wire(protocol.request_to_wire(req)) == req


def test_degenerate_sampling_options_rejected():
    with pytest.raises(ValueError, match="top_p"):
        GenerationRequest("m", "x", max_new_tokens=4, top_p=0.0)
    with pytest.raises(ValueError, match="repeat_penalty"):
        GenerationRequest("m", "x", max_new_tokens=4, repeat_penalty=0.0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        GenerationRequest("m", "x", max_new_tokens=0)
    # and over the wire they surface as a clean 400
    with pytest.raises(ValueError, match="top_p"):
        protocol.request_from_wire(
            {"model": "m", "prompt": "x", "options": {"top_p": 0}}
        )


def test_mid_stream_backend_failure_is_clean_error():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationChunk,
    )

    class ExplodingBackend(FakeBackend):
        def generate_stream(self, request):
            yield GenerationChunk(text="partial", tokens=[1])
            raise RuntimeError("decode blew up")

    srv = GenerationServer(
        ExplodingBackend(), host="127.0.0.1", port=0, quiet=True
    )
    srv.start()
    try:
        cl = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        req = GenerationRequest("m", "x", max_new_tokens=4)
        chunks = []
        with pytest.raises(RemoteServerError, match="decode blew up"):
            for c in cl.generate_stream(req):
                chunks.append(c)
        # the partial chunk arrived before the terminal error record
        assert chunks and chunks[0].text == "partial"
    finally:
        srv.stop()


def test_streaming_chunks_carry_token_ids(server, client):
    req = GenerationRequest("qwen2:1.5b", "tok ids", max_new_tokens=8)
    mono = client.generate(req)
    chunks = list(client.generate_stream(req))
    streamed = [t for c in chunks[:-1] for t in c.tokens]
    assert streamed == mono.tokens


def test_negative_num_predict_maps_to_bounded_budget():
    req = protocol.request_from_wire(
        {"model": "m", "prompt": "x", "options": {"num_predict": -1}}
    )
    assert req.max_new_tokens == protocol.UNLIMITED_NUM_PREDICT_CAP


def test_ps_and_version_endpoints(server):
    import urllib.request

    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(f"{base}/api/version", timeout=5) as resp:
        assert json.loads(resp.read())["version"]
    client = RemoteHTTPBackend(base)
    client.generate(GenerationRequest("qwen2:1.5b", "warm", max_new_tokens=4))
    with urllib.request.urlopen(f"{base}/api/ps", timeout=5) as resp:
        body = json.loads(resp.read())
    assert {"name": "qwen2:1.5b"} in body["models"]


def test_stop_option_round_trips_on_wire():
    req = GenerationRequest(
        "m", "x", max_new_tokens=5, stop=("###", chr(10) + chr(10))
    )
    assert protocol.request_from_wire(protocol.request_to_wire(req)) == req


def test_bare_string_stop_option_wraps():
    req = protocol.request_from_wire(
        {"model": "m", "prompt": "x", "options": {"stop": "###"}}
    )
    assert req.stop == ("###",)


def test_num_predict_above_cap_rejected_at_wire():
    with pytest.raises(ValueError, match="num_predict"):
        protocol.request_from_wire(
            {"model": "m", "prompt": "x", "options": {"num_predict": 4096}}
        )


def test_server_returns_400_for_oversized_num_predict(server):
    import urllib.error
    import urllib.request

    body = json.dumps(
        {"model": "qwen2:1.5b", "prompt": "x", "options": {"num_predict": 99999}}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/api/generate",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=5)
    assert exc_info.value.code == 400
