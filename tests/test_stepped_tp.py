"""Sharded stepped decode sessions on the forced-host mesh (ISSUE 8).

The continuous scheduler's engine half (`engine/stepped.py`) carries one
explicit SPMD pytree; these tests pin that the SAME session code is
device-count-agnostic: on a 2- and an 8-device tensor-parallel mesh
(virtual CPU devices — conftest forces 8), every row's token stream is
bit-identical to its solo ``generate()`` on all four cache layouts,
mid-flight joiners and shared-prefix joiners included; cancellation
restores the pool free count EXACTLY (the PR-6 invariant, now on sharded
rows); and the carry's declared shardings survive stepping — KV payload
over heads, row control replicated.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
    TensorParallelEngine,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _tiny8():
    """A tiny config whose head/ff dims divide tp ∈ {2, 8} (the
    test_parallel.py convention)."""
    return dataclasses.replace(
        get_model_config("mistral:7b").tiny(),
        n_heads=8,
        n_kv_heads=8,
        d_ff=128,
        d_model=64,
        d_head=16,
        max_seq_len=1024,  # room for the ≥1-full-page shared prefix
    )


@pytest.fixture(scope="module")
def registry():
    return {"tiny": _tiny8()}


def _tp_engine(registry, n_devices, **kwargs):
    mesh = build_mesh(
        MeshSpec.tp_only(), devices=jax.devices()[:n_devices]
    )
    return TensorParallelEngine(
        mesh=mesh, registry=dict(registry), dtype=jnp.float32, **kwargs
    )


def _drain(session, max_steps=8, limit=300):
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


LAYOUTS = [
    pytest.param(False, None, id="contiguous-bf16"),
    pytest.param(False, "int8", id="contiguous-int8kv"),
    pytest.param(True, None, id="paged-bf16"),
    pytest.param(True, "int8", id="paged-int8kv"),
]


@pytest.mark.parametrize("n_devices", [2, 8])
@pytest.mark.parametrize("paged,kv", LAYOUTS)
def test_tp_stepped_parity_with_mid_flight_join(registry, n_devices, paged, kv):
    """The acceptance matrix: 4 cache layouts × {2, 8}-device mesh, a
    mid-flight joiner included — every row token-identical to its own
    solo generate() on the same sharded engine."""
    eng = _tp_engine(registry, n_devices, paged_kv=paged, kv_quantize=kv)
    anchor = GenerationRequest(
        "tiny", "anchor runs long on the mesh", max_new_tokens=24,
        stop_at_eos=False,
    )
    short = GenerationRequest(
        "tiny", "short companion", max_new_tokens=6, seed=2
    )
    joiner = GenerationRequest(
        "tiny", "late arrival joins mid-flight", max_new_tokens=10, seed=3
    )
    solo = {id(r): eng.generate(r) for r in (anchor, short, joiner)}
    sess = eng.decode_open([anchor, short], reserve_rows=4)
    sess.step(4)  # anchor mid-flight
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    for req in (anchor, short, joiner):
        assert results[id(req)].tokens == solo[id(req)].tokens, (
            f"row diverged on tp={n_devices} paged={paged} kv={kv}"
        )
    sess.close()


def test_tp_carry_shardings_declared_and_stable(registry):
    """The tentpole's contract, directly: KV payload leaves shard over
    the heads axis, row-control leaves replicate, and one compiled
    slice step returns the carry with the SAME placements (explicit
    out_shardings — no silent reshard, no host bounce)."""
    from jax.sharding import PartitionSpec as P

    eng = _tp_engine(registry, 8, paged_kv=True)
    sess = eng.decode_open(
        [
            GenerationRequest(
                "tiny", "sharding probe", max_new_tokens=20,
                stop_at_eos=False,
            )
        ],
        reserve_rows=2,
    )

    def specs():
        out = {}
        for key, leaf in sess.carry.items():
            arr = leaf["q"] if isinstance(leaf, dict) else leaf
            out[key] = arr.sharding.spec
        return out

    before = specs()
    assert before["pool_k"] == P(None, None, "tp", None, None)
    assert before["pool_v"] == P(None, None, "tp", None, None)
    for key in ("tokens", "done", "remaining", "table", "presence"):
        assert before[key] == P(), key
    sess.step(4)
    assert specs() == before  # one slice later: placements unchanged
    # per-device accounting reflects the head shard: each of the 8
    # devices holds 1/8 of the pool payload
    state = sess.debug_state()
    assert state["mesh"]["devices"] == 8
    assert state["mesh"]["axes"] == {"tp": 8}
    pool_leaf = sess.carry["pool_k"]
    total = pool_leaf.nbytes + sess.carry["pool_v"].nbytes
    assert state["pool"]["per_device"]["bytes"] == total // 8
    sess.close()


def test_tp_carry_falls_back_to_replicated_kv(registry):
    """Heads that don't divide the mesh replicate the KV payload — the
    documented fallback keeps the session correct (and the debug
    surface honest) instead of crashing the mesh."""
    from jax.sharding import PartitionSpec as P

    cfg = dataclasses.replace(_tiny8(), n_heads=6, n_kv_heads=3, d_ff=128)
    eng = _tp_engine({"tiny3": cfg}, 2, paged_kv=True)
    req = GenerationRequest(
        "tiny3", "odd heads", max_new_tokens=16, stop_at_eos=False
    )
    joiner = GenerationRequest(
        "tiny3", "replicated joiner", max_new_tokens=6, seed=4
    )
    solo = eng.generate(req)
    solo_joiner = eng.generate(joiner)
    sess = eng.decode_open([req], reserve_rows=2)
    arr = sess.carry["pool_k"]
    assert arr.sharding.spec == P(None, None, None, None, None)
    sess.step(4)
    # the regression that shipped this assert: a JOIN's eager page
    # scatter let GSPMD re-shard the replicated pool, and the next
    # slice's explicit in_shardings rejected the arg — _recommit_carry
    # re-pins the placement after every host-side mutation batch
    sess.join(joiner)
    assert sess.carry["pool_k"].sharding.spec == P(
        None, None, None, None, None
    )
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(req)].tokens == solo.tokens
    assert results[id(joiner)].tokens == solo_joiner.tokens
    sess.close()


def test_tp_shared_prefix_joiner_parity_and_exact_restoration(registry):
    """Shared-prefix CoW paging composes on the mesh: the joiner maps
    read-only head-sharded prefix pages, chunk-prefills only the
    divergent tail, stays solo-identical — and retirement + close()
    restore the pool free count exactly (refcounted pages, PR 7)."""
    eng = _tp_engine(registry, 8, paged_kv=True, prefix_share=True)
    # ≥1 FULL 128-token page of shared prefix (character tokenizer —
    # the test_prefix.py convention)
    prefix = "s" * 140 + " "
    anchor = GenerationRequest(
        "tiny", prefix + "anchor question", max_new_tokens=24,
        stop_at_eos=False,
    )
    sharer = GenerationRequest(
        "tiny", prefix + "different tail", max_new_tokens=8, seed=5
    )
    solo_sharer = eng.generate(sharer)
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    assert sess.can_join(sharer)
    free_before_join = sess.pool.free_pages
    pj = sess.join_begin(sharer)
    assert pj.hit_tokens > 0, "joiner did not hit the published prefix"
    assert pj.shared_pages > 0, "no pool pages were mapped read-only"
    # only the divergent tail came off the free list — the shared page
    # is a refcounted read-only mapping, billed once
    assert free_before_join - sess.pool.free_pages < len(pj.pages)
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    assert sess.pool.shared_pages > 0  # live CoW mapping on the mesh
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(sharer)].tokens == solo_sharer.tokens
    # exact restoration: close releases rows, then index refs LAST —
    # every refcount reaches zero and only the parking page stays out
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - 1


@pytest.mark.parametrize("n_devices", [2, 8])
def test_tp_spec_session_parity_and_draft_sharding(registry, n_devices):
    """ISSUE 9 on the mesh: a speculating stepped session — draft KV
    leaves in the SPMD carry, sharded by the DRAFT model's own heads —
    emits the plain greedy stream for every row incl. a mid-flight
    joiner, and the declared carry placements survive stepping."""
    from jax.sharding import PartitionSpec as P

    draft_cfg = dataclasses.replace(_tiny8(), n_layers=1)
    reg = {"tiny": _tiny8(), "tiny-d": draft_cfg}
    eng = _tp_engine(
        reg, n_devices, paged_kv=True,
        speculative={"tiny": ("tiny-d", 3)},
    )
    anchor = GenerationRequest(
        "tiny", "mesh anchor runs long", max_new_tokens=24,
        stop_at_eos=False,
    )
    joiner = GenerationRequest(
        "tiny", "late mesh arrival", max_new_tokens=10, seed=3
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert sess.spec is not None
    # draft payload sharded over the DRAFT's heads (8 % tp == 0),
    # per-row spec state replicated
    assert sess.carry["draft_k"].sharding.spec == P(
        None, None, "tp", None, None
    )
    for key in ("draft_offsets", "spec_rounds", "spec_accepted"):
        assert sess.carry[key].sharding.spec == P(), key
    # ISSUE 10: the kernel-less native verify's scratch leaves ride the
    # SPMD carry as KV payload — [L,B,Hkv,k+1,Dh], heads over tp
    assert not sess.stacked  # no kernel on the forced-host mesh
    for key in ("scratch_k", "scratch_v"):
        assert sess.carry[key].sharding.spec == P(
            None, None, "tp", None, None
        ), key
    before = {
        key: leaf.sharding.spec
        for key, leaf in sess.carry.items()
        if not isinstance(leaf, dict)
    }
    sess.step(4)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    after = {
        key: leaf.sharding.spec
        for key, leaf in sess.carry.items()
        if not isinstance(leaf, dict)
    }
    assert after == before  # placements stable across spec slices + join
    for req in (anchor, joiner):
        assert results[id(req)].tokens == eng._generate_plain(req).tokens, (
            f"spec row diverged on tp={n_devices}"
        )
        assert results[id(req)].extras["spec"]["rounds"] >= 1
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - 1


@pytest.mark.parametrize("n_devices", [2, 8])
def test_tp_spec_stacked_native_verify_on_mesh(registry, n_devices):
    """ISSUE 10 × ISSUE 8: the STACKED native verify on a mesh — the
    multi-query parts kernel runs under shard_map with heads sharded
    and the verify's candidates in the head-sharded side caches; the
    speculating session stays plain-greedy identical and bills
    prompt-only pages. Kernels are enabled by patching the gate (the
    forced-host mesh has no TPU), which leaves the draft's contiguous
    decode kernel-free as production would."""
    draft_cfg = dataclasses.replace(_tiny8(), n_layers=1)
    reg = {"tiny": _tiny8(), "tiny-d": draft_cfg}
    eng = _tp_engine(
        reg, n_devices, paged_kv=True,
        speculative={"tiny": ("tiny-d", 3)},
    )
    eng._specialised_kernels_enabled = lambda: True  # engage the wrapper
    exp = _tp_engine(reg, n_devices, paged_kv=True)
    anchor = GenerationRequest(
        "tiny", "stacked mesh anchor", max_new_tokens=20,
        stop_at_eos=False,
    )
    joiner = GenerationRequest(
        "tiny", "stacked mesh joiner", max_new_tokens=8, seed=3
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert sess.spec is not None and sess.stacked
    assert sess._pages_needed(100, 40) == -(-100 // 128)  # prompt-only
    sess.step(2)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    for req in (anchor, joiner):
        assert results[id(req)].tokens == exp._generate_plain(req).tokens
        assert results[id(req)].extras["spec"]["rounds"] >= 1
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - 1


def test_tp_cancel_restores_free_count_exactly(registry):
    """PR-6's cancellation invariant on SHARDED rows (the ROADMAP
    follow-on): cancel() parks the table row and frees the victim's
    pages mid-flight with exact free-count restoration, and the
    surviving anchor decodes on, unperturbed, to its solo stream."""
    eng = _tp_engine(registry, 8, paged_kv=True)
    anchor = GenerationRequest(
        "tiny", "anchor", max_new_tokens=40, stop_at_eos=False
    )
    victim = GenerationRequest(
        "tiny", "victim row to cancel", max_new_tokens=40,
        stop_at_eos=False, seed=3,
    )
    solo_anchor = eng.generate(anchor)
    sess = eng.decode_open([anchor], reserve_rows=4)
    free_before_join = sess.pool.free_pages
    sess.step(4)
    sess.join(victim)
    victim_pages = next(
        row.pages
        for row in sess.rows
        if row is not None and row.request is victim
    )
    assert sess.pool.free_pages == free_before_join - len(victim_pages)
    sess.step(4)
    assert sess.cancel(victim)
    assert sess.pool.free_pages == free_before_join
    assert sess.active == 1
    results = _drain(sess)
    assert results[0].tokens == solo_anchor.tokens
    sess.close()


# -- tp×dp in-mesh row sharding (ISSUE 19) -------------------------------------


def _dp_engine(registry, dp, tp, **kwargs):
    mesh = build_mesh(
        MeshSpec.dp_tp(dp, tp), devices=jax.devices()[: dp * tp]
    )
    return TensorParallelEngine(
        mesh=mesh, registry=dict(registry), dtype=jnp.float32, **kwargs
    )


@pytest.mark.parametrize("paged,kv", LAYOUTS)
def test_dp_stepped_parity_all_layouts(registry, paged, kv):
    """The ISSUE-19 acceptance matrix: a 2×2 tp×dp mesh (4 virtual
    devices), all four cache layouts — every row, a mid-flight joiner
    included, emits the token stream bit-identical to its solo
    generate() on the SAME dp-sharded engine."""
    eng = _dp_engine(registry, 2, 2, paged_kv=paged, kv_quantize=kv)
    anchor = GenerationRequest(
        "tiny", "dp anchor runs long on the mesh", max_new_tokens=24,
        stop_at_eos=False,
    )
    short = GenerationRequest(
        "tiny", "dp short companion", max_new_tokens=6, seed=2
    )
    joiner = GenerationRequest(
        "tiny", "dp late joiner lands here", max_new_tokens=10,
        seed=3,
    )
    solo = {id(r): eng.generate(r) for r in (anchor, short, joiner)}
    sess = eng.decode_open([anchor, short], reserve_rows=4)
    assert sess.dp_shards == 2, "dp never engaged on the 2x2 mesh"
    sess.step(4)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    for req in (anchor, short, joiner):
        assert results[id(req)].tokens == solo[id(req)].tokens, (
            f"row diverged on dp=2 tp=2 paged={paged} kv={kv}"
        )
    sess.close()


@pytest.mark.parametrize("dp,tp", [(4, 1), (2, 4), (4, 2)])
def test_dp_mesh_shapes_paged_parity(registry, dp, tp):
    """Mesh-shape sweep on the paged layout: pure-dp (4×1), wide-tp
    (2×4) and the full 8-device 4×2 — the same session code engages
    whatever dp the mesh offers and stays solo-identical."""
    eng = _dp_engine(registry, dp, tp, paged_kv=True)
    reqs = [
        GenerationRequest(
            "tiny", f"dp sweep row {i}", max_new_tokens=12, seed=i + 1,
            stop_at_eos=False,
        )
        for i in range(3)
    ]
    solo = {id(r): eng.generate(r) for r in reqs}
    sess = eng.decode_open(reqs, reserve_rows=4)
    assert sess.dp_shards == dp
    results = {id(r.request): r for r in _drain(sess)}
    for req in reqs:
        assert results[id(req)].tokens == solo[id(req)].tokens, (
            f"row diverged on dp={dp} tp={tp}"
        )
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - sess.dp_shards


def test_dp_carry_shardings_declared_and_stable(registry):
    """The dp contract, directly: payload leaves gain a 'dp' row/page
    axis next to the tp head axis, row-control leaves shard their
    leading row dim over dp instead of replicating, and one compiled
    slice step returns the carry with the SAME placements."""
    from jax.sharding import PartitionSpec as P

    eng = _dp_engine(registry, 2, 2, paged_kv=True)
    sess = eng.decode_open(
        [
            GenerationRequest(
                "tiny", "dp sharding probe", max_new_tokens=20,
                stop_at_eos=False,
            )
        ],
        reserve_rows=4,
    )
    assert sess.dp_shards == 2

    def specs():
        out = {}
        for key, leaf in sess.carry.items():
            arr = leaf["q"] if isinstance(leaf, dict) else leaf
            out[key] = arr.sharding.spec
        return out

    before = specs()
    # pool payload: page dim over dp, heads over tp
    assert before["pool_k"] == P(None, "dp", "tp", None, None)
    assert before["pool_v"] == P(None, "dp", "tp", None, None)
    # row control: leading row dim over dp (no longer replicated)
    for key in ("tokens", "done", "remaining", "table", "presence"):
        assert before[key][0] == "dp", (key, before[key])
    sess.step(4)
    assert specs() == before  # one slice later: placements unchanged
    state = sess.debug_state()
    assert state["mesh"]["devices"] == 4
    assert state["mesh"]["axes"] == {"dp": 2, "tp": 2}
    sess.close()


def test_dp_per_shard_parking_and_page_locality(registry):
    """The host allocator mirrors the GSPMD split: each dp shard keeps
    its OWN parking page, and a row's pages come from the page range
    its shard owns (best-effort locality — spillover is allowed, the
    preference is what's pinned here on an empty pool)."""
    eng = _dp_engine(registry, 2, 2, paged_kv=True)
    reqs = [
        GenerationRequest(
            "tiny", f"locality row {i}", max_new_tokens=8, seed=i + 1
        )
        for i in range(4)
    ]
    sess = eng.decode_open(reqs, reserve_rows=4)
    assert sess.dp_shards == 2
    assert len(sess.parking_pages) == 2
    half = sess.pool.n_pages // 2
    shard_of = lambda p: 0 if p < half else 1  # noqa: E731
    # parking pages live one per shard
    assert sorted(shard_of(p) for p in sess.parking_pages) == [0, 1]
    # every live row's pages sit on the shard that owns the row slot
    for r, row in enumerate(sess.rows):
        if row is None:
            continue
        want = sess._row_shard(r)
        assert all(shard_of(p) == want for p in row.pages), (
            r, want, row.pages,
        )
    # cancellation hands the pages back and keeps the exact-free
    # invariant on the sharded pool
    free_before = sess.pool.free_pages
    victim = next(row for row in sess.rows if row is not None)
    pages = len(victim.pages)
    assert sess.cancel(victim.request)
    assert sess.pool.free_pages == free_before + pages
    sess.close()


def test_dp_mid_flight_join_lands_on_row_shard(registry):
    """A mid-flight joiner on the dp mesh allocates its pages on the
    shard owning its seat — the join path routes through the same
    shard-preferred allocator as open — and still matches solo."""
    eng = _dp_engine(registry, 2, 2, paged_kv=True)
    anchor = GenerationRequest(
        "tiny", "dp join anchor", max_new_tokens=20, stop_at_eos=False
    )
    joiner = GenerationRequest(
        "tiny", "dp joiner lands sharded", max_new_tokens=8, seed=5
    )
    solo_joiner = eng.generate(joiner)
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert sess.dp_shards == 2
    sess.step(2)
    sess.join(joiner)
    half = sess.pool.n_pages // 2
    r, row = next(
        (r, row)
        for r, row in enumerate(sess.rows)
        if row is not None and row.request is joiner
    )
    want = sess._row_shard(r)
    assert all(
        (0 if p < half else 1) == want for p in row.pages
    ), (r, want, row.pages)
    results = {id(r_.request): r_ for r_ in _drain(sess)}
    assert results[id(joiner)].tokens == solo_joiner.tokens
    sess.close()


def test_dp_indivisible_bucket_falls_back_to_tp_only(registry):
    """A bucket width that does not divide dp must NOT engage row
    sharding (the stepped_carry_shardings divisibility fallback) — the
    session still serves, tp-only, instead of crashing the mesh."""
    from jax.sharding import PartitionSpec as P

    eng = _dp_engine(registry, 4, 2, paged_kv=True)
    req = GenerationRequest(
        "tiny", "bucket of two on dp four", max_new_tokens=8
    )
    solo = eng.generate(req)
    # b_bucket=2 (one row + reserve 1 → bucket 2) does not divide dp=4
    sess = eng.decode_open([req], reserve_rows=1)
    assert sess.b_bucket % 4 != 0
    assert sess.dp_shards == 1
    assert sess.carry["tokens"].sharding.spec == P()
    results = _drain(sess)
    assert results[0].tokens == solo.tokens
    sess.close()


def test_dp_continuous_scheduler_serves_sharded_rows(registry):
    """The serve plumbing end-to-end in-process: a tp×dp engine behind
    the continuous scheduler (what ``serve --backend jax-tp --tp N
    --dp M`` builds) admits staggered rows, steps them on the sharded
    session and retires both with solo-identical streams."""
    import threading
    import time

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    eng = _dp_engine(registry, 2, 2, paged_kv=True)
    r1 = GenerationRequest(
        "tiny", "sched dp row one", max_new_tokens=10, stop_at_eos=False
    )
    r2 = GenerationRequest(
        "tiny", "sched dp row two", max_new_tokens=8, seed=2
    )
    solo = {id(r): eng.generate(r) for r in (r1, r2)}
    sched = ContinuousScheduler(eng, slice_steps=2)
    sched.start()
    try:
        done = {}

        def run(req):
            done[id(req)] = sched.submit(req)

        threads = [
            threading.Thread(target=run, args=(r,)) for r in (r1, r2)
        ]
        threads[0].start()
        time.sleep(0.05)
        threads[1].start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "dp scheduler row hung"
        for req in (r1, r2):
            assert done[id(req)].tokens == solo[id(req)].tokens
        assert sched.debug_state()["backend_mesh"]["axes"] == {
            "dp": 2,
            "tp": 2,
        }
    finally:
        sched.stop()


def test_tp_deadline_reap_through_continuous_scheduler(registry):
    """Deadline reaping propagates into the sharded session: a
    mid-flight ``deadline_ms`` expiry retires the row through the
    continuous scheduler's reap sweep (session.cancel on the mesh) and
    the caller fails with DeadlineExceeded — not a hang, not a stuck
    slot."""
    import threading

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.stream import (
        DeadlineExceeded,
    )

    eng = _tp_engine(registry, 2, paged_kv=True)
    # warm the compiled shapes so the deadline races decode, not XLA
    warm = GenerationRequest(
        "tiny", "warm", max_new_tokens=200, stop_at_eos=False
    )
    sess = eng.decode_open([warm], reserve_rows=2)
    sess.step(2)
    sess.close()
    sched = ContinuousScheduler(eng, slice_steps=2)
    sched.start()
    try:
        doomed = GenerationRequest(
            "tiny", "doomed long row", max_new_tokens=200,
            stop_at_eos=False, deadline_ms=300.0,
        )
        errs = {}

        def run():
            try:
                sched.submit(doomed)
            except BaseException as exc:  # noqa: BLE001
                errs["exc"] = exc

        t = threading.Thread(target=run)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive(), "deadline-doomed request hung"
        assert isinstance(errs.get("exc"), DeadlineExceeded), errs
        # the session closed behind the reaped row: the scheduler's
        # debug surface shows no live session holding mesh state
        assert sched.debug_state()["backend_mesh"]["devices"] == 2
    finally:
        sched.stop()
