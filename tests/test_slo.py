"""SLO objectives + burn-rate alerting (ISSUE 17): the spec grammar,
attainment math, the firing/resolved state machine under a hand-driven
clock, and THE acceptance criterion — a hermetic fake fleet whose
router-side attainment is byte-identical to recomputing it from the
per-replica ring rollups.
"""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu import obs
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import slo as slo_mod
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
    EV_SLO_ALERT,
    FlightRecorder,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    MetricsRegistry,
    bucket_fraction_below,
    merge_expositions,
    parse_exposition,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.slo import (
    SLOEngine,
    burn_rate,
    exact_attainment,
    parse_slo_spec,
    ring_attainment,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.timeseries import (
    TimeSeriesRing,
    families_from_parsed,
    registry_families,
)


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    yield
    (obs.enable if was else obs.disable)()


@pytest.fixture
def obs_off():
    was = obs.enabled()
    obs.disable()
    yield
    (obs.enable if was else obs.disable)()


TTFT_FAMILY = "llm_request_ttft_seconds"
TTFT_BUCKETS = (0.05, 0.1, 0.5, 2.0)


# -- spec grammar -------------------------------------------------------------


def test_parse_slo_spec_full_grammar():
    objs = parse_slo_spec(
        "ttft_p99_ms<=250,completion_p95_s<=4,"
        "queue_wait_p50_ms<=80,joules_per_token<=0.35"
    )
    by_name = {o.name: o for o in objs}
    assert list(by_name) == [
        "ttft_p99_ms",
        "completion_p95_s",
        "queue_wait_p50_ms",
        "joules_per_token",
    ]
    ttft = by_name["ttft_p99_ms"]
    assert ttft.family == "llm_request_ttft_seconds"
    assert ttft.threshold == 0.25  # ms -> native seconds
    assert ttft.target == 0.99
    comp = by_name["completion_p95_s"]
    assert comp.family == "llm_request_completion_seconds"
    assert (comp.threshold, comp.target) == (4.0, 0.95)
    qw = by_name["queue_wait_p50_ms"]
    assert qw.family == "llm_sched_queue_wait_seconds"
    assert (qw.threshold, qw.target) == (0.08, 0.50)
    jpt = by_name["joules_per_token"]
    assert jpt.family == "llm_request_joules_per_token"
    assert jpt.threshold == 0.35
    assert jpt.target == 0.95  # documented default, no pct spelling


def test_parse_slo_spec_tolerates_whitespace_and_blank_parts():
    objs = parse_slo_spec(" ttft_p99_ms <= 250 , ,completion_p95_s<=4 ")
    assert [o.name for o in objs] == ["ttft_p99_ms", "completion_p95_s"]


@pytest.mark.parametrize(
    "bad",
    [
        "",  # empty spec
        "ttft_p99_ms=250",  # missing <=
        "ttft_p99_ms<=abc",  # not a number
        "ttft_p99_ms<=0",  # non-positive
        "ttft_p99_ms<=-3",
        "frobnitz_p99_ms<=250",  # unknown metric
        "ttft_p0_ms<=250",  # percentile out of 1..99
        "ttft_p99_ms<=250,ttft_p99_ms<=300",  # duplicate
    ],
)
def test_parse_slo_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_slo_spec(bad)


def test_exact_attainment_and_burn_rate():
    (obj,) = parse_slo_spec("ttft_p99_ms<=100")
    assert exact_attainment(obj, []) is None
    assert exact_attainment(obj, [0.05, 0.1, 0.2, 0.3]) == 0.5
    assert obj.attains(0.1) and not obj.attains(0.11)
    assert burn_rate(None, 0.99) == 0.0
    assert burn_rate(1.0, 0.99) == 0.0
    assert burn_rate(0.99, 0.99) == pytest.approx(1.0)
    assert burn_rate(0.0, 0.99) == pytest.approx(100.0)


# -- the firing/resolved state machine ----------------------------------------


def _single_server_rig():
    """A private registry + hand-clock ring + engine with tiny burn
    pairs — the single-GenerationServer shape in miniature."""
    reg = MetricsRegistry()
    hist = reg.histogram("llm_request_ttft_seconds", "t", buckets=TTFT_BUCKETS)
    clock = {"t": 0.0}
    ring = TimeSeriesRing(
        source=lambda: registry_families(reg, prefixes=("llm_",)),
        clock=lambda: clock["t"],
    )
    rec = FlightRecorder(capacity=64)
    engine = SLOEngine(
        parse_slo_spec("ttft_p99_ms<=100"),
        ring,
        recorder=rec,
        pairs=((2.0, 5.0, 14.4),),
    )
    return reg, hist, clock, ring, rec, engine


def _tick(ring, clock, engine, t):
    clock["t"] = t
    ring.sample_once(now=t)
    return engine.evaluate(now=t)


def test_engine_breach_fires_within_one_fast_window_then_rearms(obs_on):
    _, hist, clock, ring, rec, engine = _single_server_rig()

    # t=0 baseline: no traffic -> attainment None, burn 0, quiet
    report = _tick(ring, clock, engine, 0.0)
    r = report["ttft_p99_ms"]
    assert r["attainment"] is None
    assert r["burn_rate"] == {"2s": 0.0, "5s": 0.0}
    assert not r["firing"]
    # the attainment gauge publishes 1.0 on no-traffic (no false alarms)
    assert slo_mod._ATTAIN_G.labels(objective="ttft_p99_ms").value == 1.0

    # breach: every request blows the 100 ms threshold
    for _ in range(5):
        hist.observe(1.0)
    report = _tick(ring, clock, engine, 1.0)
    r = report["ttft_p99_ms"]
    assert r["attainment"] == 0.0
    assert r["burn_rate"]["2s"] == 100.0  # (1-0)/(1-0.99)
    assert r["firing"] and r["episodes"] == 1
    assert slo_mod._ATTAIN_G.labels(objective="ttft_p99_ms").value == 0.0
    events = rec.events(type_=EV_SLO_ALERT)
    assert len(events) == 1
    firing = events[0]
    assert firing["state"] == "firing"
    assert firing["trace_id"] == "slo-ttft_p99_ms-1"
    assert firing["burn_short"] > 14.4 and firing["burn_long"] > 14.4

    # still breached next tick: no duplicate event while firing
    report = _tick(ring, clock, engine, 2.0)
    assert report["ttft_p99_ms"]["firing"]
    assert len(rec.events(type_=EV_SLO_ALERT)) == 1

    # recovery: the bad minute ages out of both windows -> resolved,
    # sharing the episode's trace id
    report = _tick(ring, clock, engine, 10.0)
    r = report["ttft_p99_ms"]
    assert not r["firing"]
    events = rec.events(type_=EV_SLO_ALERT)
    assert [e["state"] for e in events] == ["firing", "resolved"]
    assert events[1]["trace_id"] == "slo-ttft_p99_ms-1"

    # re-arm: a second breach opens a NEW episode with a new trace id
    for _ in range(5):
        hist.observe(1.0)
    report = _tick(ring, clock, engine, 11.0)
    assert report["ttft_p99_ms"]["firing"]
    assert report["ttft_p99_ms"]["episodes"] == 2
    assert rec.events(type_=EV_SLO_ALERT)[-1]["trace_id"] == "slo-ttft_p99_ms-2"

    # transition counters kept pace
    assert slo_mod._ALERTS_C.labels(
        objective="ttft_p99_ms", state="firing"
    ).value == 2.0
    assert slo_mod._ALERTS_C.labels(
        objective="ttft_p99_ms", state="resolved"
    ).value == 1.0


def test_pair_needs_both_windows_to_trip(obs_on):
    """A short-window spike whose long window stays healthy must NOT
    fire (the flap-resistance the multi-window pairs buy)."""
    _, hist, clock, ring, rec, engine = _single_server_rig()
    # long window accumulates plenty of healthy traffic first
    for _ in range(400):
        hist.observe(0.01)
    _tick(ring, clock, engine, 0.0)
    for _ in range(400):
        hist.observe(0.01)
    _tick(ring, clock, engine, 3.0)
    # now a short burst of bad requests: short window burns, but the
    # long window still holds the 400 good observations
    for _ in range(4):
        hist.observe(1.0)
    report = _tick(ring, clock, engine, 4.0)
    r = report["ttft_p99_ms"]
    assert r["burn_rate"]["2s"] > 14.4
    assert r["burn_rate"]["5s"] < 14.4
    assert not r["firing"]
    assert rec.events(type_=EV_SLO_ALERT) == []


def test_engine_snapshot_shape(obs_on):
    _, hist, clock, ring, _, engine = _single_server_rig()
    hist.observe(0.01)
    _tick(ring, clock, engine, 0.0)
    snap = engine.snapshot()
    assert snap["engine"] == "server"
    assert snap["objectives"][0]["name"] == "ttft_p99_ms"
    assert snap["pairs_s"] == [[2.0, 5.0, 14.4]]
    assert snap["long_window_s"] == 5.0
    assert "ttft_p99_ms" in snap["report"]
    assert snap["firing"] == 0


def test_active_snapshot_sees_live_engines(obs_on):
    before = slo_mod.active_snapshot()
    names = {s["engine"] for s in before} if before else set()
    ring = TimeSeriesRing(source=dict, clock=lambda: 0.0)
    engine = SLOEngine(
        parse_slo_spec("ttft_p99_ms<=100"),
        ring,
        recorder=FlightRecorder(capacity=4),
        pairs=((2.0, 5.0, 14.4),),
        name="test-active-snap",
    )
    snaps = slo_mod.active_snapshot()
    assert {s["engine"] for s in snaps} >= names | {"test-active-snap"}
    del engine  # weakly held: drops out once collected


def test_engine_noop_when_disabled(obs_off):
    reg = MetricsRegistry()
    reg.histogram("llm_request_ttft_seconds", "t", buckets=TTFT_BUCKETS)
    ring = TimeSeriesRing(
        source=lambda: registry_families(reg), clock=lambda: 0.0
    )
    rec = FlightRecorder(capacity=4)
    engine = SLOEngine(
        parse_slo_spec("ttft_p99_ms<=100"),
        ring,
        recorder=rec,
        pairs=((2.0, 5.0, 14.4),),
    )
    assert engine.evaluate(now=0.0) is None
    assert rec.events() == []
    assert engine.snapshot()["report"] == {}


# -- the acceptance criterion: hermetic fake fleet ----------------------------


class _FakeFleet:
    """Two replica registries federated exactly like RouterServer's
    telemetry tick: per-replica rings ingest each replica's exposition,
    the fleet ring ingests the ``merge_expositions`` merge — all stamped
    with ONE shared deterministic ``now`` per tick."""

    def __init__(self):
        self.clock = {"t": 0.0}
        self.regs = {}
        self.hists = {}
        self.replica_rings = {}
        for name in ("a", "b"):
            reg = MetricsRegistry()
            self.regs[name] = reg
            self.hists[name] = reg.histogram(
                TTFT_FAMILY, "t", buckets=TTFT_BUCKETS
            )
            self.replica_rings[name] = TimeSeriesRing(
                source=dict, clock=lambda: self.clock["t"]
            )
        self.fleet_ring = TimeSeriesRing(
            source=dict, clock=lambda: self.clock["t"]
        )
        self.recorder = FlightRecorder(capacity=64)
        self.engine = SLOEngine(
            parse_slo_spec("ttft_p99_ms<=100"),
            self.fleet_ring,
            recorder=self.recorder,
            pairs=((2.0, 5.0, 14.4),),
            name="router",
        )

    def tick(self, t):
        self.clock["t"] = t
        sources = [
            (name, reg.exposition()) for name, reg in self.regs.items()
        ]
        for name, text in sources:
            self.replica_rings[name].ingest_text(text, now=t)
        merged = merge_expositions(sources)
        self.fleet_ring.ingest(
            families_from_parsed(parse_exposition(merged)), now=t
        )
        return self.engine.evaluate(now=t)


def test_fleet_breach_fires_and_attainment_matches_replica_recompute(obs_on):
    """ISSUE 17 acceptance: deterministic-clock fake fleet — a breach
    fires within one fast window and resolves after recovery, and the
    router's ``llm_slo_attainment`` equals — bit for bit — attainment
    recomputed from the per-replica ring rollups (additivity of
    ``bucket_fraction_below`` over bucket-wise merged counts)."""
    fleet = _FakeFleet()
    fleet.tick(0.0)  # baseline

    # phase 1: both replicas healthy (everything under 100 ms)
    for _ in range(20):
        fleet.hists["a"].observe(0.01)
        fleet.hists["b"].observe(0.02)
    report = fleet.tick(1.0)
    r = report["ttft_p99_ms"]
    assert r["attainment"] == 1.0
    assert not r["firing"]

    # phase 2: replica b breaches hard; a stays healthy. The FLEET
    # attainment is the traffic-weighted mix -> burns the budget.
    for _ in range(20):
        fleet.hists["a"].observe(0.01)
        fleet.hists["b"].observe(1.0)
    report = fleet.tick(2.0)  # one fast window (2 s) after the breach
    r = report["ttft_p99_ms"]
    assert r["firing"], "breach must fire within one fast window"
    fleet_att = r["attainment"]
    assert fleet_att is not None and fleet_att < 0.99

    # THE consistency assertion: recompute attainment from the
    # per-replica rings' bucket deltas over the same window, summed —
    # must equal the router engine's number exactly (same ints, same
    # float ops; the shared per-tick `now` makes the windows identical).
    (obj,) = fleet.engine.objectives
    window = fleet.engine.long_window_s
    summed = [0] * (len(TTFT_BUCKETS) + 1)
    for ring in fleet.replica_rings.values():
        rollup = ring.window(TTFT_FAMILY, window, now=2.0)
        assert rollup is not None
        for child in rollup["children"].values():
            for i, d in enumerate(child["bucket_deltas"]):
                summed[i] += d
    recomputed = bucket_fraction_below(TTFT_BUCKETS, summed, obj.threshold)
    assert fleet_att == recomputed  # byte-consistent, not approx

    # ... and the per-replica attainment view tells b from a
    by_replica = fleet.engine.attainment_by_replica(
        fleet.replica_rings, now=2.0
    )
    assert by_replica["a"]["ttft_p99_ms"] == 1.0
    assert by_replica["b"]["ttft_p99_ms"] < 0.99

    # phase 3: recovery — the breach ages out of every window
    report = fleet.tick(10.0)
    assert not report["ttft_p99_ms"]["firing"]
    states = [
        e["state"] for e in fleet.recorder.events(type_=EV_SLO_ALERT)
    ]
    assert states == ["firing", "resolved"]


def test_fleet_engine_prefers_fleet_spelling(obs_on):
    """The router ring holds BOTH the raw families (its own registry)
    and the ``llm_fleet_`` merge; only the merge covers remote replicas,
    so the resolver must pick the fleet spelling when present."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.timeseries import (
        FamilySample,
    )

    (obj,) = parse_slo_spec("ttft_p99_ms<=100")
    ring = TimeSeriesRing(source=dict, clock=lambda: 0.0)
    # raw family says "all good"; fleet merge says "all bad"
    good = FamilySample("histogram", {"_": ((5, 0, 0, 0, 0), 0.05, 5)}, TTFT_BUCKETS)
    bad = FamilySample("histogram", {"_": ((0, 0, 0, 5, 0), 5.0, 5)}, TTFT_BUCKETS)
    ring.ingest({TTFT_FAMILY: good, "llm_fleet_request_ttft_seconds": bad}, now=0.0)
    ring.ingest(
        {
            TTFT_FAMILY: FamilySample(
                "histogram", {"_": ((10, 0, 0, 0, 0), 0.1, 10)}, TTFT_BUCKETS
            ),
            "llm_fleet_request_ttft_seconds": FamilySample(
                "histogram", {"_": ((0, 0, 0, 10, 0), 10.0, 10)}, TTFT_BUCKETS
            ),
        },
        now=1.0,
    )
    att = ring_attainment([obj], ring, 60.0, now=1.0)
    assert att["ttft_p99_ms"] == 0.0  # the fleet view won
