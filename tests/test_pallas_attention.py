"""Pallas decode kernel vs the jnp reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.attention import (
    decode_attention_reference,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
    _pick_block_t,
    pallas_decode_attention,
)


def _mk(b, hq, hkv, t, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, hq, d), dtype=dtype)
    k = jax.random.normal(ks[1], (b, hkv, t, d), dtype=dtype)
    v = jax.random.normal(ks[2], (b, hkv, t, d), dtype=dtype)
    return q, k, v


def test_pick_block_t():
    assert _pick_block_t(4096) == 512
    assert _pick_block_t(48) == 16
    assert _pick_block_t(33) == 1
    assert _pick_block_t(256, preferred=128) == 128


@pytest.mark.parametrize(
    "b,hq,hkv,t,d,length",
    [
        (1, 8, 2, 64, 16, 10),  # GQA, d needs lane padding
        (1, 8, 1, 128, 128, 128),  # MQA, full cache, aligned d
        (2, 4, 4, 96, 64, 33),  # MHA, batch 2, ragged block
        (1, 4, 4, 256, 96, 200),  # phi3-style d=96
    ],
)
def test_pallas_matches_reference(b, hq, hkv, t, d, length):
    q, k, v = _mk(b, hq, hkv, t, d)
    lengths = jnp.full((b,), length, dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_per_batch_lengths():
    q, k, v = _mk(2, 4, 2, 64, 32)
    lengths = jnp.array([5, 50], dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_ignores_garbage_beyond_length():
    q, k, v = _mk(1, 4, 2, 64, 32)
    lengths = jnp.array([7], dtype=jnp.int32)
    out1 = pallas_decode_attention(q, k, v, lengths, interpret=True)
    k2 = k.at[:, :, 7:].set(1e9)
    v2 = v.at[:, :, 7:].set(-1e9)
    out2 = pallas_decode_attention(q, k2, v2, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_pallas_bf16_inputs():
    q, k, v = _mk(1, 8, 2, 128, 64, dtype=jnp.bfloat16)
    lengths = jnp.array([100], dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        atol=3e-2,
    )


def test_pallas_inside_jit_and_grad_free_scan():
    """The kernel must be traceable under jit with traced lengths."""
    q, k, v = _mk(1, 4, 2, 64, 32)

    @jax.jit
    def f(q, k, v, lengths):
        return pallas_decode_attention(q, k, v, lengths, interpret=True)

    out = f(q, k, v, jnp.array([30], dtype=jnp.int32))
    ref = decode_attention_reference(q, k, v, jnp.array([30], dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# ---- prefill kernel ---------------------------------------------------------

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (  # noqa: E402
    pallas_prefill_attention,
)


def _prefill_reference(q, k_cache, v_cache, offset):
    """Masked-softmax attention of S queries at ``offset`` vs the cache —
    the same math as the transformer's jnp prefill path."""
    b, s, hq, d = q.shape
    hkv, t = k_cache.shape[1], k_cache.shape[2]
    group = hq // hkv
    qg = q.reshape(b, s, hkv, group, d).astype(jnp.float32)
    scores = jnp.einsum(
        "bskgd,bktd->bkgst", qg, k_cache.astype(jnp.float32)
    ) / jnp.sqrt(jnp.float32(d))
    qpos = offset + jnp.arange(s)[:, None]
    kpos = jnp.arange(t)[None, :]
    scores = jnp.where((kpos <= qpos)[None, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,bktd->bskgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, s, hq, d).astype(q.dtype)


def _mk_prefill(b, s, hq, hkv, t, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, t, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, t, d), dtype=jnp.float32)
    return q, k, v


@pytest.mark.parametrize(
    "b,s,hq,hkv,t,d,offset",
    [
        (1, 32, 8, 2, 64, 16, 0),  # GQA, lane padding, fresh prefill
        (2, 16, 4, 4, 48, 64, 0),  # MHA, batch 2, ragged k blocks
        (1, 64, 8, 1, 64, 128, 0),  # MQA, aligned d, S == T
        (1, 16, 4, 2, 64, 32, 24),  # chunked prefill at offset > 0
    ],
)
def test_prefill_matches_reference(b, s, hq, hkv, t, d, offset):
    q, k, v = _mk_prefill(b, s, hq, hkv, t, d)
    ref = _prefill_reference(q, k, v, jnp.int32(offset))
    out = pallas_prefill_attention(q, k, v, jnp.int32(offset), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_prefill_small_blocks_exercise_multiblock_grid():
    """Force several q and k blocks so the online accumulation and the
    causal block-skip logic actually run."""
    q, k, v = _mk_prefill(1, 32, 4, 2, 64, 32)
    ref = _prefill_reference(q, k, v, jnp.int32(0))
    out = pallas_prefill_attention(
        q, k, v, jnp.int32(0), block_q=8, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_prefill_ignores_unwritten_cache_suffix():
    """Garbage beyond the causal frontier must not leak into the output."""
    q, k, v = _mk_prefill(1, 16, 4, 2, 64, 32)
    out1 = pallas_prefill_attention(q, k, v, jnp.int32(0), interpret=True)
    k2 = k.at[:, :, 16:].set(1e9)
    v2 = v.at[:, :, 16:].set(-1e9)
    out2 = pallas_prefill_attention(q, k2, v2, jnp.int32(0), interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-5)


def test_forward_with_pallas_prefill_matches_jnp_path():
    """End-to-end: the transformer's prefill with the Pallas kernel injected
    must match the default jnp path."""
    import dataclasses

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        Transformer,
        forward,
        logits_for,
    )

    cfg = dataclasses.replace(get_model_config("qwen2:1.5b").tiny(), n_layers=2)
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    k0, v0 = tf.init_cache(batch=1, max_len=32, dtype=jnp.float32)

    hidden_jnp, _, _ = forward(
        tf.params, cfg, tokens, jnp.int32(0), k0, v0, None
    )
    hidden_pl, _, _ = forward(
        tf.params, cfg, tokens, jnp.int32(0), k0, v0, None,
        lambda q, kc, vc, off: pallas_prefill_attention(
            q, kc, vc, off, interpret=True
        ),
    )
    np.testing.assert_allclose(
        np.asarray(logits_for(tf.params, cfg, hidden_pl)),
        np.asarray(logits_for(tf.params, cfg, hidden_jnp)),
        atol=5e-4,
    )
