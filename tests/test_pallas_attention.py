"""Pallas decode kernel vs the jnp reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.attention import (
    decode_attention_reference,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
    _pick_block_t,
    pallas_decode_attention,
)


def _mk(b, hq, hkv, t, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, hq, d), dtype=dtype)
    k = jax.random.normal(ks[1], (b, hkv, t, d), dtype=dtype)
    v = jax.random.normal(ks[2], (b, hkv, t, d), dtype=dtype)
    return q, k, v


def test_pick_block_t():
    assert _pick_block_t(4096) == 512
    assert _pick_block_t(48) == 16
    assert _pick_block_t(33) == 1
    assert _pick_block_t(256, preferred=128) == 128


@pytest.mark.parametrize(
    "b,hq,hkv,t,d,length",
    [
        (1, 8, 2, 64, 16, 10),  # GQA, d needs lane padding
        (1, 8, 1, 128, 128, 128),  # MQA, full cache, aligned d
        (2, 4, 4, 96, 64, 33),  # MHA, batch 2, ragged block
        (1, 4, 4, 256, 96, 200),  # phi3-style d=96
    ],
)
def test_pallas_matches_reference(b, hq, hkv, t, d, length):
    q, k, v = _mk(b, hq, hkv, t, d)
    lengths = jnp.full((b,), length, dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_per_batch_lengths():
    q, k, v = _mk(2, 4, 2, 64, 32)
    lengths = jnp.array([5, 50], dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_ignores_garbage_beyond_length():
    q, k, v = _mk(1, 4, 2, 64, 32)
    lengths = jnp.array([7], dtype=jnp.int32)
    out1 = pallas_decode_attention(q, k, v, lengths, interpret=True)
    k2 = k.at[:, :, 7:].set(1e9)
    v2 = v.at[:, :, 7:].set(-1e9)
    out2 = pallas_decode_attention(q, k2, v2, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_pallas_bf16_inputs():
    q, k, v = _mk(1, 8, 2, 128, 64, dtype=jnp.bfloat16)
    lengths = jnp.array([100], dtype=jnp.int32)
    ref = decode_attention_reference(q, k, v, lengths)
    out = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        atol=3e-2,
    )


def test_pallas_inside_jit_and_grad_free_scan():
    """The kernel must be traceable under jit with traced lengths."""
    q, k, v = _mk(1, 4, 2, 64, 32)

    @jax.jit
    def f(q, k, v, lengths):
        return pallas_decode_attention(q, k, v, lengths, interpret=True)

    out = f(q, k, v, jnp.array([30], dtype=jnp.int32))
    ref = decode_attention_reference(q, k, v, jnp.array([30], dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
