"""int8 KV cache: quantization helpers, kernel parity, engine decode path.

The cache is the dominant per-step stream for many-KV-head models at long
context (phi3: ~0.8 GB/step at 2k); int8 halves it. The decode kernel
never materialises the dequantized cache — K scales fold into scores, V
scales into probabilities — so the Pallas output must match the bf16
kernel run on the dequantized cache to float tolerance.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
    dequant_cache,
    is_quantized,
    is_quantized_cache,
    quantize_kv_cache,
    quantize_kv_vector,
)


def test_kv_cache_quantization_roundtrip():
    key = jax.random.PRNGKey(0)
    k = jax.random.normal(key, (2, 4, 64, 32), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 64, 32), jnp.float32)
    kq, vq = quantize_kv_cache(k, v)
    assert is_quantized_cache(kq) and is_quantized_cache(vq)
    assert kq["q"].dtype == jnp.int8 and kq["s"].shape == (2, 4, 64)
    # per-vector symmetric int8: relative error bounded by 1/127 of the max
    err = np.abs(np.asarray(dequant_cache(kq)) - np.asarray(k))
    bound = np.asarray(kq["s"])[..., None] * 0.5 + 1e-6
    assert (err <= bound).all()


def test_quantized_cache_distinct_from_weight_leaf():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 16), jnp.float32)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        quantize_tensor,
    )

    leaf = quantize_tensor(w)
    assert is_quantized(leaf) and not is_quantized_cache(leaf)
    kq, _ = quantize_kv_cache(
        jnp.zeros((1, 1, 4, 8)), jnp.zeros((1, 1, 4, 8))
    )
    assert is_quantized_cache(kq)


def test_int8_decode_kernel_matches_dequantized_reference():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
        pallas_decode_attention_int8,
    )

    key = jax.random.PRNGKey(2)
    b, hq, hkv, t, d = 2, 8, 2, 256, 128
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(3), (b, hkv, t, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(4), (b, hkv, t, d), jnp.float32)
    lengths = jnp.asarray([100, 256], jnp.int32)
    kq, vq = quantize_kv_cache(k, v)
    got = pallas_decode_attention_int8(
        q, kq["q"], kq["s"], vq["q"], vq["s"], lengths, interpret=True
    )
    want = pallas_decode_attention(
        q, dequant_cache(kq), dequant_cache(vq), lengths, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.fixture(scope="module")
def engines():
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    base = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    kv8 = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, kv_quantize="int8"
    )
    return base, kv8


def test_engine_kv_quantize_generates(engines):
    base, kv8 = engines
    req = GenerationRequest("tiny", "hello quantized cache", max_new_tokens=16)
    r8 = kv8.generate(req)
    rb = base.generate(req)
    assert r8.generated_tokens == rb.generated_tokens == 16
    # greedy decode over a tiny random model: int8 cache noise may flip a
    # late token, but the stream must agree early (same prefill, first
    # token sampled before any quantized read)
    assert r8.tokens[0] == rb.tokens[0]


def test_engine_kv_quantize_stream_matches_monolithic(engines):
    _, kv8 = engines
    req = GenerationRequest("tiny", "stream parity", max_new_tokens=12)
    mono = kv8.generate(req)
    chunks = list(kv8.generate_stream(req, chunk_tokens=4))
    streamed = [t for c in chunks[:-1] for t in c.tokens]
    assert streamed == mono.tokens
    assert chunks[-1].result.tokens == mono.tokens


def test_kv_quantize_guards():
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    with pytest.raises(ValueError, match="kv_quantize"):
        JaxEngine(registry=registry, kv_quantize="int4")
    # ISSUE 9 retired the kv_quantize × speculative exclusion (the last
    # standing ctor rejection): the TARGET cache is int8 — the verify
    # block quantizes per vector exactly like a plain int8 decode step —
    # while the tiny draft cache stays at the engine dtype.
    eng = JaxEngine(
        registry=registry,
        kv_quantize="int8",
        speculative={"a": ("b", 4)},
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.speculative import (
        DraftSpec,
    )

    # ctor tuples normalize to DraftSpec entries (ISSUE 16)
    assert eng.kv_quantize == "int8"
    assert eng.speculative == {"a": DraftSpec("model", "b", 4)}


def test_kv_quantize_composes_with_speculative_decoding():
    """The retired exclusion, pinned by parity (mirroring how ISSUE 7
    retired prefix×int8): solo speculative decode over an int8 target
    cache emits exactly the same engine's plain int8 greedy stream —
    the verify block's per-vector quantization IS the decode step's."""
    tiny = get_model_config("qwen2:1.5b").tiny(max_seq_len=1024)
    registry = {
        "tiny": tiny,
        "tiny-d": dataclasses.replace(tiny, n_layers=1),
    }
    eng = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        kv_quantize="int8",
        speculative={"tiny": ("tiny-d", 4)},
    )
    req = GenerationRequest(
        "tiny", "int8 target, bf16 draft", max_new_tokens=24,
        stop_at_eos=False,
    )
    spec = eng.generate(req)  # greedy → routes through the spec path
    assert "spec" in (spec.extras or {}), spec.extras
    plain = eng._generate_plain(req)
    assert spec.tokens == plain.tokens
    assert spec.text == plain.text
    # batched stepped twin on the int8 PAGED pool, mid-flight joiner incl.
    eng8p = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        kv_quantize="int8",
        paged_kv=True,
        speculative={"tiny": ("tiny-d", 4)},
    )
    joiner = GenerationRequest("tiny", "joins late", max_new_tokens=10, seed=7)
    sess = eng8p.decode_open([req], reserve_rows=4)
    assert sess.spec is not None
    sess.step(4)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {}
    while sess.active:
        for res in sess.step(8):
            results[id(res.request)] = res
    assert results[id(req)].tokens == eng8p._generate_plain(req).tokens
    assert results[id(joiner)].tokens == eng8p._generate_plain(joiner).tokens


def test_kv_quantize_composes_with_prefix_cache():
    """ISSUE 7 retires the int8×prefix exclusion: the solo prefix cache
    stores the PRE-quantization bf16 prompt KV and seeds the next
    request's cache before its post-prefill quantization, so a hit is
    token-identical to the cold path under kv_quantize="int8"."""
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    eng = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        kv_quantize="int8",
        prefix_cache_size=4,
    )
    shared = "system prompt shared by both requests. "
    cold = eng.generate(
        GenerationRequest("tiny", shared + "tail one", max_new_tokens=10)
    )
    assert eng._prefix_cache["tiny"]  # the cold run populated the LRU
    warm = eng.generate(
        GenerationRequest("tiny", shared + "tail one", max_new_tokens=10)
    )
    assert warm.tokens == cold.tokens  # exact-hit parity
    partial = eng.generate(
        GenerationRequest("tiny", shared + "tail two", max_new_tokens=10)
    )
    fresh = JaxEngine(
        registry=registry, dtype=jnp.float32, kv_quantize="int8"
    ).generate(
        GenerationRequest("tiny", shared + "tail two", max_new_tokens=10)
    )
    assert partial.tokens == fresh.tokens  # partial-hit parity


def test_kv_quantize_batch_matches_single(engines):
    """VERDICT round-2 item 3: generate_batch must run with
    kv_quantize="int8", each row token-identical to its own
    single-request quantized decode (per-row scales make rows
    independent)."""
    _, kv8 = engines
    reqs = [
        GenerationRequest("tiny", "batch row one", max_new_tokens=10),
        GenerationRequest("tiny", "a different second row", max_new_tokens=14),
        GenerationRequest("tiny", "and row three", max_new_tokens=7),
    ]
    batch = kv8.generate_batch(reqs)
    singles = [kv8.generate(r) for r in reqs]
    for b_r, s_r in zip(batch, singles):
        assert b_r.tokens == s_r.tokens
        assert b_r.text == s_r.text


def test_kv_quantize_on_tensor_parallel_engine():
    """VERDICT round-2 item 3: the TP engine serves kv_quantize="int8" —
    the {"q","s"} cache pytree gets explicit mesh shardings — with
    tokens matching the single-device quantized engine."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    mesh = build_mesh(MeshSpec.tp_only(2), devices=jax.devices()[:2])
    tp = TensorParallelEngine(
        mesh=mesh,
        registry=dict(registry),
        dtype=jnp.float32,
        kv_quantize="int8",
    )
    single = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, kv_quantize="int8"
    )
    req = GenerationRequest("tiny", "sharded quantized cache", max_new_tokens=12)
    r_tp = tp.generate(req)
    r_single = single.generate(req)
    assert r_tp.tokens == r_single.tokens
    # batched decode on the sharded quantized cache too
    batch = tp.generate_batch([req, req])
    assert batch[0].tokens == r_tp.tokens == batch[1].tokens


def test_quantize_kv_vector_shapes():
    vec = jax.random.normal(jax.random.PRNGKey(5), (3, 4, 32), jnp.float32)
    q, s = quantize_kv_vector(vec)
    assert q.shape == vec.shape and q.dtype == jnp.int8
    assert s.shape == (3, 4)


def test_installed_models_never_evicted(monkeypatch):
    """install_model'ed weights exist only in memory — eviction must never
    pick them (a reload would silently re-randomise a trained model)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        init_params,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import (
        memory as mem,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    cfg_a = get_model_config("qwen2:1.5b").tiny()
    cfg_b = get_model_config("gemma:2b").tiny()
    one = estimate_weight_bytes(cfg_a, None, 4)
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)
    monkeypatch.setenv("TPU_ALLOC_BUDGET_BYTES", str(int(1.7 * one)))
    eng = JaxEngine(registry={"b": cfg_b}, dtype=jnp.float32)
    trained = init_params(cfg_a, jax.random.PRNGKey(7), jnp.float32)
    eng.install_model("trained", cfg_a, trained)
    # loading b would need eviction; 'trained' is pinned, so the budget is
    # simply exceeded rather than the trained weights destroyed
    eng.load_model("b")
    assert "trained" in eng._models
    np.testing.assert_array_equal(
        np.asarray(eng._models["trained"].params["embed"]),
        np.asarray(trained["embed"]),
    )


def test_int8_kernel_engages_for_all_head_dims(monkeypatch):
    """The int8 flash-decode kernel engages for every model, including
    phi3's d_head=96 (the kernel zero-pads the head dim internally).
    Round 4 gated d=96 out after a real-hardware trace abort; round 5
    traced that abort to the kernel's rank-3 scales BlockSpec — Mosaic
    rejected it for EVERY int8-KV shape — and fixed it by shipping
    scales as [B,Hkv,T,1] (real-chip lowering sweep:
    docs/kernel_lowering.jsonl). The gate would have left the KV-heavy
    model kv-quantize exists for on the dequantizing fallback."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    engine = JaxEngine(kv_quantize="int8")
    monkeypatch.setattr(
        JaxEngine, "_specialised_kernels_enabled", lambda self: True
    )
    phi3 = get_model_config("phi3:3.8b")  # d_head 96
    qwen = get_model_config("qwen2:1.5b")  # d_head 128
    assert engine._decode_attention_for_cache(phi3) is not None
    assert engine._decode_attention_for_cache(qwen) is not None


def test_int8_kernel_parity_at_lane_padded_head_dim():
    """Exact-math (interpret) parity for the int8 kernel at d_head=96 —
    the configuration the removed round-4 gate excluded."""
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention_int8,
    )

    b, hq, hkv, t, d = 2, 4, 2, 256, 96
    key = jax.random.PRNGKey(3)
    kq_, kk, kv_, ks_, vs_ = jax.random.split(key, 5)
    q = jax.random.normal(kq_, (b, hq, d), jnp.float32)
    k_q = jax.random.randint(kk, (b, hkv, t, d), -127, 128, jnp.int8)
    v_q = jax.random.randint(kv_, (b, hkv, t, d), -127, 128, jnp.int8)
    k_s = jax.random.uniform(ks_, (b, hkv, t), jnp.float32, 0.01, 0.1)
    v_s = jax.random.uniform(vs_, (b, hkv, t), jnp.float32, 0.01, 0.1)
    lengths = jnp.asarray([256, 33], jnp.int32)
    got = pallas_decode_attention_int8(
        q, k_q, k_s, v_q, v_s, lengths, interpret=True
    )
    kf = k_q.astype(jnp.float32) * k_s[..., None]
    vf = v_q.astype(jnp.float32) * v_s[..., None]
    qg = q.reshape(b, hkv, hq // hkv, d)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kf) / jnp.sqrt(jnp.float32(d))
    mask = jnp.arange(t)[None, :] < lengths[:, None]
    s = jnp.where(mask[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    want = jnp.einsum("bkgt,bktd->bkgd", p, vf).reshape(b, hq, d)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4
    )
