"""Windowed telemetry ring (ISSUE 17): snapshot ingestion, rollup math,
drop-oldest bounds, the exposition ingestion path, and the kill-switch
guarantees of :mod:`obs.timeseries`.

Everything here drives a hand-held clock — no sleeps, no wall time in
any window assertion (the SamplerThread cadence tests use real time
but only assert "ticked at least once", never durations).
"""

import threading

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu import obs
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    MetricsRegistry,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.timeseries import (
    SamplerThread,
    TimeSeriesRing,
    families_from_parsed,
    registry_families,
)


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    yield
    (obs.enable if was else obs.disable)()


@pytest.fixture
def obs_off():
    was = obs.enabled()
    obs.disable()
    yield
    (obs.enable if was else obs.disable)()


def _make_ring(reg, **kw):
    """Ring sampling a private registry with a hand-driven clock."""
    clock = {"t": 0.0}
    ring = TimeSeriesRing(
        source=lambda: registry_families(reg, prefixes=("llm_",)),
        clock=lambda: clock["t"],
        **kw,
    )
    return ring, clock


# -- snapshot sources ---------------------------------------------------------


def test_registry_families_shapes(obs_on):
    reg = MetricsRegistry()
    reg.counter("llm_c", "c").inc(3)
    reg.gauge("llm_g", "g").set(1.5)
    reg.histogram("llm_h", "h", buckets=(0.1, 1.0)).observe(0.5)
    reg.counter("other_c", "excluded by prefix").inc()
    reg.counter("llm_untouched", "no children -> omitted")

    fams = registry_families(reg, prefixes=("llm_",))
    assert set(fams) == {"llm_c", "llm_g", "llm_h"}
    assert fams["llm_c"].kind == "counter"
    assert fams["llm_c"].children["_"] == 3.0
    assert fams["llm_g"].children["_"] == 1.5
    h = fams["llm_h"]
    assert h.bounds == (0.1, 1.0)
    counts, total, count = h.children["_"]
    assert counts == (0, 1, 0)  # per-bucket, +Inf overflow last
    assert (total, count) == (0.5, 1)


def test_families_from_parsed_matches_direct_read(obs_on):
    """The exposition path (the router's fleet ingestion) produces the
    same snapshot shape as the direct registry read."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        parse_exposition,
    )

    reg = MetricsRegistry()
    reg.counter("llm_c", "c", labels=("k",)).labels(k="a").inc(2)
    h = reg.histogram("llm_h", "h", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    direct = registry_families(reg, prefixes=("llm_",))
    parsed = families_from_parsed(
        parse_exposition(reg.exposition()), prefixes=("llm_",)
    )
    assert set(parsed) == set(direct)
    assert parsed["llm_c"].children == direct["llm_c"].children
    assert parsed["llm_h"].bounds == direct["llm_h"].bounds
    assert parsed["llm_h"].children["_"][0] == direct["llm_h"].children["_"][0]
    assert parsed["llm_h"].children["_"][2] == direct["llm_h"].children["_"][2]


# -- windowed rollups ---------------------------------------------------------


def test_counter_window_delta_and_rate(obs_on):
    reg = MetricsRegistry()
    c = reg.counter("llm_reqs_total", "r")
    ring, clock = _make_ring(reg)

    c.inc(10)
    ring.sample_once(now=0.0)
    clock["t"] = 10.0
    c.inc(5)
    ring.sample_once(now=10.0)

    roll = ring.window("llm_reqs_total", 60.0, now=10.0)
    assert roll["kind"] == "counter"
    assert roll["samples"] == 2
    assert roll["children"]["_"] == {"delta": 5.0, "rate": 0.5}


def test_counter_reset_clamps_to_zero(obs_on):
    """A counter that went DOWN inside the window (process restart)
    reports delta 0, not a negative rate."""
    ring = TimeSeriesRing(source=dict, clock=lambda: 0.0)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.timeseries import (
        FamilySample,
    )

    ring.ingest({"llm_c": FamilySample("counter", {"_": 100.0})}, now=0.0)
    ring.ingest({"llm_c": FamilySample("counter", {"_": 3.0})}, now=5.0)
    roll = ring.window("llm_c", 60.0, now=5.0)
    assert roll["children"]["_"]["delta"] == 0.0


def test_absent_family_baseline_is_zero(obs_on):
    """THE delta-0 trap: untouched families are omitted from snapshots,
    so a family first touched mid-window must diff against an all-zeros
    baseline (the window's oldest snapshot), not against its own first
    appearance — otherwise its initial traffic reports delta 0."""
    reg = MetricsRegistry()
    ring, clock = _make_ring(reg)

    ring.sample_once(now=0.0)  # family does not exist yet
    c = reg.counter("llm_late_total", "first touched after baseline")
    c.inc(7)
    clock["t"] = 5.0
    ring.sample_once(now=5.0)

    roll = ring.window("llm_late_total", 60.0, now=5.0)
    assert roll["children"]["_"]["delta"] == 7.0
    assert roll["t0"] == 0.0  # baseline = the window's oldest snapshot


def test_gauge_window_min_mean_max_last(obs_on):
    reg = MetricsRegistry()
    g = reg.gauge("llm_depth", "d")
    ring, clock = _make_ring(reg)
    for t, v in ((0.0, 2.0), (1.0, 8.0), (2.0, 5.0)):
        g.set(v)
        clock["t"] = t
        ring.sample_once(now=t)
    roll = ring.window("llm_depth", 60.0, now=2.0)
    assert roll["children"]["_"] == {
        "min": 2.0,
        "mean": 5.0,
        "max": 8.0,
        "last": 5.0,
    }


def test_histogram_window_quantiles_from_bucket_deltas(obs_on):
    """Quantiles come from the deltas INSIDE the window: observations
    before the window's oldest snapshot must not leak in."""
    reg = MetricsRegistry()
    h = reg.histogram("llm_lat_seconds", "l", buckets=(0.1, 0.2, 0.4))
    ring, clock = _make_ring(reg)

    # 100 slow observations BEFORE the window baseline
    for _ in range(100):
        h.observe(0.39)
    ring.sample_once(now=0.0)
    # 4 fast observations inside the window
    for _ in range(4):
        h.observe(0.05)
    clock["t"] = 10.0
    ring.sample_once(now=10.0)

    roll = ring.window("llm_lat_seconds", 60.0, now=10.0)
    child = roll["children"]["_"]
    assert child["count"] == 4
    assert child["bucket_deltas"] == [4, 0, 0, 0]
    # all windowed mass is in [0, 0.1]: every quantile lands there
    assert 0.0 < child["p99"] <= 0.1
    # lifetime distribution would put p50 near 0.39 — windowing must not
    assert child["p50"] <= 0.1


def test_window_wider_than_history_reports_actual_span(obs_on):
    reg = MetricsRegistry()
    reg.counter("llm_c", "c").inc()
    ring, clock = _make_ring(reg)
    ring.sample_once(now=100.0)
    clock["t"] = 103.0
    ring.sample_once(now=103.0)
    roll = ring.window("llm_c", 3600.0, now=103.0)
    assert roll["window_s"] == 3600.0
    assert roll["span_s"] == 3.0


def test_window_none_for_unknown_family(obs_on):
    reg = MetricsRegistry()
    ring, _ = _make_ring(reg)
    ring.sample_once(now=0.0)
    assert ring.window("llm_never", 60.0, now=0.0) is None


# -- capacity / points / export -----------------------------------------------


def test_drop_oldest_bounds_memory(obs_on):
    reg = MetricsRegistry()
    c = reg.counter("llm_c", "c")
    ring, clock = _make_ring(reg, capacity=4)
    for t in range(10):
        c.inc()
        clock["t"] = float(t)
        ring.sample_once(now=float(t))
    assert len(ring) == 4
    s = ring.summary()
    assert s["capacity"] == 4
    assert s["samples_total"] == 10
    assert s["dropped"] == 6
    assert s["t0"] == 6.0 and s["t1"] == 9.0


def test_points_stride_and_always_include_last(obs_on):
    reg = MetricsRegistry()
    g = reg.gauge("llm_g", "g")
    ring, clock = _make_ring(reg)
    for t in range(11):  # t = 0..10, 1 s apart
        g.set(float(t))
        clock["t"] = float(t)
        ring.sample_once(now=float(t))
    pts = ring.points("llm_g", 60.0, step_s=4.0, now=10.0)
    times = [p["t_s"] for p in pts]
    assert times == [0.0, 4.0, 8.0, 10.0]  # strided, last forced in
    assert pts[-1]["values"]["_"] == 10.0


def test_debug_payload_and_dump_are_jsonable(obs_on):
    import json

    reg = MetricsRegistry()
    reg.counter("llm_c", "c").inc()
    reg.histogram("llm_h", "h", buckets=(1.0,)).observe(0.5)
    ring, clock = _make_ring(reg)
    ring.sample_once(now=0.0)
    clock["t"] = 1.0
    ring.sample_once(now=1.0)

    one = ring.debug_payload(family="llm_h", window_s=60.0, now=1.0)
    assert one["rollup"]["kind"] == "histogram"
    assert one["points"]
    every = ring.debug_payload(window_s=60.0, now=1.0)
    assert set(every["families"]) == {"llm_c", "llm_h"}
    missing = ring.debug_payload(family="llm_nope", window_s=60.0, now=1.0)
    assert "error" in missing
    dump = ring.dump()
    assert len(dump["snapshots"]) == 2
    json.dumps(one), json.dumps(every), json.dumps(dump)


def test_ingest_text_roundtrip_window(obs_on):
    """Exposition-fed ring (the router's path) computes the same counter
    delta as the direct path."""
    reg = MetricsRegistry()
    c = reg.counter("llm_c", "c")
    ring = TimeSeriesRing(source=dict, clock=lambda: 0.0)
    c.inc(2)
    ring.ingest_text(reg.exposition(), now=0.0)
    c.inc(3)
    ring.ingest_text(reg.exposition(), now=10.0)
    roll = ring.window("llm_c", 60.0, now=10.0)
    assert roll["children"]["_"] == {"delta": 3.0, "rate": 0.3}


def test_ingest_text_tolerates_garbage(obs_on):
    ring = TimeSeriesRing(source=dict, clock=lambda: 0.0)
    snap = ring.ingest_text("not { an exposition ]][", now=0.0)
    assert snap is not None and snap.families == {}


# -- kill switch --------------------------------------------------------------


def test_ring_is_inert_when_disabled(obs_off):
    calls = []

    def source():
        calls.append(1)
        return {}

    ring = TimeSeriesRing(source=source, clock=lambda: 0.0)
    assert ring.sample_once() is None
    assert ring.ingest({}, now=0.0) is None
    assert ring.ingest_text("llm_c 1", now=0.0) is None
    assert calls == []  # the source was never even invoked
    assert len(ring) == 0


def test_sampler_refuses_start_when_disabled(obs_off):
    ticks = []
    s = SamplerThread(lambda: ticks.append(1), interval_s=0.01)
    assert s.start() is False
    assert not s.running
    s.stop()
    assert ticks == []


def test_sampler_ticks_baseline_immediately_then_on_cadence(obs_on):
    """start() must produce a baseline tick right away (window deltas
    subtract the oldest snapshot) and keep ticking until stop()."""
    first = threading.Event()
    third = threading.Event()
    ticks = []

    def tick():
        ticks.append(1)
        first.set()
        if len(ticks) >= 3:
            third.set()

    s = SamplerThread(tick, interval_s=0.01, name="test-sampler")
    assert s.start() is True
    assert s.start() is True  # idempotent
    assert first.wait(5.0)
    assert third.wait(5.0)
    s.stop()
    assert not s.running
    n = len(ticks)
    s.stop()  # idempotent
    assert len(ticks) == n  # no ticks after stop


def test_sampler_tick_exceptions_do_not_kill_the_loop(obs_on):
    done = threading.Event()
    ticks = []

    def tick():
        ticks.append(1)
        if len(ticks) >= 3:
            done.set()
        raise RuntimeError("telemetry must not kill serving")

    s = SamplerThread(tick, interval_s=0.01)
    s.start()
    assert done.wait(5.0)
    s.stop()
