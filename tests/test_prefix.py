"""Shared-prefix copy-on-write paging (ISSUE 7): PagePool refcounts,
the session-scoped PrefixIndex, and stepped-session integration.

The contracts under test:

- refcounted pages: a page is recycled only when its LAST reader frees
  it; every pre-existing free site (retire/cancel/abort/close) keeps
  its exact-free-count behavior whether or not pages are shared;
- joiners whose prompt shares a published prefix map its read-only
  pages (billed ONCE), seed the boundary positions (CoW), chunk-prefill
  only the divergent tail — and stay TOKEN-IDENTICAL to their solo
  ``generate()`` on all four cache layouts;
- N sharers admitted then all retired (eos / budget / cancelled)
  restore the pool free-count EXACTLY; close() restores it fully
  (index references released last).
"""

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
    PagePool,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.prefix import (
    PREFIX_COW_COPIES_C,
    PREFIX_HIT_TOKENS_C,
    PREFIX_SHARED_PAGES_G,
    PrefixIndex,
    common_prefix_len,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)

# 140 's' chars -> 141 ids (BOS + bytes): one FULL 128-token page plus a
# 13-token partial — every sharer exercises both the page mapping and
# the copy-on-write boundary.
SHARED = "s" * 140


@pytest.fixture(scope="module")
def registry():
    return {"tiny": get_model_config("qwen2:1.5b").tiny(max_seq_len=512)}


def _engine(registry, paged=True, kv=None, share=True, **kw):
    return JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=paged,
        kv_quantize=kv,
        prefix_share=share,
        **kw,
    )


def _drain(session, max_steps=8, limit=400):
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


# -- PagePool refcounts --------------------------------------------------------


def _tiny_pool(n_pages=8):
    return PagePool.create(
        n_layers=1, n_pages=n_pages, n_kv_heads=1, d_head=4, page_size=128
    )


def test_pool_share_defers_recycling_to_last_reader():
    pool = _tiny_pool()
    pages = pool.alloc(2)
    free0 = pool.free_pages
    pool.share(pages)  # second reader
    assert pool.refcount(pages[0]) == 2
    assert pool.shared_pages == 2
    pool.free(pages)  # first reader leaves — pages stay allocated
    assert pool.free_pages == free0
    assert pool.shared_pages == 0
    pool.free(pages)  # last reader leaves — NOW they recycle
    assert pool.free_pages == free0 + 2
    assert pool.refcount(pages[0]) == 0


def test_pool_double_free_and_share_free_raise():
    pool = _tiny_pool()
    pages = pool.alloc(1)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free(pages)
    with pytest.raises(ValueError, match="share a free page"):
        pool.share(pages)


# -- PrefixIndex ---------------------------------------------------------------


def test_index_longest_match_and_partial_common():
    idx = PrefixIndex(capacity=4)
    idx.publish([1, 2, 3, 4], [], None, None)
    idx.publish([1, 2, 9], [], None, None)
    entry, common = idx.match([1, 2, 3, 5, 6])
    assert entry.ids == [1, 2, 3, 4] and common == 3
    assert idx.match([7, 8]) is None
    assert common_prefix_len([1, 2], [1, 2, 3]) == 2


def test_index_capacity_evicts_lru_and_releases_pages():
    pool = _tiny_pool(n_pages=8)
    idx = PrefixIndex(capacity=2)
    a, b, c = pool.alloc(1), pool.alloc(1), pool.alloc(1)
    free0 = pool.free_pages
    idx.publish([1, 1], a, None, None, pool)
    idx.publish([2, 2], b, None, None, pool)
    # touch [1,1] so [2,2] is the LRU victim when [3,3] lands
    entry, _ = idx.match([1, 1, 5])
    idx.touch(entry)
    idx.publish([3, 3], c, None, None, pool)
    assert len(idx) == 2
    assert {tuple(e.ids) for e in idx._entries} == {(1, 1), (3, 3)}
    # the victim's index reference released; owner still holds b
    assert pool.refcount(b[0]) == 1
    assert pool.free_pages == free0
    idx.release_all(pool)
    for pages in (a, b, c):
        pool.free(pages)
    assert pool.free_pages == 8


def test_index_publish_supersedes_covered_entries():
    idx = PrefixIndex(capacity=8)
    idx.publish([1, 2], [], None, None)
    idx.publish([1, 2, 3, 4], [], None, None)  # covers [1,2] — supersedes
    assert len(idx) == 1 and idx._entries[0].ids == [1, 2, 3, 4]
    # re-publishing a covered prefix refreshes the covering entry instead
    assert idx.publish([1, 2, 3], [], None, None) is False
    assert len(idx) == 1


# -- session integration: sharing, parity, exact accounting --------------------


@pytest.mark.parametrize("kv", [None, "int8"], ids=["bf16", "int8"])
def test_sharers_map_pages_and_match_solo_exactly(registry, kv):
    """The tentpole invariant on both paged pools: sharers map the
    anchor's read-only prefix page (fewer pages off the free list than
    a full allocation), every stream is bit-identical to solo
    generate(), all-sharers-retired restores the free count EXACTLY,
    and close() restores the pool fully (index refs released last)."""
    eng = _engine(registry, kv=kv)
    plain = _engine(registry, kv=kv, share=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor tail", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert len(sess.prefix) == 1  # the anchor published at open
    sess.step(4)
    free_before = sess.pool.free_pages
    j1 = GenerationRequest("tiny", SHARED + " j-one", max_new_tokens=8, seed=3)
    j2 = GenerationRequest("tiny", SHARED + " j-two!!", max_new_tokens=8, seed=4)
    assert sess.can_join(j1)
    pj = sess.join_begin(j1, chunk_tokens=32)
    assert pj.hit_tokens == 142  # BOS + 140 shared chars + ' '
    assert pj.shared_pages == 1  # one full page mapped read-only
    assert sess.pool.refcount(pj.pages[0]) >= 3  # anchor + index + j1
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    sess.join(j2)  # the one-shot join path shares too
    results = {}
    while len(results) < 2:  # both sharers retire; anchor still live
        for res in sess.step(8):
            results[id(res.request)] = res
    assert sess.active == 1
    assert sess.pool.free_pages == free_before  # exact restoration
    for res in _drain(sess):
        results[id(res.request)] = res
    for r in (anchor, j1, j2):
        assert results[id(r)].tokens == plain.generate(r).tokens
    total = sess.pool.n_pages
    sess.close()
    assert sess.pool.free_pages == total - 1  # only parking stays held


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_cow_divergence_mid_page_parity_all_layouts(registry, paged, kv):
    """A joiner diverging MID-PAGE (141 shared ids = 1 full page + 13
    partial) seeds the boundary from the index and recomputes only the
    tail — token parity with solo generate() on all four cache layouts
    (paged pools share pages; contiguous sessions get seed-only reuse)."""
    eng = _engine(registry, paged=paged, kv=kv)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(4)
    joiner = GenerationRequest(
        "tiny", SHARED + " divergent continuation", max_new_tokens=12, seed=9
    )
    hits0 = PREFIX_HIT_TOKENS_C.labels().value
    pj = sess.join_begin(joiner, chunk_tokens=32)
    assert pj.hit_tokens > 0
    assert PREFIX_HIT_TOKENS_C.labels().value - hits0 == pj.hit_tokens
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    ref = _engine(registry, paged=paged, kv=kv, share=False)
    assert results[id(anchor)].tokens == ref.generate(anchor).tokens
    assert results[id(joiner)].tokens == ref.generate(joiner).tokens


def test_cow_copy_counted_and_shared_pages_gauge(registry):
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    cow0 = PREFIX_COW_COPIES_C.labels().value
    sess.join(GenerationRequest("tiny", SHARED + " q", max_new_tokens=6, seed=2))
    # hit 142 tokens > 1 shared page * 128 -> the partial page was CoW'd
    assert PREFIX_COW_COPIES_C.labels().value == cow0 + 1
    assert PREFIX_SHARED_PAGES_G.labels().value >= 1
    _drain(sess)
    sess.close()
    assert PREFIX_SHARED_PAGES_G.labels().value == 0


def test_cancelled_sharer_restores_shared_refs_exactly(registry):
    """Cancellation (the disconnect/deadline retirement path) drops
    exactly one reference per mapped page — the ISSUE 6 exact page-free
    accounting composes with sharing."""
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(4)
    free0 = sess.pool.free_pages
    victim = GenerationRequest(
        "tiny", SHARED + " cancelled", max_new_tokens=60,
        stop_at_eos=False, seed=5,
    )
    sess.join(victim)
    shared_page = sess.prefix._entries[0].pages[0]
    refs_mid = sess.pool.refcount(shared_page)
    sess.step(4)
    assert sess.cancel(victim)
    assert sess.pool.free_pages == free0
    assert sess.pool.refcount(shared_page) == refs_mid - 1
    _drain(sess)
    sess.close()


def test_join_abort_restores_shared_refs(registry):
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    free0 = sess.pool.free_pages
    pj = sess.join_begin(
        GenerationRequest("tiny", SHARED + " aborted", max_new_tokens=8, seed=6),
        chunk_tokens=32,
    )
    assert pj.shared_pages == 1 and sess.pool.free_pages < free0
    sess.join_abort(pj)
    assert sess.pool.free_pages == free0
    _drain(sess)
    sess.close()


def test_can_join_bills_shared_pages_once(registry):
    """Admission billing: with the free list squeezed to exactly the
    DIVERGENT-TAIL pages, a sharer still fits (its prefix pages are
    billed once, to the publisher) while an equal-shape non-sharer is
    deferred."""
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sharer = GenerationRequest(
        "tiny", SHARED + " sq", max_new_tokens=8, seed=7
    )
    stranger = GenerationRequest(
        "tiny", "x" * 140 + " sq", max_new_tokens=8, seed=7
    )
    # same shape, same page need — only the prefix differs
    need = sess._pages_needed(145, 8)
    hog = sess.pool.alloc(sess.pool.free_pages - (need - 1))
    assert sess.can_join(sharer)  # needs need-1 own pages (1 shared)
    assert not sess.can_join(stranger)  # needs all `need` pages
    sess.pool.free(hog)
    _drain(sess)
    sess.close()


def test_joiner_publish_is_page_capped_but_seeds_grow(registry):
    """A joiner's commit publishes its prompt for future SEED reuse but
    references only the already-shared pages — its own tail pages die
    with it (that is what keeps sharers' retirement exact). A later
    joiner matching the longer prompt seeds MORE tokens than the
    anchor-only match would give."""
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    long_tail = SHARED + " shared-second-stage continuation body"
    j1 = GenerationRequest("tiny", long_tail + " one", max_new_tokens=6, seed=2)
    sess.join(j1)
    assert len(sess.prefix) == 2
    j1_entry = next(
        e for e in sess.prefix._entries if len(e.ids) > len(SHARED) + 10
    )
    assert len(j1_entry.pages) == 1  # capped at the shared region
    j2 = GenerationRequest("tiny", long_tail + " two", max_new_tokens=6, seed=3)
    pj = sess.join_begin(j2, chunk_tokens=32)
    assert pj.hit_tokens > 142  # seeded past the anchor's common prefix
    assert pj.shared_pages == 1
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    ref = _engine(registry, share=False)
    for r in (j1, j2):
        assert results[id(r)].tokens == ref.generate(r).tokens
    sess.close()


def test_contiguous_index_has_no_pages_and_close_clears(registry):
    eng = _engine(registry, paged=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=24,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert len(sess.prefix) == 1
    assert sess.prefix._entries[0].pages == []
    assert sess.debug_state()["prefix"]["entries"] == 1
    _drain(sess)
    sess.close()
    assert len(sess.prefix) == 0


def test_prefix_share_off_is_default_and_inert(registry):
    eng = JaxEngine(registry=dict(registry), dtype=jnp.float32, paged_kv=True)
    assert eng.prefix_share is False
    sess = eng.decode_open(
        [GenerationRequest("tiny", SHARED + " a", max_new_tokens=6, seed=1)]
    )
    assert sess.prefix is None
    assert "prefix" not in sess.debug_state()
    _drain(sess)
    sess.close()


def test_max_admission_rows_bills_shared_prefix_once(registry, monkeypatch):
    """The budget-aware admission estimate admits a LARGER fleet under
    prefix sharing: sharers are billed only their divergent-tail pages,
    so the same KV budget caps more rows."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine import (
        jax_engine as je,
    )

    req = GenerationRequest(
        "tiny", "s" * 600, max_new_tokens=8, stop_at_eos=False
    )
    share_eng = _engine(registry)
    plain_eng = _engine(registry, share=False)
    cfg = share_eng.registry["tiny"]
    # 601 prompt ids + 8 generation tokens -> 5 legacy pages per row;
    # 4 of them shared. Budget sized to EXACTLY the shared bill of one
    # 64-row chunk (anchor pays 5, every sharer 1): the full bill
    # (64 x 5 pages) blows it and stays at the 32-row floor.
    need = -(-(601 + 8) // 128)
    g_bucket = je._bucket(8, je.GEN_BUCKETS)
    budget = plain_eng._paged_chunk_bytes(
        cfg, [need] + [1] * 63, 64, g_bucket, False
    )
    monkeypatch.setattr(je, "BATCH_KV_BUDGET_BYTES", int(budget))
    assert plain_eng.max_admission_rows(req) == 32  # full bill: floor
    assert share_eng.max_admission_rows(req) == 64  # shared billed once


def test_engine_validates_prefix_index_entries(registry):
    with pytest.raises(ValueError, match="prefix_index_entries"):
        JaxEngine(registry=dict(registry), prefix_index_entries=0)
