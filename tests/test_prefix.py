"""Shared-prefix paging over the ENGINE-level prefix store (ISSUE 7 →
ISSUE 14): PagePool refcounts, store-backed stepped-session integration,
and the store × preemption interaction.

The contracts under test:

- refcounted pages: a page is recycled only when its LAST reader frees
  it; every pre-existing free site (retire/cancel/abort/close) keeps
  its exact-free-count behavior whether or not pages are shared;
- joiners whose prompt shares a published prefix map the STORE's
  read-only pages (billed ONCE), seed the boundary positions (CoW),
  chunk-prefill only the divergent tail — and stay TOKEN-IDENTICAL to
  their solo ``generate()`` on all four cache layouts;
- publication is PAGE-BACKED and UNCAPPED (ISSUE 14): a joiner's own
  divergent-tail pages are adopted by the store, so a second-generation
  sharer maps them read-only too; the store's holdings survive sharer
  retirement, and the pool free-count accounts for them exactly;
- the store OUTLIVES the session: a joiner in a FRESH session (prior
  session closed — its pool dead) still hits, restoring spilled pages
  into the new pool, and close() leaves the old pool fully free (only
  the parking page held);
- a preemption victim whose row maps store-shared pages releases them
  at preempt and re-shares them from the store at resume; a store that
  moved on (eviction) degrades the resume to recompute.

The radix-tree data structure itself (splitting, budgets, spill and
restore arithmetic) is pinned in tests/test_radix_store.py.
"""

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
    PagePool,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.prefix import (
    PREFIX_COW_COPIES_C,
    PREFIX_HIT_TOKENS_C,
    PREFIX_SHARED_PAGES_G,
    common_prefix_len,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.radix_store import (
    STORE_HITS_C,
    STORE_RESTORES_C,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)

# 140 's' chars -> 141 ids (BOS + bytes): one FULL 128-token page plus a
# 13-token partial — every sharer exercises both the page mapping and
# the copy-on-write boundary.
SHARED = "s" * 140


@pytest.fixture(scope="module")
def registry():
    return {"tiny": get_model_config("qwen2:1.5b").tiny(max_seq_len=512)}


def _engine(registry, paged=True, kv=None, share=True, **kw):
    return JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=paged,
        kv_quantize=kv,
        prefix_share=share,
        **kw,
    )


def _drain(session, max_steps=8, limit=400):
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


# -- PagePool refcounts --------------------------------------------------------


def _tiny_pool(n_pages=8):
    return PagePool.create(
        n_layers=1, n_pages=n_pages, n_kv_heads=1, d_head=4, page_size=128
    )


def test_pool_share_defers_recycling_to_last_reader():
    pool = _tiny_pool()
    pages = pool.alloc(2)
    free0 = pool.free_pages
    pool.share(pages)  # second reader
    assert pool.refcount(pages[0]) == 2
    assert pool.shared_pages == 2
    pool.free(pages)  # first reader leaves — pages stay allocated
    assert pool.free_pages == free0
    assert pool.shared_pages == 0
    pool.free(pages)  # last reader leaves — NOW they recycle
    assert pool.free_pages == free0 + 2
    assert pool.refcount(pages[0]) == 0


def test_pool_double_free_and_share_free_raise():
    pool = _tiny_pool()
    pages = pool.alloc(1)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free(pages)
    with pytest.raises(ValueError, match="share a free page"):
        pool.share(pages)


def test_common_prefix_len():
    assert common_prefix_len([1, 2], [1, 2, 3]) == 2
    assert common_prefix_len([1, 2, 3], [1, 9]) == 1
    assert common_prefix_len([7], [8]) == 0


# -- session integration: sharing, parity, exact accounting --------------------


@pytest.mark.parametrize("kv", [None, "int8"], ids=["bf16", "int8"])
def test_sharers_map_pages_and_match_solo_exactly(registry, kv):
    """The core invariant on both paged pools: sharers map the anchor's
    read-only prefix page (fewer pages off the free list than a full
    allocation), every stream is bit-identical to solo generate(),
    sharer retirement returns everything except what the STORE adopted
    (page-backed tail publication — accounted exactly), and close()
    restores the pool fully (store nodes spill; only parking held)."""
    eng = _engine(registry, kv=kv)
    plain = _engine(registry, kv=kv, share=False)
    store = eng.prefix_store
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor tail", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert store.debug_state()["nodes"] == 1  # the anchor published
    sess.step(4)
    free_before = sess.pool.free_pages
    held_before = store.hbm_pages_held
    j1 = GenerationRequest("tiny", SHARED + " j-one", max_new_tokens=8, seed=3)
    j2 = GenerationRequest("tiny", SHARED + " j-two!!", max_new_tokens=8, seed=4)
    assert sess.can_join(j1)
    pj = sess.join_begin(j1, chunk_tokens=32)
    assert pj.hit_tokens == 142  # BOS + 140 shared chars + ' '
    assert pj.shared_pages == 1  # one full page mapped read-only
    assert sess.pool.refcount(pj.pages[0]) >= 3  # anchor + store + j1
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    sess.join(j2)  # the one-shot join path shares too
    results = {}
    while len(results) < 2:  # both sharers retire; anchor still live
        for res in sess.step(8):
            results[id(res.request)] = res
    assert sess.active == 1
    # exact accounting under UNCAPPED publication: the store adopts a
    # sharer's full-page-aligned TAIL pages (here the short tails span
    # no full page, so adopted == 0 and restoration is exact like PR 7;
    # test_joiner_tail_pages_published_for_second_generation pins the
    # adopted > 0 shape) — everything else recycled
    adopted = store.hbm_pages_held - held_before
    assert sess.pool.free_pages == free_before - adopted
    for res in _drain(sess):
        results[id(res.request)] = res
    for r in (anchor, j1, j2):
        assert results[id(r)].tokens == plain.generate(r).tokens
    total = sess.pool.n_pages
    sess.close()
    # detach spilled every store node out of this pool: free-count
    # exactly restored, only the parking page stays held
    assert sess.pool.free_pages == total - 1
    assert store.hbm_pages_held == 0


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_cow_divergence_mid_page_parity_all_layouts(registry, paged, kv):
    """A joiner diverging MID-PAGE (141 shared ids = 1 full page + 13
    partial) seeds the boundary from the store and recomputes only the
    tail — token parity with solo generate() on all four cache layouts
    (paged pools share pages; contiguous sessions get seed-only reuse)."""
    eng = _engine(registry, paged=paged, kv=kv)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(4)
    joiner = GenerationRequest(
        "tiny", SHARED + " divergent continuation", max_new_tokens=12, seed=9
    )
    hits0 = PREFIX_HIT_TOKENS_C.labels().value
    pj = sess.join_begin(joiner, chunk_tokens=32)
    assert pj.hit_tokens > 0
    assert PREFIX_HIT_TOKENS_C.labels().value - hits0 == pj.hit_tokens
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    ref = _engine(registry, paged=paged, kv=kv, share=False)
    assert results[id(anchor)].tokens == ref.generate(anchor).tokens
    assert results[id(joiner)].tokens == ref.generate(joiner).tokens


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_fresh_session_joiner_hits_cross_session(registry, paged, kv):
    """THE ISSUE-14 acceptance path on all four layouts: the publishing
    session CLOSES (its pool dies), a new session opens, and a joiner
    whose prompt shares the published prefix still hits — paged pools
    restore the spilled pages into the NEW pool and map them read-only
    (restore counter moves), contiguous sessions seed from the host
    slab — token-for-token equal to solo generate()."""
    eng = _engine(registry, paged=paged, kv=kv)
    plain = _engine(registry, paged=paged, kv=kv, share=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=24,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    _drain(sess)
    sess.close()
    # fresh session, fresh pool; anchor sized so the joiner fits
    a2 = GenerationRequest(
        "tiny", "x" * 170 + " new session anchor", max_new_tokens=24,
        stop_at_eos=False, seed=2,
    )
    sess2 = eng.decode_open([a2], reserve_rows=4)
    sess2.step(2)
    joiner = GenerationRequest(
        "tiny", SHARED + " cross-session tail", max_new_tokens=10, seed=7
    )
    hits0 = STORE_HITS_C.labels().value
    restores0 = STORE_RESTORES_C.labels().value
    assert sess2.can_join(joiner)
    pj = sess2.join_begin(joiner, chunk_tokens=32)
    assert pj.hit_tokens > 0, "no cross-session hit"
    assert STORE_HITS_C.labels().value == hits0 + 1
    if paged:
        assert pj.shared_pages >= 1, "store pages not mapped in new pool"
        assert STORE_RESTORES_C.labels().value > restores0
    while not sess2.join_step(pj):
        pass
    sess2.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess2)}
    assert results[id(joiner)].tokens == plain.generate(joiner).tokens
    assert results[id(a2)].tokens == plain.generate(a2).tokens
    total = sess2.pool.n_pages if paged else None
    sess2.close()
    if paged:
        assert sess2.pool.free_pages == total - 1


def test_cow_copy_counted_and_shared_pages_gauge(registry):
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    cow0 = PREFIX_COW_COPIES_C.labels().value
    sess.join(GenerationRequest("tiny", SHARED + " q", max_new_tokens=6, seed=2))
    # hit 142 tokens > 1 shared page * 128 -> the partial page was CoW'd
    assert PREFIX_COW_COPIES_C.labels().value == cow0 + 1
    assert PREFIX_SHARED_PAGES_G.labels().value >= 1
    _drain(sess)
    sess.close()
    assert PREFIX_SHARED_PAGES_G.labels().value == 0


def test_cancelled_sharer_restores_shared_refs_exactly(registry):
    """Cancellation (the disconnect/deadline retirement path) drops
    exactly one reference per mapped page — the ISSUE 6 exact page-free
    accounting composes with store sharing. The cancelled sharer never
    commits, so the store adopts nothing from it."""
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(4)
    victim = GenerationRequest(
        "tiny", SHARED + " cancelled", max_new_tokens=60,
        stop_at_eos=False, seed=5,
    )
    ids = sess.tok.encode(victim.prompt)
    shared_page = eng.prefix_store.hbm_run("tiny", ids)[0]
    free0 = sess.pool.free_pages
    held0 = eng.prefix_store.hbm_pages_held
    refs0 = sess.pool.refcount(shared_page)
    sess.join(victim)
    # the one-shot join COMMITTED → its tail pages were adopted by the
    # store (page-backed publication); the mapping added one reference
    adopted = eng.prefix_store.hbm_pages_held - held0
    assert sess.pool.refcount(shared_page) == refs0 + 1
    sess.step(4)
    assert sess.cancel(victim)
    # cancel returns the row's OWN references; the store keeps its
    # adopted tail pages (that is the uncapped-publication point)
    assert sess.pool.free_pages == free0 - adopted
    assert sess.pool.refcount(shared_page) == refs0
    _drain(sess)
    sess.close()


def test_join_abort_restores_shared_refs(registry):
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    free0 = sess.pool.free_pages
    pj = sess.join_begin(
        GenerationRequest("tiny", SHARED + " aborted", max_new_tokens=8, seed=6),
        chunk_tokens=32,
    )
    assert pj.shared_pages == 1 and sess.pool.free_pages < free0
    sess.join_abort(pj)
    assert sess.pool.free_pages == free0
    _drain(sess)
    sess.close()


def test_can_join_bills_shared_pages_once(registry):
    """Admission billing: with the free list squeezed to exactly the
    DIVERGENT-TAIL pages, a sharer still fits (its prefix pages are
    billed once, to the store) while an equal-shape non-sharer is
    deferred."""
    eng = _engine(registry)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=60,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sharer = GenerationRequest(
        "tiny", SHARED + " sq", max_new_tokens=8, seed=7
    )
    stranger = GenerationRequest(
        "tiny", "x" * 140 + " sq", max_new_tokens=8, seed=7
    )
    # same shape, same page need — only the prefix differs
    need = sess._pages_needed(145, 8)
    hog = sess.pool.alloc(sess.pool.free_pages - (need - 1))
    assert sess.can_join(sharer)  # needs need-1 own pages (1 shared)
    assert not sess.can_join(stranger)  # needs all `need` pages
    sess.pool.free(hog)
    _drain(sess)
    sess.close()


def test_joiner_tail_pages_published_for_second_generation():
    """ISSUE 14 retires PR 7's page cap: a joiner's commit publishes
    its own divergent-tail pages, so a SECOND-generation sharer
    matching the longer prompt maps MORE pages than the anchor-only
    match would give — not just more seeded tokens."""
    wide = {"tiny": get_model_config("qwen2:1.5b").tiny(max_seq_len=1024)}
    eng = _engine(wide)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    # long enough that j1's divergent tail itself spans a full page
    # (262 ids: full pages [1, 2) are PAST the anchor's shared page)
    long_tail = SHARED + " stage " + "t" * 110
    j1 = GenerationRequest("tiny", long_tail + " one", max_new_tokens=6, seed=2)
    sess.join(j1)
    j2 = GenerationRequest("tiny", long_tail + " two", max_new_tokens=6, seed=3)
    pj = sess.join_begin(j2, chunk_tokens=32)
    assert pj.hit_tokens > 142  # seeded past the anchor's common prefix
    assert pj.shared_pages >= 2  # j1's tail page mapped too (uncapped)
    while not sess.join_step(pj):
        pass
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    ref = _engine(wide, share=False)
    for r in (j1, j2):
        assert results[id(r)].tokens == ref.generate(r).tokens
    sess.close()


def test_contiguous_store_survives_close(registry):
    eng = _engine(registry, paged=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=24,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    store_state = sess.debug_state()["prefix_store"]
    assert store_state["nodes"] == 1
    assert store_state["hbm_pages"] == 0  # contiguous: seed-only nodes
    _drain(sess)
    sess.close()
    # the ENGINE store outlives the session (the ISSUE 14 point)
    assert eng.prefix_store.debug_state()["nodes"] == 1
    assert eng.prefix_store.debug_state()["host_bytes"] > 0


def test_prefix_share_off_is_default_and_inert(registry):
    eng = JaxEngine(registry=dict(registry), dtype=jnp.float32, paged_kv=True)
    assert eng.prefix_share is False
    assert eng.prefix_store is None
    sess = eng.decode_open(
        [GenerationRequest("tiny", SHARED + " a", max_new_tokens=6, seed=1)]
    )
    assert sess.store is None
    assert "prefix_store" not in sess.debug_state()
    _drain(sess)
    sess.close()


def test_max_admission_rows_bills_shared_prefix_once(registry, monkeypatch):
    """The budget-aware admission estimate admits a LARGER fleet under
    prefix sharing: sharers are billed only their divergent-tail pages,
    so the same KV budget caps more rows."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine import (
        jax_engine as je,
    )

    req = GenerationRequest(
        "tiny", "s" * 600, max_new_tokens=8, stop_at_eos=False
    )
    share_eng = _engine(registry)
    plain_eng = _engine(registry, share=False)
    cfg = share_eng.registry["tiny"]
    # 601 prompt ids + 8 generation tokens -> 5 legacy pages per row;
    # 4 of them shared. Budget sized to EXACTLY the shared bill of one
    # 64-row chunk (anchor pays 5, every sharer 1): the full bill
    # (64 x 5 pages) blows it and stays at the 32-row floor.
    need = -(-(601 + 8) // 128)
    g_bucket = je._bucket(8, je.GEN_BUCKETS)
    budget = plain_eng._paged_chunk_bytes(
        cfg, [need] + [1] * 63, 64, g_bucket, False
    )
    monkeypatch.setattr(je, "BATCH_KV_BUDGET_BYTES", int(budget))
    assert plain_eng.max_admission_rows(req) == 32  # full bill: floor
    assert share_eng.max_admission_rows(req) == 64  # shared billed once


def test_engine_validates_prefix_knobs(registry):
    with pytest.raises(ValueError, match="prefix_index_entries"):
        JaxEngine(registry=dict(registry), prefix_index_entries=0)
    with pytest.raises(ValueError, match="prefix_store_hbm_bytes"):
        JaxEngine(registry=dict(registry), prefix_store_hbm_bytes=-1)
    with pytest.raises(ValueError, match="prefix_store_host_bytes"):
        JaxEngine(registry=dict(registry), prefix_store_host_bytes=-1)
    with pytest.raises(ValueError, match="scope"):
        JaxEngine(
            registry=dict(registry),
            prefix_share=True,
            prefix_store_scope="both",
        )


# -- store × preemption interaction (ISSUE 14 satellite) -----------------------


def test_preempted_sharer_releases_and_reshares_store_pages(registry):
    """A victim whose row maps store-shared pages preempts correctly:
    the shared pages are RELEASED (never swapped — the store and other
    readers keep them device-resident), its own pages spill, and the
    resume re-shares the same store pages — the continued stream is
    bit-identical to an uninterrupted run."""
    eng = _engine(registry)
    plain = _engine(registry, share=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    victim = GenerationRequest(
        "tiny", SHARED + " victim tail", max_new_tokens=24,
        stop_at_eos=False, seed=5,
    )
    sess.join(victim)
    sess.step(4)
    free_mid = sess.pool.free_pages
    shared_page = sess.rows[
        next(r for r, row in enumerate(sess.rows)
             if row is not None and row.request is victim)
    ].pages[0]
    refs_live = sess.pool.refcount(shared_page)
    pr = sess.preempt(victim, policy="swap")
    assert pr is not None
    assert pr.shared_pages == [shared_page]
    # the shared page was released (one ref down), own pages swapped out
    assert sess.pool.refcount(shared_page) == refs_live - 1
    assert pr.blob is not None and pr.n_own_pages >= 1
    sess.step(2)
    assert sess.can_resume(pr)
    pending = sess.resume_begin(pr)
    while not sess.join_step(pending):
        pass
    sess.join_commit(pending)
    assert sess.pool.refcount(shared_page) == refs_live  # re-shared
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(victim)].tokens == plain.generate(victim).tokens
    assert free_mid  # silence lint; the real invariant is parity above
    sess.close()


def test_preempt_resume_degrades_to_recompute_after_store_eviction(registry):
    """Eviction-degrades-to-recompute: while the victim is parked the
    store's tree for its prefix is dropped — the resume plan can no
    longer verify the released shared pages and falls back to a full
    re-prefill, still token-exact."""
    eng = _engine(registry)
    plain = _engine(registry, share=False)
    anchor = GenerationRequest(
        "tiny", SHARED + " anchor", max_new_tokens=90,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    victim = GenerationRequest(
        "tiny", SHARED + " victim tail", max_new_tokens=24,
        stop_at_eos=False, seed=5,
    )
    sess.join(victim)
    sess.step(4)
    pr = sess.preempt(victim, policy="swap")
    assert pr is not None and pr.shared_pages
    # the store moves on: every node evicted (refs released)
    eng.prefix_store.release_all()
    plan = sess._resume_plan(pr)
    assert plan is not None and plan["mode"] == "recompute"
    assert sess.can_resume(pr)
    pending = sess.resume_begin(pr)
    while not sess.join_step(pending):
        pass
    sess.join_commit(pending)
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(victim)].tokens == plain.generate(victim).tokens
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - 1
