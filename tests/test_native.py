"""Native C++ sampler: build, sample, bind, profiler integration."""

import time

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.native.build import (
    load_sampler_library,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.native_host import (
    NativeHostProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import RunContext

lib = load_sampler_library()
pytestmark = pytest.mark.skipif(lib is None, reason="no native toolchain")


def _ctx(tmp_path) -> RunContext:
    run_dir = tmp_path / "run_0"
    run_dir.mkdir(parents=True, exist_ok=True)
    return RunContext("run_0", 1, 1, {}, run_dir, tmp_path)


def test_library_builds_and_caches():
    assert lib is not None
    assert load_sampler_library() is lib  # cached


def test_raw_sampler_round_trip():
    import ctypes

    handle = lib.sampler_create(1000, 10_000, b"")
    assert handle
    lib.sampler_start(handle)
    time.sleep(0.15)
    lib.sampler_stop(handle)
    n = lib.sampler_count(handle)
    # 1 kHz for 150 ms → expect on the order of 100+ samples
    assert n >= 50
    buf = (ctypes.c_double * (n * 5))()
    got = lib.sampler_read(handle, buf, n)
    assert got == n
    # timestamps strictly increasing, cpu totals monotone
    ts = [buf[i * 5] for i in range(got)]
    assert all(b > a for a, b in zip(ts, ts[1:]))
    totals = [buf[i * 5 + 3] for i in range(got) if buf[i * 5 + 3] >= 0]
    assert all(b >= a for a, b in zip(totals, totals[1:]))
    lib.sampler_destroy(handle)


def test_sampler_create_rejects_bad_args():
    assert not lib.sampler_create(10, 10_000, b"")  # period too small
    assert not lib.sampler_create(1000, 4, b"")  # capacity too small


def test_native_profiler_collects(tmp_path):
    prof = NativeHostProfiler(period_us=1000, write_artifact=True)
    if not prof.available:
        pytest.skip("sampler unavailable")
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    # burn some CPU so cpu_usage is nonzero
    t_end = time.time() + 0.2
    x = 0
    while time.time() < t_end:
        x += 1
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert data["host_sample_rate_hz"] and data["host_sample_rate_hz"] > 100
    assert data["cpu_usage"] is not None and data["cpu_usage"] > 0
    assert data["memory_usage"] is not None and 0 < data["memory_usage"] < 100
    # RAPL may be absent in this VM: columns None is acceptable then
    assert (tmp_path / "run_0" / "native_host_samples.csv").exists()


def test_native_profiler_reusable_across_runs(tmp_path):
    prof = NativeHostProfiler(period_us=1000)
    if not prof.available:
        pytest.skip("sampler unavailable")
    for run in range(2):
        ctx = _ctx(tmp_path)
        prof.on_start(ctx)
        time.sleep(0.05)
        prof.on_stop(ctx)
        data = prof.collect(ctx)
        assert data["host_sample_rate_hz"] is not None
