"""Trained tiny LM: the real-weights path (train → install → EOS-driven
generation with readable text), closing the random-weights-only gap."""

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tiny_lm import (
    TINY_LM_NAME,
    build_corpus,
    load_or_train_tiny_lm,
    tiny_lm_config,
    train_tiny_lm,
)


@pytest.fixture(scope="module")
def trained():
    cfg = tiny_lm_config(d_model=96, n_layers=3)
    corpus = build_corpus()[:16]
    params, losses = train_tiny_lm(
        cfg=cfg, corpus=corpus, steps=600, batch=16, seq_len=96
    )
    return cfg, corpus, params, losses


def test_training_converges(trained):
    _, _, _, losses = trained
    assert losses[0] > 3.0  # random init: ~ln(vocab)
    assert losses[-1] < 0.3  # memorised the corpus
    assert len(losses) < 600  # early-stopped at the loss target


def test_trained_model_generates_eos_driven_text(trained):
    cfg, corpus, params, _ = trained
    engine = JaxEngine(registry={}, dtype=jnp.float32)
    engine.install_model(TINY_LM_NAME, cfg, params)
    prompt = corpus[0][: corpus[0].index(".") + 1]  # first sentence prefix
    budget = 134
    r = engine.generate(
        GenerationRequest(TINY_LM_NAME, prompt, max_new_tokens=budget)
    )
    # the whole point: content-driven length, not budget-driven
    assert 0 < r.generated_tokens < budget
    assert r.text  # readable learned bytes, not empty
    assert all(32 <= ord(c) < 127 or c.isspace() for c in r.text)


def test_install_model_applies_engine_quantization(trained):
    cfg, _, params, _ = trained
    engine = JaxEngine(registry={}, dtype=jnp.float32, quantize="int8")
    engine.install_model(TINY_LM_NAME, cfg, params)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        is_quantized,
    )

    assert is_quantized(engine._models[TINY_LM_NAME].params["wq"])
    r = engine.generate(
        GenerationRequest(TINY_LM_NAME, "Here is information", max_new_tokens=8)
    )
    assert r.generated_tokens >= 1


def test_load_or_train_round_trips(tmp_path, trained):
    cfg, corpus, params, _ = trained
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tiny_lm import (
        save_tiny_lm,
    )

    save_tiny_lm(params, tmp_path / "tiny_lm")
    cfg2, restored = load_or_train_tiny_lm(tmp_path, cfg=cfg)
    assert cfg2 == cfg
    import numpy as np

    np.testing.assert_array_equal(
        np.asarray(restored["embed"]), np.asarray(params["embed"])
    )
