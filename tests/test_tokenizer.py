"""Tokenizers: byte fallback surface, HF adapter, and engine integration.

The HF tokenizer is built locally from a handcrafted ``tokenizer.json``
(this environment has no egress), exercising the same loading path a real
checkpoint directory provides.
"""

import json

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.tokenizer import (
    ByteTokenizer,
    load_tokenizer,
)

tokenizers = pytest.importorskip("tokenizers")
transformers = pytest.importorskip("transformers")


VOCAB = {
    "<pad>": 0,
    "<s>": 1,
    "</s>": 2,
    "[UNK]": 3,
    "hello": 4,
    "world": 5,
    "energy": 6,
    "tpu": 7,
}


@pytest.fixture()
def hf_dir(tmp_path):
    tok = tokenizers.Tokenizer(
        tokenizers.models.WordLevel(vocab=VOCAB, unk_token="[UNK]")
    )
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Whitespace()
    d = tmp_path / "ckpt"
    d.mkdir()
    tok.save(str(d / "tokenizer.json"))
    (d / "tokenizer_config.json").write_text(
        json.dumps(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "bos_token": "<s>",
                "eos_token": "</s>",
                "pad_token": "<pad>",
            }
        )
    )
    return d


def test_byte_tokenizer_uniform_surface():
    tok = ByteTokenizer()
    assert (tok.pad_id, tok.bos_id, tok.eos_id) == (0, 1, 2)
    ids = tok.encode("hi")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "hi"


def test_hf_tokenizer_roundtrip_and_special_ids(hf_dir):
    tok = load_tokenizer(str(hf_dir))
    assert type(tok).__name__ == "HFTokenizer"
    assert tok.bos_id == 1 and tok.eos_id == 2 and tok.pad_id == 0
    ids = tok.encode("hello world")
    assert ids == [1, 4, 5]  # bos + words
    assert tok.decode(ids) == "hello world"
    assert tok.encode("hello", add_bos=False) == [4]
    assert tok.vocab_size == len(VOCAB)


def test_load_tokenizer_falls_back_to_bytes(tmp_path):
    assert isinstance(load_tokenizer(None), ByteTokenizer)
    assert isinstance(load_tokenizer(str(tmp_path)), ByteTokenizer)  # empty dir
    # malformed tokenizer.json → fallback, not a crash
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "tokenizer.json").write_text("{not json")
    assert isinstance(load_tokenizer(str(bad)), ByteTokenizer)


def test_engine_uses_checkpoint_tokenizer(hf_dir):
    """An engine serving an HF checkpoint tokenizes with that checkpoint's
    tokenizer: prompt ids line up with the trained embedding rows and the
    output text decodes through the same vocab."""
    import dataclasses

    import jax.numpy as jnp
    import torch  # noqa: F401 — transformers model construction

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.convert import (
        hf_config_for,
    )

    cfg = dataclasses.replace(
        get_model_config("mistral:7b").tiny(), vocab_size=len(VOCAB)
    )
    model = transformers.AutoModelForCausalLM.from_config(
        hf_config_for(cfg), attn_implementation="eager"
    )
    model.save_pretrained(hf_dir)  # weights join the tokenizer files

    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.float32,
        hf_checkpoints={cfg.name: str(hf_dir)},
    )
    tok = engine._tokenizer_for(cfg.name)
    assert type(tok).__name__ == "HFTokenizer"
    result = engine.generate(
        GenerationRequest(cfg.name, "hello world energy", max_new_tokens=4)
    )
    assert result.prompt_tokens == 4  # bos + 3 known words
    # every generated id is in the checkpoint vocab, and the text is its
    # decode (possibly empty if only specials were sampled)
    assert all(0 <= t < len(VOCAB) for t in result.tokens)
    assert result.text == tok.decode(result.tokens)
