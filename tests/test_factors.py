"""Run-table algebra: factors, full factorial, exclusions, repetitions, shuffle."""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import RunTableError
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.factors import (
    DONE_COLUMN,
    RUN_ID_COLUMN,
    Factor,
    RunTableModel,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress


def test_factor_rejects_duplicate_treatments():
    with pytest.raises(RunTableError, match="duplicate treatment"):
        Factor("model", ["a", "a"])


def test_factor_rejects_empty_and_dunder_names():
    with pytest.raises(RunTableError):
        Factor("", ["a"])
    with pytest.raises(RunTableError):
        Factor("__run_id", ["a"])
    with pytest.raises(RunTableError, match="no treatments"):
        Factor("model", [])


def test_full_factorial_counts():
    model = RunTableModel(
        factors=[Factor("a", [1, 2, 3]), Factor("b", ["x", "y"])],
        repetitions=4,
    )
    rows = model.generate()
    assert len(rows) == 3 * 2 * 4
    ids = [r[RUN_ID_COLUMN] for r in rows]
    assert len(set(ids)) == len(ids)
    assert all(r[DONE_COLUMN] == RunProgress.TODO for r in rows)


def test_run_id_format_matches_reference():
    # reference RunTableModel.py:87: run_{i}_repetition_{j}
    model = RunTableModel(factors=[Factor("a", [1, 2])], repetitions=2)
    ids = [r[RUN_ID_COLUMN] for r in model.generate()]
    assert ids == [
        "run_0_repetition_0",
        "run_1_repetition_0",
        "run_0_repetition_1",
        "run_1_repetition_1",
    ]


def test_exclusions_are_conjunctive_within_disjunctive_across():
    model = RunTableModel(
        factors=[Factor("loc", ["local", "remote"]), Factor("len", [100, 500])],
        exclusions=[{"loc": ["remote"], "len": [500]}, {"len": [100]}],
    )
    variations = model.variations()
    assert {"loc": "local", "len": 500} in variations
    assert {"loc": "remote", "len": 500} not in variations
    assert all(v["len"] != 100 for v in variations)


def test_all_excluded_raises():
    model = RunTableModel(
        factors=[Factor("a", [1])], exclusions=[{"a": [1]}]
    )
    with pytest.raises(RunTableError, match="empty run table"):
        model.generate()


def test_exclusion_unknown_factor_rejected():
    with pytest.raises(RunTableError, match="unknown factors"):
        RunTableModel(factors=[Factor("a", [1])], exclusions=[{"nope": [1]}])


def test_shuffle_is_seeded_and_deterministic():
    kw = dict(factors=[Factor("a", list(range(10)))], repetitions=3)
    r1 = RunTableModel(shuffle=True, shuffle_seed=7, **kw).generate()
    r2 = RunTableModel(shuffle=True, shuffle_seed=7, **kw).generate()
    r3 = RunTableModel(shuffle=True, shuffle_seed=8, **kw).generate()
    assert [r[RUN_ID_COLUMN] for r in r1] == [r[RUN_ID_COLUMN] for r in r2]
    assert [r[RUN_ID_COLUMN] for r in r1] != [r[RUN_ID_COLUMN] for r in r3]
    unshuffled = RunTableModel(**kw).generate()
    assert sorted(r[RUN_ID_COLUMN] for r in r1) == sorted(
        r[RUN_ID_COLUMN] for r in unshuffled
    )


def test_data_columns_and_plugin_append():
    model = RunTableModel(
        factors=[Factor("a", [1])], data_columns=["tokens", "time_s"]
    )
    model.add_data_columns(["energy_J"])
    row = model.generate()[0]
    assert row["tokens"] is None and row["energy_J"] is None
    with pytest.raises(RunTableError, match="already exists"):
        model.add_data_columns(["tokens"])


def test_column_collisions_rejected():
    with pytest.raises(RunTableError, match="collide"):
        RunTableModel(factors=[Factor("a", [1])], data_columns=["a"])
    with pytest.raises(RunTableError, match="duplicate factor names"):
        RunTableModel(factors=[Factor("a", [1]), Factor("a", [2])])
    with pytest.raises(RunTableError, match="repetitions"):
        RunTableModel(factors=[Factor("a", [1])], repetitions=0)
