"""Mixture-of-experts MLP and expert-parallel (ep) sharding.

The reference sweep has no MoE model, but Ollama serves one (mixtral) and
the framework's scaling mandate includes expert parallelism; correctness
evidence mirrors the other parallel paths: (1) a single-expert MoE must
reduce exactly to the dense MLP, (2) the ep/tp-sharded forward must match
the unsharded one, (3) the HF logit-parity test lives in test_convert.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
    forward,
    logits_for,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.sharding import (
    param_specs,
    shard_model,
)


def _tiny_moe(n_experts=4, top_k=2, **overrides):
    cfg = get_model_config("mixtral:8x7b").tiny()
    return dataclasses.replace(
        cfg, n_experts=n_experts, top_k_experts=top_k, **overrides
    )


def _run(cfg, params, tokens):
    b, s = tokens.shape
    shape = (cfg.n_layers, b, cfg.n_kv_heads, s, cfg.d_head)
    k0 = jnp.zeros(shape, dtype=jnp.float32)
    v0 = jnp.zeros(shape, dtype=jnp.float32)
    hidden, _, _ = forward(params, cfg, tokens, jnp.int32(0), k0, v0, None)
    return logits_for(params, cfg, hidden)


def test_single_expert_moe_equals_dense():
    """E=1, k=1: routing is trivial (softmax over one logit = 1), so the MoE
    forward must equal the dense forward with identical MLP weights."""
    moe_cfg = _tiny_moe(n_experts=1, top_k=1)
    dense_cfg = dataclasses.replace(moe_cfg, n_experts=0)
    tf = Transformer.initialise(dense_cfg, seed=3, dtype=jnp.float32)
    dense_params = tf.params

    moe_params = dict(dense_params)
    moe_params["w_gate"] = dense_params["w_gate"][:, None]  # [L,1,D,F]
    moe_params["w_up"] = dense_params["w_up"][:, None]
    moe_params["w_down"] = dense_params["w_down"][:, None]
    moe_params["router"] = jnp.zeros(
        (moe_cfg.n_layers, moe_cfg.d_model, 1), dtype=jnp.float32
    )

    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (2, 7), 0, moe_cfg.vocab_size
    )
    np.testing.assert_allclose(
        np.asarray(_run(moe_cfg, moe_params, tokens)),
        np.asarray(_run(dense_cfg, dense_params, tokens)),
        rtol=2e-5,
        atol=1e-5,
    )


def test_moe_decode_step_matches_prefill_logits():
    """Prefill of n tokens then a 1-token decode must agree with prefill of
    n+1 tokens at the last position (the MoE block works in both modes)."""
    cfg = _tiny_moe()
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)

    cache_shape = (cfg.n_layers, 1, cfg.n_kv_heads, 8, cfg.d_head)
    k0 = jnp.zeros(cache_shape, dtype=jnp.float32)
    v0 = jnp.zeros(cache_shape, dtype=jnp.float32)

    # full prefill
    hidden_full, _, _ = forward(
        tf.params, cfg, tokens, jnp.int32(0), k0, v0, None
    )
    want = logits_for(tf.params, cfg, hidden_full[:, -1])

    # prefill 7 + decode 1
    hidden_pre, kc, vc = forward(
        tf.params, cfg, tokens[:, :7], jnp.int32(0), k0, v0, None
    )
    hidden_dec, _, _ = forward(
        tf.params, cfg, tokens[:, 7:8], jnp.int32(7), kc, vc, None
    )
    got = logits_for(tf.params, cfg, hidden_dec[:, -1])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=1e-5)


def test_ep_tp_sharded_forward_matches_unsharded():
    """tp=2 × ep=4 GSPMD placement must not change the numbers."""
    cfg = _tiny_moe(n_experts=4, d_ff=128)
    tf = Transformer.initialise(cfg, seed=1, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)

    want = np.asarray(_run(cfg, tf.params, tokens))

    mesh = build_mesh(MeshSpec.tp_ep(2, 4), jax.devices())
    specs = param_specs(cfg, mesh)
    assert specs["w_gate"] == jax.sharding.PartitionSpec(None, "ep", None, "tp")
    sharded = shard_model(tf.params, cfg, mesh)
    got = np.asarray(jax.jit(lambda p: _run(cfg, p, tokens))(sharded))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_moe_quantized_forward_close_to_fp():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        quantize_params,
    )

    cfg = _tiny_moe()
    tf = Transformer.initialise(cfg, seed=4, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 6), 0, cfg.vocab_size)
    fp = np.asarray(_run(cfg, tf.params, tokens))
    q = np.asarray(_run(cfg, quantize_params(tf.params), tokens))
    # int8 weight error; logits stay close in distribution
    assert np.max(np.abs(fp - q)) < 0.35
    assert np.argmax(fp[:, -1]) == np.argmax(q[:, -1])


def test_moe_engine_generates():
    """The decode engine serves the MoE family end-to-end."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    cfg = _tiny_moe()
    engine = JaxEngine(registry={cfg.name: cfg}, dtype=jnp.float32)
    result = engine.generate(
        GenerationRequest(cfg.name, "energy study", max_new_tokens=5)
    )
    assert 1 <= result.generated_tokens <= 5
    assert result.decode_s >= 0


def test_pp_loss_matches_single_device_moe():
    """The pipeline schedule shares run_blocks, so MoE layers pipeline too."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.pp import (
        make_pp_loss,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.train import (
        next_token_loss,
    )

    cfg = _tiny_moe(n_layers=2)
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 10), 0, cfg.vocab_size)

    b, s = tokens.shape
    shape = (cfg.n_layers, b, cfg.n_kv_heads, s - 1, cfg.d_head)
    ref = next_token_loss(
        tf.params, cfg, tokens,
        jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32),
    )

    mesh = build_mesh(MeshSpec(axes=(("pp", 2),)), jax.devices()[:2])
    pp_loss = jax.jit(make_pp_loss(cfg, mesh, n_microbatches=2))
    np.testing.assert_allclose(
        float(pp_loss(tf.params, tokens)), float(ref), rtol=2e-5
    )
