"""Paged-KV attention: kernel parity, page pool allocator, write paths.

VERDICT round-2 item 7 (BASELINE.json north star: "paged-KV attention"):
a Pallas decode kernel reading K/V through a page table, parity-tested
against the contiguous kernel, plus the block-table machinery that lets a
continuous-batching scheduler admit mixed-length concurrent requests
without max-shape caches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
    PagePool,
    PagePoolExhausted,
    write_prefill,
    write_token,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
    pallas_decode_attention,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
    paged_decode_attention_reference,
    pallas_paged_decode_attention,
)


def _scattered_pool(key, b, hkv, t, d, page, n_extra_pages=3):
    """A contiguous cache scattered into a shuffled page pool.

    Returns (contiguous k/v [B,Hkv,T,D], pool k/v [P,Hkv,page,D],
    page_table [B,T/page]).
    """
    kk, kv_, kp = jax.random.split(key, 3)
    k = jax.random.normal(kk, (b, hkv, t, d), jnp.float32)
    v = jax.random.normal(kv_, (b, hkv, t, d), jnp.float32)
    jmax = t // page
    n_pages = b * jmax + n_extra_pages
    perm = jax.random.permutation(kp, n_pages)[: b * jmax]
    page_table = perm.reshape(b, jmax).astype(jnp.int32)
    k_pool = jnp.zeros((n_pages, hkv, page, d), jnp.float32)
    v_pool = jnp.zeros((n_pages, hkv, page, d), jnp.float32)
    for b_i in range(b):
        for j in range(jmax):
            p = int(page_table[b_i, j])
            k_pool = k_pool.at[p].set(k[b_i, :, j * page : (j + 1) * page])
            v_pool = v_pool.at[p].set(v[b_i, :, j * page : (j + 1) * page])
    return k, v, k_pool, v_pool, page_table


@pytest.mark.parametrize("d", [128, 64])  # aligned + lane-padded head dims
def test_paged_kernel_matches_contiguous_kernel(d):
    """The verdict's parity bar: the paged kernel through a scattered
    page table equals the contiguous kernel on the unscattered cache."""
    b, hq, hkv, t, page = 2, 8, 2, 512, 128
    key = jax.random.PRNGKey(0)
    k, v, k_pool, v_pool, table = _scattered_pool(key, b, hkv, t, d, page)
    q = jax.random.normal(jax.random.PRNGKey(1), (b, hq, d), jnp.float32)
    lengths = jnp.asarray([300, 512], jnp.int32)

    got = pallas_paged_decode_attention(
        q, k_pool, v_pool, table, lengths, interpret=True
    )
    want = pallas_decode_attention(q, k, v, lengths, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_paged_kernel_matches_jnp_reference():
    b, hq, hkv, t, d, page = 3, 4, 4, 256, 64, 128
    key = jax.random.PRNGKey(2)
    _, _, k_pool, v_pool, table = _scattered_pool(key, b, hkv, t, d, page)
    q = jax.random.normal(jax.random.PRNGKey(3), (b, hq, d), jnp.float32)
    lengths = jnp.asarray([1, 129, 256], jnp.int32)  # page edges + minimum

    got = pallas_paged_decode_attention(
        q, k_pool, v_pool, table, lengths, interpret=True
    )
    want = paged_decode_attention_reference(q, k_pool, v_pool, table, lengths)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_page_pool_allocator():
    pool = PagePool.create(
        n_layers=2, n_pages=8, n_kv_heads=2, d_head=16, page_size=128
    )
    assert pool.free_pages == 8
    assert pool.pages_for(1) == 1
    assert pool.pages_for(128) == 1
    assert pool.pages_for(129) == 2
    a = pool.alloc(3)
    b = pool.alloc(4)
    assert len(set(a) | set(b)) == 7 and pool.free_pages == 1
    with pytest.raises(PagePoolExhausted):
        pool.alloc(2)
    pool.free(a)
    assert pool.free_pages == 4
    c = pool.alloc(4)
    assert len(c) == 4


def test_mixed_length_requests_share_the_pool():
    """The capacity win paging exists for: two requests of very different
    lengths hold exactly ceil(len/page) pages each — no padding to the
    widest request — and both attend correctly through the shared pool."""
    hq, hkv, d, page = 4, 2, 64, 128
    pool = PagePool.create(
        n_layers=1, n_pages=6, n_kv_heads=hkv, d_head=d, page_size=page,
        dtype=jnp.float32,
    )
    lengths = [130, 500]  # 2 pages + 4 pages = 6 — fits exactly
    tables, caches = [], []
    key = jax.random.PRNGKey(4)
    for i, n in enumerate(lengths):
        n_pages = pool.pages_for(n)
        pages = pool.alloc(n_pages)
        key, kk, kv_ = jax.random.split(key, 3)
        k_seq = jax.random.normal(kk, (1, hkv, n, d), jnp.float32)
        v_seq = jax.random.normal(kv_, (1, hkv, n, d), jnp.float32)
        row = jnp.asarray(pages, jnp.int32)
        pool.k, pool.v = write_prefill(pool.k, pool.v, row, k_seq, v_seq, n)
        tables.append(pages)
        caches.append((k_seq, v_seq))
    assert pool.free_pages == 0

    jmax = max(len(t) for t in tables)
    table = jnp.asarray(
        [t + [0] * (jmax - len(t)) for t in tables], jnp.int32
    )
    q = jax.random.normal(jax.random.PRNGKey(5), (2, hq, d), jnp.float32)
    got = pallas_paged_decode_attention(
        q, pool.k[0], pool.v[0], table, jnp.asarray(lengths, jnp.int32),
        interpret=True,
    )
    # per-request contiguous reference at each request's OWN length
    for i, (k_seq, v_seq) in enumerate(caches):
        want = pallas_decode_attention(
            q[i : i + 1],
            k_seq[0][None],
            v_seq[0][None],
            jnp.asarray([lengths[i]], jnp.int32),
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got[i : i + 1]), np.asarray(want), rtol=2e-5, atol=2e-5
        )


def test_engine_paged_batch_matches_contiguous_batch():
    """The serving integration: generate_batch over the page pool emits
    the same tokens as the contiguous batch path, row for row, including
    mixed lengths, sampled rows, and per-row budgets."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    contiguous = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", "short row", max_new_tokens=6),
        GenerationRequest("tiny", "a much longer prompt for the second row "
                          "of this batch", max_new_tokens=20),
        GenerationRequest(
            "tiny", "sampled row", max_new_tokens=12,
            temperature=0.7, seed=3,
        ),
    ]
    want = contiguous.generate_batch(reqs)
    got = paged.generate_batch(reqs)
    for g, w in zip(got, want):
        assert g.tokens == w.tokens
        assert g.text == w.text


def test_engine_paged_batch_matches_single_requests():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", "row a", max_new_tokens=8),
        GenerationRequest("tiny", "row b is different", max_new_tokens=10),
    ]
    batch = paged.generate_batch(reqs)
    for r, req in zip(batch, reqs):
        assert r.tokens == paged.generate(req).tokens


def test_paged_kv_guards():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    with pytest.raises(ValueError, match="page_size"):
        JaxEngine(registry=registry, paged_kv=True, page_size=100)
    # paged_kv × kv_quantize COMPOSES since the int8 page pool landed
    # (tests/test_paged_int8.py pins its parity) — the old guard is gone
    engine = JaxEngine(registry=registry, paged_kv=True, kv_quantize="int8")
    assert engine.paged_kv and engine.kv_quantize == "int8"


def test_paged_batch_on_tensor_parallel_engine():
    """Paged decode composes with TP: the pool's heads shard over the
    mesh (pages/table replicated) and every row matches the single-device
    paged engine token for token."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 (virtual) devices")
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only(2), devices=jax.devices()[:2]),
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
    )
    single = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", "sharded paged row", max_new_tokens=8),
        GenerationRequest("tiny", "another longer sharded paged row here",
                          max_new_tokens=14),
    ]
    got = tp.generate_batch(reqs)
    want = single.generate_batch(reqs)
    for g, w in zip(got, want):
        assert g.tokens == w.tokens


def test_write_token_appends_through_the_table():
    """Decode-step appends land at (page_table[len//page], len%page) and
    the kernel sees them immediately."""
    hkv, d, page = 2, 64, 128
    pool = PagePool.create(
        n_layers=1, n_pages=3, n_kv_heads=hkv, d_head=d, page_size=page,
        dtype=jnp.float32,
    )
    pages = pool.alloc(2)
    row = jnp.asarray(pages, jnp.int32)

    key = jax.random.PRNGKey(6)
    n0 = 127  # appends will cross the page boundary
    key, kk, kv_ = jax.random.split(key, 3)
    k_seq = jax.random.normal(kk, (1, hkv, n0, d), jnp.float32)
    v_seq = jax.random.normal(kv_, (1, hkv, n0, d), jnp.float32)
    pool.k, pool.v = write_prefill(pool.k, pool.v, row, k_seq, v_seq, n0)

    k_all, v_all = [k_seq], [v_seq]
    length = n0
    for step in range(3):  # slots 127, 128 (page 2!), 129
        key, kk, kv_ = jax.random.split(key, 3)
        k_vec = jax.random.normal(kk, (1, hkv, d), jnp.float32)
        v_vec = jax.random.normal(kv_, (1, hkv, d), jnp.float32)
        pool.k, pool.v = write_token(
            pool.k, pool.v, row, jnp.int32(length), k_vec, v_vec
        )
        k_all.append(k_vec[:, :, None])
        v_all.append(v_vec[:, :, None])
        length += 1

    k_cat = jnp.concatenate(k_all, axis=2)  # [1, Hkv, 130, D]
    v_cat = jnp.concatenate(v_all, axis=2)
    q = jax.random.normal(jax.random.PRNGKey(7), (1, 4, d), jnp.float32)
    got = pallas_paged_decode_attention(
        q, pool.k[0], pool.v[0], row[None], jnp.asarray([length], jnp.int32),
        interpret=True,
    )
    want = pallas_decode_attention(
        q,
        jnp.pad(k_cat, ((0, 0), (0, 0), (0, 2 * page - length), (0, 0))),
        jnp.pad(v_cat, ((0, 0), (0, 0), (0, 2 * page - length), (0, 0))),
        jnp.asarray([length], jnp.int32),
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("parts_impl", ["kernel", "xla"])
def test_engine_paged_stacked_pool_matches_contiguous(
    parts_impl, monkeypatch
):
    """The STACKED-HYBRID decode path (read-only prompt pool closed over
    the layer scan + carry-resident side caches for generated tokens +
    parts/side online-softmax merge — the design that removed the
    full-pool-copy-per-step, docs/PERF.md): forcing the kernel on CPU
    (interpret) must produce token-identical output to the contiguous
    engine, including the head-dim pad path (tiny d_head=16 → pool padded
    to 128). BOTH prompt-parts implementations are pinned — the Pallas
    parts kernel and the gather+fused-XLA variant that is the
    single-chip default since round 5 (PAGED_XLA_PARTS_MIN_ROWS)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
    )

    monkeypatch.setattr(
        je,
        "PAGED_XLA_PARTS_MIN_ROWS",
        1 if parts_impl == "xla" else 10**9,
    )

    registry = {
        "tiny": get_model_config("qwen2:1.5b").tiny(),  # GQA
        "tiny-mha": get_model_config("phi3:3.8b").tiny(),  # MHA (d pads)
    }
    contiguous = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    stacked = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
        decode_attention=pallas_decode_attention,  # forces the kernel path
    )
    # the stacked mode must actually be active (kernel closure present)
    assert stacked._paged_decode_attention() is not None
    reqs = [
        GenerationRequest("tiny", "short row", max_new_tokens=6),
        GenerationRequest(
            "tiny",
            "a much longer prompt for the second row of this batch",
            max_new_tokens=20,
        ),
        GenerationRequest(
            "tiny", "sampled row", max_new_tokens=12,
            temperature=0.7, seed=3,
        ),
    ]
    want = contiguous.generate_batch(reqs)
    got = stacked.generate_batch(reqs)
    for g, w in zip(got, want):
        assert g.tokens == w.tokens
        assert g.text == w.text
    # MHA coverage (a real-chip phi3 smoke showed bf16 near-tie argmax
    # divergence between impls; this pins the f32 math is exact for the
    # MHA + padded-head-dim combination too)
    mha_reqs = [
        GenerationRequest("tiny-mha", "row one", max_new_tokens=8),
        GenerationRequest("tiny-mha", "row two is longer", max_new_tokens=14),
    ]
    want = contiguous.generate_batch(mha_reqs)
    got = stacked.generate_batch(mha_reqs)
    for g, w in zip(got, want):
        assert g.tokens == w.tokens


def test_paged_parts_kernel_matches_per_layer_kernel():
    """The PRODUCTION stacked path (pallas_paged_decode_attention_parts:
    layer-indexed DMA into [L,P,Hkv,page,Dp], unnormalised output): its
    normalised result acc/l must equal the per-layer kernel on each
    layer's slice at the same lengths."""
    import numpy as np

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
        pallas_paged_decode_attention,
        pallas_paged_decode_attention_parts,
    )

    rng = np.random.default_rng(0)
    L, P, HKV, PAGE, D = 3, 8, 2, 128, 128
    B, HQ, JMAX = 2, 4, 2
    q = jnp.asarray(rng.normal(size=(B, HQ, D)), jnp.float32)
    k_pool = jnp.asarray(rng.normal(size=(L, P, HKV, PAGE, D)), jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(L, P, HKV, PAGE, D)), jnp.float32)
    table = jnp.asarray([[3, 5], [1, 6]], jnp.int32)
    lengths = jnp.asarray([200, 130], jnp.int32)
    for layer in range(L):
        want = pallas_paged_decode_attention(
            q, k_pool[layer], v_pool[layer], table, lengths, interpret=True
        )
        acc, m, l = pallas_paged_decode_attention_parts(
            q, k_pool, v_pool, table, lengths,
            layer=jnp.int32(layer), interpret=True,
        )
        got = (acc / l[..., None]).reshape(B, HQ, D)
        assert jnp.allclose(got, want, atol=1e-5), layer
        # the per-layer (xs-streamed) mode must agree too
        acc2, m2, l2 = pallas_paged_decode_attention_parts(
            q, k_pool[layer], v_pool[layer], table, lengths, interpret=True
        )
        got2 = (acc2 / l2[..., None]).reshape(B, HQ, D)
        assert jnp.allclose(got2, want, atol=1e-5), layer
    # zero-length rows exit with the sentinel triplet the self-term
    # merge relies on: (0, -inf, 0)
    acc, m, l = pallas_paged_decode_attention_parts(
        q, k_pool, v_pool, table, jnp.zeros((B,), jnp.int32),
        layer=jnp.int32(0), interpret=True,
    )
    assert jnp.all(acc == 0.0) and jnp.all(l == 0.0)
    assert jnp.all(jnp.isneginf(m))


def test_paged_parts_kernel_rejects_unpadded_head_dim():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
        pallas_paged_decode_attention_parts,
    )

    q = jnp.zeros((1, 2, 96), jnp.float32)
    pool = jnp.zeros((2, 4, 2, 128, 96), jnp.float32)
    table = jnp.zeros((1, 2), jnp.int32)
    lengths = jnp.ones((1,), jnp.int32)
    with pytest.raises(ValueError, match="pre-padded"):
        pallas_paged_decode_attention_parts(
            q, pool, pool, table, lengths, layer=jnp.int32(0), interpret=True
        )


def test_paged_kernel_gating_follows_auto_policy():
    """"auto" engages the paged kernel only on TPU backends (its gather
    fallback is the right CPU/test path); an explicitly injected kernel
    opts in anywhere. Pinned because the whole stacked-hybrid path hangs
    off this gate."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    auto_cpu = JaxEngine(registry=dict(registry), paged_kv=True)
    assert auto_cpu._paged_decode_attention() is None  # CPU: fallback
    explicit = JaxEngine(
        registry=dict(registry),
        paged_kv=True,
        decode_attention=pallas_decode_attention,
    )
    assert explicit._paged_decode_attention() is not None
    none_ = JaxEngine(
        registry=dict(registry), paged_kv=True, decode_attention=None
    )
    assert none_._paged_decode_attention() is None  # explicit XLA-fused


def test_group_chunks_matches_per_row_paginate():
    """The fused assembly call emits, for each selected row, exactly the
    chunks the per-row `_paginate` chain produced — including tail-page
    zero padding and the stacked pool's lane-padded head dim. One
    compiled call per group replaced ~8 host dispatches per row: on a
    tunneled chip those RPCs, not their device time, dominated paged
    batch assembly (docs/paged_trace.json)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
        _paginate,
        group_chunks,
    )

    l, g, hkv, t, d, page = 2, 4, 2, 192, 64, 128
    kk, kv = jax.random.split(jax.random.PRNGKey(7))
    k = jax.random.normal(kk, (l, g, hkv, t, d), jnp.float32)
    v = jax.random.normal(kv, (l, g, hkv, t, d), jnp.float32)
    rows = jnp.asarray([2, 0, 3], jnp.int32)
    tp = -(-t // page)

    ck, cv = group_chunks(k, v, rows, page, d)
    assert ck.shape == (len(rows) * tp, l, hkv, page, d)
    for out_i, gi in enumerate([2, 0, 3]):
        np.testing.assert_array_equal(
            np.asarray(ck[out_i * tp : (out_i + 1) * tp]),
            np.asarray(_paginate(k[:, gi], t, page)),
        )
        np.testing.assert_array_equal(
            np.asarray(cv[out_i * tp : (out_i + 1) * tp]),
            np.asarray(_paginate(v[:, gi], t, page)),
        )

    # stacked pools carry a lane-padded head dim (phi3: 96 → 128)
    ck_p, _ = group_chunks(k, v, rows, page, 96)
    assert ck_p.shape[-1] == 96
    np.testing.assert_array_equal(np.asarray(ck_p[..., :d]), np.asarray(ck))
    assert not np.asarray(ck_p[..., d:]).any()


def test_paged_batch_fused_assembly_with_mixed_groups_and_solo_rows():
    """A paged batch mixing a fused prefill group with a solo fallback
    row takes exactly one group_chunks call per multi-row group, and
    every row's tokens still match its solo generate() — covering the
    permutation that reorders per-group gathers back to row order."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv as pkv
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
        _prompt_alloc,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", "short row one", max_new_tokens=6),
        GenerationRequest(
            "tiny",
            # long enough for a larger prompt bucket than the short rows
            # (→ prefills solo), short enough for tiny's max_seq_len
            "solo " * 8,  # byte-level tiny tokenizer: 40 tokens → bucket 64
            max_new_tokens=8,
        ),
        GenerationRequest(
            "tiny", "short row two", max_new_tokens=10,
            temperature=0.6, seed=11,
        ),
    ]
    tok = engine._tokenizer_for("tiny")
    allocs = [_prompt_alloc(len(tok.encode(r.prompt))) for r in reqs]
    multi_groups = {
        a for a in set(allocs) if allocs.count(a) > 1
    }
    assert multi_groups and len(set(allocs)) > 1, (
        "test prompts must produce at least one multi-row group AND a "
        f"solo row; got allocs {allocs}"
    )

    calls = []
    real = pkv.group_chunks

    def spy(*args, **kwargs):
        calls.append(args[2].shape[0])
        return real(*args, **kwargs)

    pkv.group_chunks = spy
    try:
        batch = engine.generate_batch(reqs)
    finally:
        pkv.group_chunks = real
    assert len(calls) == len(multi_groups)
    for r, req in zip(batch, reqs):
        assert r.tokens == engine.generate(req).tokens


def test_xla_parts_match_kernel_parts():
    """The gather+fused-XLA parts variant (wide-batch sibling) returns
    the same (acc, m, l) contract as the Pallas parts kernel, including
    lane-padded head dims and empty-prompt rows (m=-inf, l=0)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
        pallas_paged_decode_attention_parts,
        xla_paged_decode_attention_parts,
    )

    b, hq, hkv, d, page, n_pool, jmax = 4, 8, 2, 64, 128, 8, 2
    dp = 128  # lane-padded pool head dim
    key = jax.random.PRNGKey(9)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, hq, d), jnp.float32)
    k_pool = jax.random.normal(kk, (n_pool, hkv, page, dp), jnp.float32)
    v_pool = jax.random.normal(kv, (n_pool, hkv, page, dp), jnp.float32)
    # zero the padding lanes as the engine's pools do
    k_pool = k_pool.at[..., d:].set(0)
    v_pool = v_pool.at[..., d:].set(0)
    table = jnp.asarray([[0, 1], [2, 3], [4, 5], [0, 0]], jnp.int32)
    lengths = jnp.asarray([130, 256, 1, 0], jnp.int32)  # incl. empty row

    acc_k, m_k, l_k = pallas_paged_decode_attention_parts(
        q, k_pool, v_pool, table, lengths, interpret=True
    )
    acc_x, m_x, l_x = xla_paged_decode_attention_parts(
        q, k_pool, v_pool, table, lengths
    )
    assert acc_x.shape == (b, hkv, hq // hkv, d)
    np.testing.assert_allclose(
        np.asarray(acc_x), np.asarray(acc_k[..., :d]), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(m_x), np.asarray(m_k), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(l_x), np.asarray(l_k), rtol=2e-5, atol=2e-5
    )
    # empty-prompt row: zero weight in the caller's merge
    assert not np.isfinite(np.asarray(m_x)[3]).any()
    assert (np.asarray(l_x)[3] == 0).all()


def test_paged_parts_policy_is_width_and_jmax_aware(monkeypatch):
    """The stacked parts impl choice is static-shape-driven: XLA parts
    for wide batches with NARROW page tables; the Pallas kernel below
    the row threshold OR when the table is wide (the XLA gather reads
    Jmax pages for every row, so the longest row taxes all —
    docs/PERF.md mixed-length A/B)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention as ppa
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
    )

    monkeypatch.setattr(je, "PAGED_XLA_PARTS_MIN_ROWS", 4)
    monkeypatch.setattr(je, "PAGED_XLA_PARTS_MAX_JMAX", 8)
    monkeypatch.setattr(
        ppa, "xla_paged_decode_attention_parts",
        lambda *a, **k: "xla",
    )
    monkeypatch.setattr(
        ppa, "pallas_paged_decode_attention_parts",
        lambda *a, **k: "kernel",
    )
    engine = JaxEngine(
        registry={"tiny": get_model_config("qwen2:1.5b").tiny()},
        paged_kv=True,
        decode_attention=pallas_decode_attention,  # enables kernels
    )
    da = engine._paged_decode_attention()

    def kc(b, jmax):
        return {
            "pool": jnp.zeros((4, 2, 128, 128)),
            "table": jnp.zeros((b, jmax), jnp.int32),
            "side": jnp.zeros((b, 2, 8, 16)),
        }

    q = jnp.zeros((8, 4, 16))
    lengths = jnp.zeros((8,), jnp.int32)
    assert da(q, kc(8, 2), kc(8, 2), lengths) == "xla"  # wide B, narrow table
    assert da(q, kc(8, 16), kc(8, 16), lengths) == "kernel"  # wide table
    q2 = jnp.zeros((2, 4, 16))
    l2 = jnp.zeros((2,), jnp.int32)
    assert da(q2, kc(2, 2), kc(2, 2), l2) == "kernel"  # below row threshold
