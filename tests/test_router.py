"""Replica-fleet routing (ISSUE 12, serve/router.py): dispatch
policies, the retry-once rule and its first-streamed-token cut, drain /
scale-up membership, cancellation and deadline propagation through the
front door, and the dispatch/health accounting — all hermetic over
``FakeBackend`` replicas."""

import json
import threading
import time
import urllib.request

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import FLIGHT
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import router as router_mod
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
    RemoteHTTPBackend,
    RemoteServerError,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
    LocalReplica,
    RemoteReplica,
    Router,
    RouterServer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.stream import (
    DeadlineExceeded,
)


def _req(prompt="hello", n=8, **kw):
    return GenerationRequest("m", prompt, max_new_tokens=n, **kw)


def _dispatch_count(name, policy):
    return router_mod._DISPATCH_C.labels(replica=name, policy=policy).value


def _retries(reason):
    return router_mod._RETRIES_C.labels(reason=reason).value


def _healthy(name):
    return router_mod._REPLICA_HEALTHY_G.labels(replica=name).value


@pytest.fixture()
def fleet2():
    replicas = [
        LocalReplica("fa", FakeBackend()),
        LocalReplica("fb", FakeBackend()),
    ]
    router = Router(replicas, policy="round-robin")
    yield router, replicas
    router.stop()


def test_round_robin_splits_and_attributes(fleet2):
    router, (ra, rb) = fleet2
    before = {n: _dispatch_count(n, "round-robin") for n in ("fa", "fb")}
    seen = []
    for i in range(6):
        result = router.dispatch(_req(f"p{i}"))
        assert result.generated_tokens == 8
        seen.append(result.extras["router"]["replica"])
    assert seen.count("fa") == 3 and seen.count("fb") == 3
    # dispatch accounting exact: one counted attempt per ticket
    assert _dispatch_count("fa", "round-robin") - before["fa"] == 3
    assert _dispatch_count("fb", "round-robin") - before["fb"] == 3
    assert ra.dispatched == 3 and rb.dispatched == 3
    assert ra.outstanding == 0 and rb.outstanding == 0


def test_least_queue_prefers_idle_replica():
    slow = LocalReplica(
        "lq_slow", FakeBackend(tokens_per_s=100.0, simulate_delay=True)
    )
    idle = LocalReplica("lq_idle", FakeBackend())
    router = Router([slow, idle], policy="least-queue")
    try:
        # occupy the slow replica with a long-running ticket...
        t = threading.Thread(
            target=lambda: router.dispatch(_req("long", n=64))
        )
        # round 0: both idle — the tie-break (name order) picks lq_idle;
        # pin the long ticket onto lq_slow directly instead
        slow.outstanding += 1
        try:
            t.start()
            time.sleep(0.05)
            # ...so the next three tickets all go to the idle one
            for i in range(3):
                result = router.dispatch(_req(f"q{i}", n=4))
                assert result.extras["router"]["replica"] == "lq_idle"
        finally:
            slow.outstanding -= 1
        t.join(timeout=10)
    finally:
        router.stop()


def test_refused_admission_retries_once_elsewhere():
    ra = LocalReplica("ref_a", FakeBackend())
    rb = LocalReplica("ref_b", FakeBackend())
    router = Router([ra, rb], policy="round-robin")
    try:
        before = _retries("refused")
        # stop ra AFTER the membership probe: the router still believes
        # it is healthy, so the first pick lands there and is REFUSED
        ra.scheduler.stop()
        results = [router.dispatch(_req(f"r{i}")) for i in range(2)]
        replicas = [r.extras["router"]["replica"] for r in results]
        assert replicas == ["ref_b", "ref_b"]
        # a refusal is a capacity answer, not a death: ref_a stays
        # healthy — but the refusal zeroes its CACHED admission
        # headroom (ISSUE 19), so only the FIRST ticket pays a retry;
        # the second is steered straight to the survivor by the
        # admission gate without touching the full replica
        retried = [r for r in results if r.extras["router"].get("retried")]
        assert len(retried) == 1
        assert retried[0].extras["router"]["retried"] == "refused"
        assert _retries("refused") - before == 1
        # the probe notices the stopped scheduler; dispatch then goes
        # straight to the survivor with no retry
        router.probe_now()
        assert not ra.healthy
        clean = router.dispatch(_req("r2"))
        assert clean.extras["router"]["replica"] == "ref_b"
        assert "retried" not in clean.extras["router"]
        assert _retries("refused") - before == 1
    finally:
        router.stop()


def test_dead_replica_mid_prefill_retries_once_elsewhere():
    backend_a = FakeBackend()
    ra = LocalReplica("dead_a", backend_a)
    rb = LocalReplica("dead_b", FakeBackend())
    router = Router([ra, rb], policy="round-robin")
    try:
        before = _retries("dead")
        backend_a.fail_decode_open = True  # dies at session open
        got_b = 0
        for i in range(2):
            result = router.dispatch(_req(f"d{i}"))
            assert result.generated_tokens == 8
            got_b += result.extras["router"]["replica"] == "dead_b"
        assert got_b == 2
        assert _retries("dead") - before == 1
        # a DEAD dispatch marks the replica unhealthy immediately
        assert not ra.healthy
        assert _healthy("dead_a") == 0.0
        down = [e for e in FLIGHT.events(type_="replica_down")]
        assert any(e["replica"] == "dead_a" for e in down)
    finally:
        router.stop()


def test_streaming_retry_before_first_token():
    backend_a = FakeBackend()
    ra = LocalReplica("sdead_a", backend_a)
    rb = LocalReplica("sdead_b", FakeBackend())
    router = Router([ra, rb], policy="round-robin")
    try:
        backend_a.fail_decode_open = True
        tokens, final = [], None
        for chunk in router.dispatch_stream(_req("s0", n=8)):
            if chunk.done:
                final = chunk.result
            else:
                tokens.extend(chunk.tokens)
        assert final is not None and len(tokens) == 8
        assert final.extras["router"]["replica"] == "sdead_b"
        assert final.extras["router"]["retried"] == "dead"
    finally:
        router.stop()


def test_mid_stream_death_is_terminal_error_never_retried():
    backend_a = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    ra = LocalReplica("mid_a", backend_a)
    rb = LocalReplica("mid_b", FakeBackend())
    router = Router([ra, rb], policy="round-robin")
    try:
        before_b = _dispatch_count("mid_b", "round-robin")
        backend_a.fail_after_slices = 1  # dies after one decode slice
        got = 0
        with pytest.raises(RuntimeError, match="died"):
            for chunk in router.dispatch_stream(_req("m0", n=256)):
                if not chunk.done:
                    got += len(chunk.tokens)
        # tokens HAD streamed before the death — so no retry happened
        assert got > 0
        assert _dispatch_count("mid_b", "round-robin") == before_b
    finally:
        router.stop()


def test_retry_only_once_then_error_surfaces():
    ba, bb = FakeBackend(), FakeBackend()
    ra, rb = LocalReplica("once_a", ba), LocalReplica("once_b", bb)
    router = Router([ra, rb], policy="round-robin")
    try:
        ba.fail_decode_open = True
        bb.fail_decode_open = True
        with pytest.raises(RuntimeError):
            router.dispatch(_req("x"))
    finally:
        router.stop()


def test_drain_finishes_inflight_then_detaches(fleet2):
    router, (ra, rb) = fleet2
    ra.backend.simulate_delay = True
    ra.backend.tokens_per_s = 150.0
    done = {}

    def long_client():
        done["result"] = router.dispatch(_req("drain-long", n=48))

    # pin the long ticket to fa (round-robin cursor starts there)
    t = threading.Thread(target=long_client)
    t.start()
    time.sleep(0.05)
    assert ra.outstanding == 1
    assert router.drain("fa", timeout_s=30.0)
    t.join(timeout=30)
    # the in-flight ticket FINISHED (drain waited for it)
    assert done["result"].generated_tokens == 48
    assert done["result"].extras["router"]["replica"] == "fa"
    # ...and fa is detached: gone from membership, gauge dropped, event
    assert [r.name for r in router.replicas()] == ["fb"]
    assert _healthy("fa") == 0.0
    drained = FLIGHT.events(type_="replica_drained")
    assert any(e["replica"] == "fa" for e in drained)
    # new dispatch only reaches the survivor
    for i in range(3):
        assert (
            router.dispatch(_req(f"post{i}")).extras["router"]["replica"]
            == "fb"
        )


def test_drain_unknown_replica_raises(fleet2):
    router, _ = fleet2
    with pytest.raises(KeyError):
        router.drain("nope")


def test_add_replica_scales_up(fleet2):
    router, _ = fleet2
    router.add_replica(LocalReplica("fc", FakeBackend()))
    seen = {
        router.dispatch(_req(f"a{i}")).extras["router"]["replica"]
        for i in range(6)
    }
    assert "fc" in seen
    with pytest.raises(ValueError):
        router.add_replica(LocalReplica("fc", FakeBackend()))


def test_no_healthy_replica_raises():
    ra = LocalReplica("none_a", FakeBackend())
    router = Router([ra], policy="least-queue")
    try:
        ra.scheduler.stop()
        router.probe_now()
        assert not ra.healthy
        with pytest.raises(RuntimeError, match="no healthy replica"):
            router.dispatch(_req("x"))
    finally:
        router.stop()


def test_cancellation_propagates_to_replica_row():
    backend = FakeBackend(tokens_per_s=150.0, simulate_delay=True)
    ra = LocalReplica("can_a", backend)
    router = Router([ra], policy="least-queue")
    try:
        chunks = router.dispatch_stream(_req("cancel me", n=512))
        got = 0
        for chunk in chunks:
            got += len(chunk.tokens)
            if got >= 8:
                break
        chunks.close()  # the front-door disconnect
        # the replica-side row retires within one slice: the scheduler
        # goes idle instead of decoding 512 tokens
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            health = ra.scheduler.health_state()
            if (
                health["inflight_rows"] == 0
                and health["queue_depth"] == 0
            ):
                break
            time.sleep(0.01)
        else:
            pytest.fail("replica row never retired after cancel")
        assert ra.outstanding == 0
    finally:
        router.stop()


def test_deadline_propagates_through_front_door(fleet2):
    router, _ = fleet2
    # a deadline that has effectively already passed is shed by the
    # replica's scheduler pre-admission and must NOT be retried (the
    # outcome is the ticket's own, not the replica's)
    before = [_retries("refused"), _retries("dead")]
    with pytest.raises(DeadlineExceeded):
        router.dispatch(_req("late", deadline_ms=0.0001))
    assert [_retries("refused"), _retries("dead")] == before


def test_priority_rides_through_router(fleet2):
    router, _ = fleet2
    result = router.dispatch(_req("vip", priority=2))
    assert result.generated_tokens == 8


class _SlowProbeReplica(LocalReplica):
    probes = 0

    def probe(self):
        type(self).probes += 1
        return super().probe()


def test_background_prober_ticks():
    ra = _SlowProbeReplica("probe_a", FakeBackend())
    router = Router([ra], policy="least-queue", probe_interval_s=0.05)
    try:
        base = _SlowProbeReplica.probes
        router.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if _SlowProbeReplica.probes - base >= 2:
                break
            time.sleep(0.01)
        assert _SlowProbeReplica.probes - base >= 2
        assert ra.t_probe is not None
        assert ra.last_stats.get("scheduler") == "continuous"
    finally:
        router.stop()


# -- the HTTP front door ------------------------------------------------------


@pytest.fixture()
def front_door():
    replicas = [
        LocalReplica("h0", FakeBackend()),
        LocalReplica("h1", FakeBackend()),
    ]
    router = Router(replicas, policy="round-robin")
    server = RouterServer(
        router, host="127.0.0.1", port=0, models=["m"], quiet=True
    )
    server.start()
    yield server, router, replicas
    server.stop()


def test_front_door_round_trip_and_attribution(front_door):
    server, _router, _ = front_door
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    req = _req("front door")
    result = client.generate(req)
    assert result.tokens == FakeBackend().generate(req).tokens
    assert result.extras["router"]["replica"] in ("h0", "h1")
    assert result.extras["router"]["policy"] == "round-robin"
    # scheduler attribution from the REPLICA rides through untouched
    assert "sched" in result.extras


def test_front_door_streaming_parity(front_door):
    server, _router, _ = front_door
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    req = _req("stream via router", n=12)
    mono = client.generate(req)
    chunks = list(client.generate_stream(req))
    assert chunks[-1].done
    final = chunks[-1].result
    assert final.text == mono.text and final.tokens == mono.tokens
    assert final.extras["router"]["replica"] in ("h0", "h1")


def test_front_door_unknown_model_404_and_bad_request_400(front_door):
    server, _router, _ = front_door
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    with pytest.raises(RemoteServerError) as exc_info:
        client.generate(GenerationRequest("nope", "x", max_new_tokens=4))
    assert exc_info.value.status == 404
    with pytest.raises(RemoteServerError) as exc_info:
        client.generate(_req("x", n=99999))
    assert exc_info.value.status == 400


def test_front_door_healthz_and_debug_state(front_door):
    server, _router, _ = front_door
    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
        health = json.loads(resp.read())
    assert health["role"] == "router" and health["status"] == "ok"
    assert health["replicas"] == 2 and health["healthy_replicas"] == 2
    with urllib.request.urlopen(f"{base}/debug/state", timeout=5) as resp:
        state = json.loads(resp.read())
    assert state["policy"] == "round-robin"
    names = {r["name"] for r in state["replicas"]}
    assert names == {"h0", "h1"}
    for r in state["replicas"]:
        assert r["healthy"] is True
        assert r["last_probe"].get("scheduler") == "continuous"
        assert "queue_depth" in r["last_probe"]


def test_front_door_all_replicas_down_is_503(front_door):
    server, router, replicas = front_door
    for replica in replicas:
        replica.scheduler.stop()
    router.probe_now()
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    with pytest.raises(RemoteServerError) as exc_info:
        client.generate(_req("x"))
    assert exc_info.value.status == 503


def test_front_door_kill_one_replica_mid_fleet(front_door):
    """The smoke's kill scenario, hermetic: one replica dies, the
    healthy gauge drops, the retried ticket completes on the survivor,
    and zero accepted tickets are lost."""
    server, router, (r0, r1) = front_door
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    r0.backend.fail_decode_open = True  # r0 is now dead mid-prefill
    results = [client.generate(_req(f"k{i}")) for i in range(4)]
    assert all(r.generated_tokens == 8 for r in results)
    assert {r.extras["router"]["replica"] for r in results} == {"h1"}
    assert not r0.healthy and _healthy("h0") == 0.0


def test_front_door_mid_stream_death_is_terminal_sse_error():
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    router = Router([LocalReplica("w0", backend)], policy="least-queue")
    server = RouterServer(router, host="127.0.0.1", port=0, quiet=True)
    server.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
        backend.fail_after_slices = 1
        chunks = []
        with pytest.raises(RemoteServerError, match="died"):
            for c in client.generate_stream(_req("wire death", n=256)):
                chunks.append(c)
        # deltas arrived before the terminal error record — a clean,
        # terminated stream, not a hang or an IncompleteRead
        assert chunks and chunks[0].tokens
    finally:
        server.stop()


def test_front_door_deadline_maps_to_504(front_door):
    server, _router, _ = front_door
    client = RemoteHTTPBackend(f"http://127.0.0.1:{server.port}")
    with pytest.raises(RemoteServerError) as exc_info:
        client.generate(_req("late wire", deadline_ms=0.0001))
    assert exc_info.value.status == 504


def test_remote_replica_probe_parses_healthz_and_metrics():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
        GenerationServer,
    )

    backend_server = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    backend_server.start()
    try:
        replica = RemoteReplica(
            "remote0", f"http://127.0.0.1:{backend_server.port}"
        )
        stats = replica.probe()
        assert stats["running"] is True
        assert stats["scheduler"] == "continuous"
        assert stats["queue_depth"] == 0
        # dispatch over the wire works too
        result = replica.generate(_req("remote"))
        assert result.generated_tokens == 8
    finally:
        backend_server.stop()


def test_metrics_scrape_parser():
    # the shared v0.0.4 parser (obs/metrics.py) replaced the router's
    # two ad-hoc regexes (ISSUE 13 satellite): probe reads go through
    # parse_exposition / sample_value / histogram_mean, including the
    # bare _sum/_count fallback for scrapes with no TYPE line
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        histogram_mean,
        parse_exposition,
        sample_value,
    )

    text = (
        "# TYPE llm_paged_pool_occupancy gauge\n"
        "llm_paged_pool_occupancy 0.25\n"
        "llm_request_joules_per_token_sum 4.0\n"
        "llm_request_joules_per_token_count 8\n"
    )
    families = parse_exposition(text)
    assert sample_value(families, "llm_paged_pool_occupancy") == 0.25
    assert sample_value(families, "absent_family") is None
    assert histogram_mean(families, "llm_request_joules_per_token") == 0.5
    # typed histograms parse bucket samples and labelled children
    typed = (
        "# TYPE llm_request_ttft_seconds histogram\n"
        'llm_request_ttft_seconds_bucket{le="0.1"} 3\n'
        'llm_request_ttft_seconds_bucket{le="+Inf"} 4\n'
        "llm_request_ttft_seconds_sum 2.0\n"
        "llm_request_ttft_seconds_count 4\n"
    )
    tfam = parse_exposition(typed)
    assert histogram_mean(tfam, "llm_request_ttft_seconds") == 0.5


def test_route_policy_validation():
    with pytest.raises(ValueError, match="route policy"):
        Router([], policy="fastest")


def test_least_pages_discounts_store_held_prefix_pages():
    """ISSUE 14 satellite: a replica fat with REUSABLE prefix pages
    (llm_prefix_store_hbm_pages) is not penalized like one fat with
    live traffic — least-pages discounts the store's holdings from the
    occupancy figure, and falls back to raw occupancy when the store
    gauges are absent."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
        Replica,
    )

    router = Router([], policy="least-pages")
    hot_cache = Replica("hot_cache")
    # 60% occupied, but 8 of its 16 pages are store-held prefixes →
    # live-traffic load is only 10%
    hot_cache.last_stats = {
        "running": True,
        "pool_occupancy": 0.6,
        "pool_pages": 16,
        "prefix_store_hbm_pages": 8,
    }
    live_traffic = Replica("live_traffic")
    live_traffic.last_stats = {
        "running": True,
        "pool_occupancy": 0.4,
        "pool_pages": 16,
    }
    assert router._load_key(hot_cache) < router._load_key(live_traffic)
    # without the store gauge, raw occupancy decides (pre-ISSUE-14 rule)
    hot_cache.last_stats.pop("prefix_store_hbm_pages")
    assert router._load_key(hot_cache) > router._load_key(live_traffic)


def test_local_replica_probe_reports_store_pages():
    """LocalReplica.probe surfaces the backend store's device-resident
    page count so the policy above has its figure in-process."""
    backend = FakeBackend(prefix_share=True)
    replica = LocalReplica("store_probe", backend)
    try:
        backend.prefix_store.probe(b"a shared system prompt " * 4)
        stats = replica.probe()
        assert stats.get("prefix_store_hbm_pages", 0) > 0
    finally:
        replica.close()


# -- prefix-affinity routing (ISSUE 19) ----------------------------------------


SHARED = "affinity shared system prompt: " + "x" * 64  # 4+ full fake pages


@pytest.fixture()
def affinity_fleet():
    replicas = [
        LocalReplica("afa", FakeBackend(prefix_share=True)),
        LocalReplica("afb", FakeBackend(prefix_share=True)),
    ]
    router = Router(replicas, policy="affinity")
    yield router, replicas
    router.stop()


def test_affinity_routes_sharer_to_warm_replica(affinity_fleet):
    router, (ra, rb) = affinity_fleet
    # warm BOTH replicas for the model first (direct, off-router) so
    # the model-placement preference never narrows the candidate set —
    # this test isolates the affinity signal
    rb.generate(_req("afb distinct local traffic"))
    # first sharer: both stores cold on the SHARED prefix →
    # least-queue tie-break (name order) seats it on afa, which
    # publishes the prefix
    first = router.dispatch(_req(SHARED + " first tail"))
    assert first.extras["router"]["replica"] == "afa"
    assert first.extras["router"]["affinity"] == "fallback"
    hits0 = router_mod._AFFINITY_C.labels(replica="afa").value
    router.probe_now()  # federate the published digest
    assert (ra.last_stats or {}).get("prefix_digest", {}).get("entries")
    # pin load on afa so least-queue alone would pick afb: the
    # estimator's longest-match claim must override the queue signal
    ra.outstanding += 1
    try:
        second = router.dispatch(_req(SHARED + " second tail"))
    finally:
        ra.outstanding -= 1
    aff = second.extras["router"]["affinity"]
    assert second.extras["router"]["replica"] == "afa"
    assert isinstance(aff, dict) and aff["est_tokens"] >= 16
    assert router_mod._AFFINITY_C.labels(replica="afa").value == hits0 + 1


def test_affinity_stale_digest_falls_back_to_least_queue(affinity_fleet):
    router, (ra, rb) = affinity_fleet
    rb.generate(_req("afb warm"))  # both warm: no placement narrowing
    router.dispatch(_req(SHARED + " warmup"))
    router.probe_now()
    # age every probe past the staleness horizon: the estimator must
    # not trust a digest the store may have evicted since
    for r in (ra, rb):
        r.t_probe = time.monotonic() - router.affinity_stale_s - 1.0
    ra.outstanding += 1  # least-queue now prefers afb
    try:
        res = router.dispatch(_req(SHARED + " sharer"))
    finally:
        ra.outstanding -= 1
    assert res.extras["router"]["affinity"] == "fallback"
    assert res.extras["router"]["replica"] == "afb"


def test_affinity_tie_breaks_deterministically(affinity_fleet):
    router, (ra, rb) = affinity_fleet
    req = _req(SHARED + " tie")
    # fabricate the tie: both replicas publish the IDENTICAL digest
    digest = ra.backend.prefix_store.digest()
    now = time.monotonic()
    router.dispatch(_req(SHARED + " seed"))  # make the digest non-empty
    digest = ra.backend.prefix_store.digest()
    assert digest["entries"]
    for r in (ra, rb):
        r.last_stats = {"prefix_digest": digest, "max_admission_rows": 8}
        r.t_probe = now
    d1 = {}
    pick1 = router._pick(request=req, decision=d1)
    assert d1["affinity"] == "hit" and pick1.name == "afa"  # name order
    rb_pick_expected = "afb"
    ra.outstanding += 2  # equal estimates: load breaks the tie
    try:
        d2 = {}
        pick2 = router._pick(request=req, decision=d2)
    finally:
        ra.outstanding -= 2
    assert d2["affinity"] == "hit" and pick2.name == rb_pick_expected


def test_affinity_cold_store_degrades_to_least_queue_exactly():
    # replicas WITHOUT prefix stores: the affinity policy must pick
    # byte-identically to least-queue in every load state
    replicas = [
        LocalReplica("ca", FakeBackend()),
        LocalReplica("cb", FakeBackend()),
    ]
    router = Router(replicas, policy="affinity")
    try:
        req = _req("cold store prompt with no published prefixes")
        for loads in [(0, 0), (1, 0), (0, 1), (2, 2), (3, 1)]:
            replicas[0].outstanding, replicas[1].outstanding = loads
            decision = {}
            pick_aff = router._pick(request=req, decision=decision)
            assert decision["affinity"] == "fallback"
            router.policy = "least-queue"
            try:
                pick_lq = router._pick()
            finally:
                router.policy = "affinity"
            assert pick_aff.name == pick_lq.name
        replicas[0].outstanding = replicas[1].outstanding = 0
    finally:
        router.stop()
