"""End-to-end lifecycle through ExperimentController with a toy config."""

import multiprocessing
import os

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.config import (
    ExperimentConfig,
    OperationType,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.controller import (
    ExperimentController,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import (
    ConfigError,
    RunFailedError,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.factors import (
    Factor,
    RunTableModel,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import RunTableStore
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress


class ToyConfig(ExperimentConfig):
    name = "toy"
    time_between_runs_in_ms = 0
    isolate_runs = False

    def __init__(self, out):
        self.results_output_path = out
        self.trace = []

    def create_run_table_model(self):
        return RunTableModel(
            factors=[Factor("x", [1, 2]), Factor("y", ["a"])],
            repetitions=2,
            data_columns=["product"],
        )

    def before_experiment(self):
        self.trace.append("before_experiment")

    def before_run(self, ctx):
        self.trace.append(f"before_run:{ctx.run_id}")

    def start_run(self, ctx):
        self.trace.append("start_run")

    def start_measurement(self, ctx):
        self.trace.append("start_measurement")

    def interact(self, ctx):
        self.trace.append("interact")

    def stop_measurement(self, ctx):
        self.trace.append("stop_measurement")

    def stop_run(self, ctx):
        self.trace.append("stop_run")

    def populate_run_data(self, ctx):
        return {"product": ctx.factor("x") * 10}

    def after_experiment(self):
        self.trace.append("after_experiment")


def test_full_lifecycle_inline(tmp_path):
    config = ToyConfig(tmp_path)
    ctrl = ExperimentController(config, echo=False)
    ctrl.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert len(rows) == 4
    assert all(r["__done"] == RunProgress.DONE for r in rows)
    assert {r["product"] for r in rows} == {10, 20}
    # lifecycle order for the first run
    first = config.trace[: config.trace.index("stop_run") + 1]
    assert first == [
        "before_experiment",
        "before_run:run_0_repetition_0",
        "start_run",
        "start_measurement",
        "interact",
        "stop_measurement",
        "stop_run",
    ]
    assert config.trace[-1] == "after_experiment"
    # per-run artifact dirs exist (reference IRunController.py:20-21)
    assert (tmp_path / "toy" / "run_0_repetition_0").is_dir()


def test_full_lifecycle_isolated_subprocess(tmp_path):
    if multiprocessing.get_start_method(allow_none=True) != "fork":
        multiprocessing.set_start_method("fork", force=True)

    class IsolatedConfig(ToyConfig):
        isolate_runs = True

        def populate_run_data(self, ctx):
            return {"product": ctx.factor("x") * 10 + os.getpid() * 0}

    config = IsolatedConfig(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert all(r["__done"] == RunProgress.DONE for r in rows)
    assert {r["product"] for r in rows} == {10, 20}


def test_resume_skips_done_rows(tmp_path):
    config = ToyConfig(tmp_path)
    ctrl = ExperimentController(config, echo=False)
    # Simulate a crash after two runs: mark them done manually.
    for row in ctrl.rows[:2]:
        ctrl.store.update_row(
            row["__run_id"], {"__done": RunProgress.DONE, "product": 99}
        )
    config2 = ToyConfig(tmp_path)
    ctrl2 = ExperimentController(config2, echo=False)
    ctrl2.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    done_products = {r["__run_id"]: r["product"] for r in rows}
    # the two pre-done rows kept their stored value; others were computed
    assert done_products["run_0_repetition_0"] == 99
    assert done_products["run_1_repetition_0"] == 99
    assert done_products["run_0_repetition_1"] in (10, 20)
    # only two runs actually executed on resume
    assert config2.trace.count("start_run") == 2


def test_failed_run_marked_and_raises(tmp_path):
    class FailingConfig(ToyConfig):
        def interact(self, ctx):
            raise ValueError("boom in run")

    config = FailingConfig(tmp_path)
    ctrl = ExperimentController(config, echo=False)
    with pytest.raises(ValueError, match="boom in run"):
        ctrl.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert rows[0]["__done"] == RunProgress.FAILED
    # after_experiment still ran (finally-block)
    assert config.trace[-1] == "after_experiment"


def test_failed_isolated_run_carries_child_traceback(tmp_path):
    if multiprocessing.get_start_method(allow_none=True) != "fork":
        multiprocessing.set_start_method("fork", force=True)

    class FailingIsolated(ToyConfig):
        isolate_runs = True

        def interact(self, ctx):
            raise ValueError("boom in child")

    ctrl = ExperimentController(FailingIsolated(tmp_path), echo=False)
    with pytest.raises(RunFailedError, match="boom in child"):
        ctrl.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert rows[0]["__done"] == RunProgress.FAILED


def test_failed_run_retried_on_resume(tmp_path):
    class FailingOnce(ToyConfig):
        fail = True

        def interact(self, ctx):
            if type(self).fail:
                type(self).fail = False
                raise ValueError("transient")

    config = FailingOnce(tmp_path)
    with pytest.raises(ValueError):
        ExperimentController(config, echo=False).do_experiment()
    ctrl2 = ExperimentController(FailingOnce(tmp_path), echo=False)
    ctrl2.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert all(r["__done"] == RunProgress.DONE for r in rows)


def test_validation_rejects_bad_settings(tmp_path):
    class BadConfig(ToyConfig):
        time_between_runs_in_ms = -5

    with pytest.raises(ConfigError, match="time_between_runs_in_ms"):
        ExperimentController(BadConfig(tmp_path), echo=False)

    class BadName(ToyConfig):
        name = "has/slash"

    with pytest.raises(ConfigError, match="path separators"):
        ExperimentController(BadName(tmp_path), echo=False)


def test_semi_mode_raises_continue(tmp_path):
    class SemiConfig(ToyConfig):
        operation_type = OperationType.SEMI

        def continue_experiment(self):
            self.trace.append("continue")

    config = SemiConfig(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    # No CONTINUE gate after the final run: 4 runs -> 3 gates.
    assert config.trace.count("continue") == 3
    assert config.trace[-1] == "after_experiment"


def test_isolated_child_killed_surfaces_as_run_failure(tmp_path):
    if multiprocessing.get_start_method(allow_none=True) != "fork":
        multiprocessing.set_start_method("fork", force=True)

    class DyingConfig(ToyConfig):
        isolate_runs = True

        def interact(self, ctx):
            os._exit(137)  # simulate OOM-kill: child dies without reporting

    ctrl = ExperimentController(DyingConfig(tmp_path), echo=False)
    with pytest.raises(RunFailedError, match="without reporting"):
        ctrl.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert rows[0]["__done"] == RunProgress.FAILED


def test_resume_with_numeric_string_treatments(tmp_path):
    """CSV round-trip turns '32' into int 32; resume must still reconcile."""

    class StringyConfig(ToyConfig):
        def create_run_table_model(self):
            return RunTableModel(
                factors=[Factor("prompt_len", ["32", "64"]), Factor("flag", ["True"])],
                data_columns=["product"],
            )

        def populate_run_data(self, ctx):
            return {"product": 1}

    config = StringyConfig(tmp_path)
    ctrl = ExperimentController(config, echo=False)
    ctrl.store.update_row(
        ctrl.rows[0]["__run_id"], {"__done": RunProgress.DONE, "product": 7}
    )
    config2 = StringyConfig(tmp_path)
    ctrl2 = ExperimentController(config2, echo=False)
    ctrl2.do_experiment()
    rows = RunTableStore(tmp_path / "toy").read()
    assert all(r["__done"] == RunProgress.DONE for r in rows)
    # factor values in the resumed controller keep the config's types
    assert ctrl2.rows[0]["prompt_len"] == "32"
