"""CLI: config scaffolding, config-class discovery, end-to-end file run."""

import textwrap

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
    config_create,
    load_config_class,
    main,
    run_config_file,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import ConfigLoadError
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import RunTableStore
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress


def test_config_create_scaffold_is_loadable(tmp_path):
    path = config_create(tmp_path)
    assert path.exists()
    cls = load_config_class(path)
    assert cls.__name__ == "MyExperiment"


def test_load_rejects_configless_module(tmp_path):
    f = tmp_path / "empty.py"
    f.write_text("x = 1\n")
    with pytest.raises(ConfigLoadError, match="no ExperimentConfig subclass"):
        load_config_class(f)


def test_load_prefers_runnerconfig_name_on_ambiguity(tmp_path):
    f = tmp_path / "multi.py"
    f.write_text(
        textwrap.dedent(
            """
            from cain_2025_device_remote_llm_energy_rep_pkg_tpu import ExperimentConfig

            class Other(ExperimentConfig):
                pass

            class RunnerConfig(ExperimentConfig):
                pass
            """
        )
    )
    assert load_config_class(f).__name__ == "RunnerConfig"


def test_run_config_file_end_to_end(tmp_path):
    config_py = tmp_path / "exp.py"
    config_py.write_text(
        textwrap.dedent(
            f"""
            from pathlib import Path
            from cain_2025_device_remote_llm_energy_rep_pkg_tpu import (
                ExperimentConfig, Factor, RunTableModel,
            )

            class RunnerConfig(ExperimentConfig):
                name = "cli_e2e"
                results_output_path = Path({str(tmp_path)!r})
                isolate_runs = False

                def create_run_table_model(self):
                    return RunTableModel(
                        factors=[Factor("n", [1, 2, 3])],
                        data_columns=["square"],
                    )

                def populate_run_data(self, context):
                    return {{"square": context.factor("n") ** 2}}
            """
        )
    )
    run_config_file(config_py)
    rows = RunTableStore(tmp_path / "cli_e2e").read()
    assert [r["square"] for r in rows] == [1, 4, 9]
    assert all(r["__done"] == RunProgress.DONE for r in rows)


def test_main_help_and_unknown_command(capsys):
    assert main(["help"]) == 0
    assert "usage" in capsys.readouterr().out
    assert main(["definitely-not-a-command"]) == 2


def test_speculative_flag_parsing_handles_colon_names():
    """Model names contain colons (qwen2:1.5b); only a trailing :<int> is
    k. Malformed values raise CommandError, not a raw traceback. (The
    no-'=' spelling is now the DRAFT-ONLY form — see the knob test
    below — so only genuinely malformed specs reject.)"""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    with pytest.raises(CommandError, match="k >= 1"):
        serve_command(["--speculative", "t=d:0"])
    with pytest.raises(CommandError, match="speculative"):
        serve_command(["--speculative", "=d:2"])
    with pytest.raises(CommandError, match="speculative"):
        serve_command(["--speculative", ""])


def test_serve_speculative_knobs_reach_engine_and_server(monkeypatch):
    """ISSUE 9 knobs: the draft-only `--speculative draft[:k]` form maps
    to the engine's "default" entry, `--spec-accept-floor` reaches the
    engine ctor AND the server (→ continuous scheduler → decode_open),
    and malformed floors fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured["backend"] = backend
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "jax", "--port", "0",
            "--speculative", "qwen2:0.5b:3",
            "--spec-accept-floor", "0.4",
        ]
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.speculative import (
        DraftSpec,
    )

    be = captured["backend"]
    assert be.speculative == {"default": DraftSpec("model", "qwen2:0.5b", 3)}
    assert be._resolve_spec("qwen2:1.5b") == DraftSpec("model", "qwen2:0.5b", 3)
    assert be._resolve_spec("qwen2:0.5b") is None  # never self-drafts
    assert be.spec_accept_floor == 0.4
    assert captured["spec_accept_floor"] == 0.4

    captured.clear()
    cli.serve_command(
        [
            "--backend", "jax", "--port", "0",
            "--speculative", "qwen2:1.5b=qwen2:0.5b:5",
        ]
    )
    be = captured["backend"]
    assert be.speculative == {"qwen2:1.5b": DraftSpec("model", "qwen2:0.5b", 5)}
    assert captured["spec_accept_floor"] is None

    with pytest.raises(CommandError, match="spec-accept-floor"):
        serve_command(["--spec-accept-floor", "1.5"])
    with pytest.raises(CommandError, match="spec-accept-floor"):
        serve_command(["--spec-accept-floor", "nope"])


def test_serve_fake_backend_speculative_knobs(monkeypatch):
    """--backend fake + --speculative runs the synthetic spec protocol:
    k lands on the FakeBackend, acceptance comes from
    FAKE_SPEC_ACCEPTANCE, and the floor rides along."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured["backend"] = backend
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    monkeypatch.setenv("FAKE_SPEC_ACCEPTANCE", "0.5")
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--speculative", "fake-draft:6",
            "--spec-accept-floor", "0.2",
        ]
    )
    be = captured["backend"]
    assert be.spec_k == 6
    assert be.spec_acceptance == 0.5
    assert be.spec_accept_floor == 0.2


def test_serve_quantize_per_model_spec_parses(monkeypatch):
    """--quantize per-model spec reaches the engine as a dict."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured["backend"] = backend
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "jax",
            "--host", "127.0.0.1",
            "--port", "0",
            "--quantize", "qwen2:1.5b=int8,phi3:3.8b=int4,default=none",
        ]
    )
    be = captured["backend"]
    assert be.quantize == {
        "qwen2:1.5b": "int8", "phi3:3.8b": "int4", "default": None,
    }
    assert be._quant_mode("qwen2:1.5b") == "int8"
    assert be._quant_mode("phi3:3.8b") == "int4"
    assert be._quant_mode("gemma:2b") is None
    assert captured["host"] == "127.0.0.1"


def test_serve_scheduler_and_window_flags(monkeypatch):
    """--scheduler / --window-ms (and the --batch-window-ms alias) reach
    the server; bad scheduler values fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--scheduler", "continuous",
            "--window-ms", "25",
        ]
    )
    assert captured["scheduler"] == "continuous"
    assert captured["batch_window_ms"] == 25.0

    captured.clear()
    cli.serve_command(
        ["--backend", "fake", "--port", "0", "--batch-window-ms", "75"]
    )
    assert captured["scheduler"] is None  # auto
    assert captured["batch_window_ms"] == 75.0

    with pytest.raises(CommandError, match="--scheduler"):
        serve_command(["--scheduler", "bogus"])


def test_serve_slice_and_chunk_knobs(monkeypatch):
    """--decode-slice-steps / --prefill-chunk-tokens reach the server
    (ISSUE 4: DECODE_SLICE_STEPS stops being env-only); zero means
    'auto' and negatives fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--scheduler", "continuous",
            "--decode-slice-steps", "4",
            "--prefill-chunk-tokens", "128",
        ]
    )
    assert captured["slice_steps"] == 4
    assert captured["prefill_chunk_tokens"] == 128

    captured.clear()
    cli.serve_command(
        ["--backend", "fake", "--port", "0", "--decode-slice-steps", "0"]
    )
    assert captured["slice_steps"] is None  # 0 = auto (engine default)
    assert captured["prefill_chunk_tokens"] is None

    with pytest.raises(CommandError, match="decode-slice-steps"):
        serve_command(["--decode-slice-steps", "-2"])
    with pytest.raises(CommandError, match="prefill-chunk-tokens"):
        serve_command(["--prefill-chunk-tokens", "-8"])


def test_serve_ttft_slo_knob(monkeypatch):
    """--ttft-slo-ms reaches the server (ISSUE 6: the TTFT SLO becomes
    enforceable at admission); 0 means off and negatives fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        ["--backend", "fake", "--port", "0", "--ttft-slo-ms", "250"]
    )
    assert captured["ttft_slo_ms"] == 250.0

    captured.clear()
    cli.serve_command(
        ["--backend", "fake", "--port", "0", "--ttft-slo-ms", "0"]
    )
    assert captured["ttft_slo_ms"] is None  # 0 = no SLO

    with pytest.raises(CommandError, match="ttft-slo-ms"):
        serve_command(["--ttft-slo-ms", "-5"])


def test_serve_preemption_knobs(monkeypatch):
    """--default-priority / --preempt-policy / --preempt-max-wait-s
    reach the server (ISSUE 11); tier names parse, bad values fail
    fast, and omitting the flags leaves the scheduler defaults."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--default-priority", "high",
            "--preempt-policy", "recompute",
            "--preempt-max-wait-s", "7.5",
        ]
    )
    assert captured["default_priority"] == 2  # "high"
    assert captured["preempt_policy"] == "recompute"
    assert captured["preempt_max_wait_s"] == 7.5

    captured.clear()
    cli.serve_command(["--backend", "fake", "--port", "0"])
    assert captured["default_priority"] is None  # server default (normal)
    assert captured["preempt_policy"] is None  # scheduler default (swap)
    assert captured["preempt_max_wait_s"] is None

    with pytest.raises(CommandError, match="default-priority"):
        serve_command(["--default-priority", "urgent-ish"])
    with pytest.raises(CommandError, match="preempt-policy"):
        serve_command(["--preempt-policy", "maybe"])
    with pytest.raises(CommandError, match="preempt-max-wait-s"):
        serve_command(["--preempt-max-wait-s", "-1"])


def test_serve_prefix_share_knobs(monkeypatch):
    """--prefix-share / --prefix-index-entries reach the ENGINE (ISSUE
    7: shared-prefix CoW paging is a backend capability, not a
    scheduler one); bad capacities fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured["backend"] = backend

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "jax", "--port", "0",
            "--prefix-share", "--prefix-index-entries", "4",
            "--paged-kv", "--kv-quantize", "int8",
        ]
    )
    backend = captured["backend"]
    assert backend.prefix_share is True
    assert backend.prefix_index_entries == 4
    # the retired exclusion: int8 KV + prefix features co-exist
    assert backend.kv_quantize == "int8" and backend.paged_kv

    captured.clear()
    cli.serve_command(["--backend", "jax", "--port", "0"])
    assert captured["backend"].prefix_share is False  # off by default

    with pytest.raises(CommandError, match="prefix-index-entries"):
        serve_command(["--prefix-index-entries", "0"])


def test_serve_prefix_store_budget_knobs(monkeypatch):
    """--prefix-store-hbm-bytes / --prefix-store-host-bytes reach the
    ENGINE's persistent prefix store (ISSUE 14); bad budgets fail
    fast; the fake backend builds a store too (hermetic CI)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured["backend"] = backend

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "jax", "--port", "0",
            "--prefix-share", "--paged-kv",
            "--prefix-store-hbm-bytes", "1048576",
            "--prefix-store-host-bytes", "2097152",
        ]
    )
    store = captured["backend"].prefix_store
    assert store is not None
    assert store.hbm_bytes == 1048576
    assert store.host_bytes == 2097152
    assert store.scope == "engine"

    captured.clear()
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--prefix-share", "--prefix-store-hbm-bytes", "4096",
        ]
    )
    fake_store = captured["backend"].prefix_store
    assert fake_store is not None and fake_store.hbm_bytes == 4096

    with pytest.raises(CommandError, match="prefix-store-hbm-bytes"):
        serve_command(["--prefix-store-hbm-bytes", "-1"])
    with pytest.raises(CommandError, match="prefix-store-host-bytes"):
        serve_command(["--prefix-store-host-bytes", "-1"])


def test_prepare_cooldown_promise_matches_consumed_channels(monkeypatch, capsys):
    """prepare's policy line must reflect the channels the study's
    profilers actually WIRE (code-review round-4): a live battery/hwmon
    channel (no consumer) must not promise measured Joules, and a live
    libtpu duty channel (kind 'utilization' but measured_channel=True in
    the study) must promise the 90 s device policy."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.energy_probe import (
        ChannelStatus,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli

    def fake_probe(statuses):
        return lambda include_device=True: statuses

    # battery-only host: SysfsPowerProfiler consumes it → host promise
    # (round-4 follow-through: the audit and the study agree)
    monkeypatch.setattr(
        "cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers."
        "energy_probe.probe_energy_channels",
        fake_probe([
            ChannelStatus("battery", "power", "host", True, "power_now ok"),
            ChannelStatus("rapl", "energy", "host", False, "no powercap"),
        ]),
    )
    cli.prepare()
    out = capsys.readouterr().out
    assert "measured HOST energy channel present" in out

    # live libtpu duty channel → the 90 s device-channel promise
    monkeypatch.setattr(
        "cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers."
        "energy_probe.probe_energy_channels",
        fake_probe([
            ChannelStatus(
                "libtpu_monitoring", "utilization", "device", True, "duty ok"
            ),
        ]),
    )
    cli.prepare()
    out = capsys.readouterr().out
    assert "measured DEVICE energy channel present" in out
    assert "90 s" in out

    # readable RAPL → the every-mode host promise
    monkeypatch.setattr(
        "cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers."
        "energy_probe.probe_energy_channels",
        fake_probe([
            ChannelStatus("rapl", "energy", "host", True, "energy_uj ok"),
        ]),
    )
    cli.prepare()
    out = capsys.readouterr().out
    assert "measured HOST energy channel present" in out


def test_serve_replica_fleet_knobs(monkeypatch):
    """--replicas / --route-policy / --probe-interval-ms build the
    front-door router over N independent local replicas (ISSUE 12);
    bad values fail fast with CommandError."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeRouterServer:
        def __init__(self, router, **kw):
            captured["router"] = router
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router as rt

    monkeypatch.setattr(rt, "RouterServer", FakeRouterServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--replicas", "3",
            "--route-policy", "round-robin",
            "--probe-interval-ms", "50",
        ]
    )
    router = captured["router"]
    try:
        names = [r.name for r in router.replicas()]
        assert names == ["r0", "r1", "r2"]
        assert router.policy == "round-robin"
        assert router.probe_interval_s == 0.05
        # each replica is fully independent: distinct backend objects
        backends = {id(r.backend) for r in router.replicas()}
        assert len(backends) == 3
    finally:
        router.stop()

    with pytest.raises(CommandError, match="--replicas"):
        serve_command(["--replicas", "0"])
    with pytest.raises(CommandError, match="--route-policy"):
        serve_command(["--route-policy", "fastest"])
    with pytest.raises(CommandError, match="--probe-interval-ms"):
        serve_command(["--probe-interval-ms", "-5"])


def test_serve_model_policy_knobs(monkeypatch):
    """--model-policy / --escalate-max-tokens reach the server (ISSUE
    15: the multi-model fleet scheduler); bad values fail fast and
    omitting the flags keeps single-model serving."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_command,
    )

    captured = {}

    class FakeServer:
        def __init__(self, backend, **kw):
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server as srv

    monkeypatch.setattr(srv, "GenerationServer", FakeServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--models", "small:1b,big:7b",
            "--model-policy", "cheapest-joules",
            "--escalate-max-tokens", "16",
        ]
    )
    assert captured["model_policy"] == "cheapest-joules"
    assert captured["escalate_max_tokens"] == 16
    assert captured["models"] == ["small:1b", "big:7b"]

    captured.clear()
    cli.serve_command(["--backend", "fake", "--port", "0"])
    assert captured["model_policy"] is None  # single-model serving
    assert captured["escalate_max_tokens"] is None

    with pytest.raises(CommandError, match="model-policy"):
        serve_command(["--model-policy", "biggest-first"])
    with pytest.raises(CommandError, match="escalate-max-tokens"):
        serve_command(["--escalate-max-tokens", "0"])
    with pytest.raises(CommandError, match="escalate-max-tokens"):
        serve_command(["--escalate-max-tokens", "lots"])


def test_serve_replicas_with_model_policy_builds_fleet_lanes(monkeypatch):
    """--replicas N + --model-policy: each replica hosts its OWN
    multi-model fleet scheduler over its own backend."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.model_fleet import (  # noqa: E501
        ModelFleetScheduler,
    )

    captured = {}

    class FakeRouterServer:
        def __init__(self, router, **kw):
            captured["router"] = router

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router as rt

    monkeypatch.setattr(rt, "RouterServer", FakeRouterServer)
    cli.serve_command(
        [
            "--backend", "fake", "--port", "0",
            "--replicas", "2",
            "--models", "small:1b,big:7b",
            "--model-policy", "small-first",
        ]
    )
    router = captured["router"]
    try:
        for replica in router.replicas():
            assert isinstance(replica.scheduler, ModelFleetScheduler)
            assert replica.scheduler.model_policy == "small-first"
            assert set(replica.scheduler._lanes) == {
                "small:1b",
                "big:7b",
            }
    finally:
        router.stop()


def test_serve_fleet_command_knobs(monkeypatch):
    """serve-fleet attaches RemoteReplicas for each --targets entry;
    missing targets / bad policy fail fast."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner import cli
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import (
        CommandError,
        serve_fleet_command,
    )

    captured = {}

    class FakeRouterServer:
        def __init__(self, router, **kw):
            captured["router"] = router
            captured.update(kw)

        def serve_forever(self):
            return None

    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router as rt

    monkeypatch.setattr(rt, "RouterServer", FakeRouterServer)
    cli.serve_fleet_command(
        [
            "--port", "0",
            "--targets", "127.0.0.1:9,http://127.0.0.1:10",
            "--route-policy", "least-pages",
        ]
    )
    router = captured["router"]
    try:
        urls = [r.base_url for r in router.replicas()]
        assert urls == ["http://127.0.0.1:9", "http://127.0.0.1:10"]
        assert router.policy == "least-pages"
        # dead targets are tolerated at attach: probed, marked down
        assert all(not r.healthy for r in router.replicas())
    finally:
        router.stop()

    with pytest.raises(CommandError, match="--targets"):
        serve_fleet_command([])
    with pytest.raises(CommandError, match="--route-policy"):
        serve_fleet_command(
            ["--targets", "a:1", "--route-policy", "nope"]
        )
