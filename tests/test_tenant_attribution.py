"""Per-row slice attribution + tenant accounting (ISSUE 20).

Engine half: every wall second and modelled Joule a stepped session
bills anywhere lands in exactly one of three books — a live row's
account, a retired row's ``extras["energy_model"]`` close-out, or the
session's dropped bucket (cancel / join-abort / close) — so
``totals == retired + live + dropped`` holds to 1e-6 across cache
layouts, chunked joiners, preempt/resume and cancellation, on the real
engine AND its hermetic fake twin (whose synthetic energy model makes
the identity ``J == joules_per_token × generated_tokens`` exact).

Serve half: the bounded tenant table (overflow → ``_other``), the
``account_request`` funnel (counters + table + ledger in one call), the
append-only JSONL usage ledger's monotonic-seq resume across reopen
(torn tails tolerated), the ``x_tenant`` wire field, and kill-switch
inertness (no attribution, no close-out, no accounting, 404 endpoint).
"""

import json
import os

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
    metrics as obs_metrics,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
    tenants as obs_tenants,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.tenants import (
    DEFAULT_TENANT,
    OTHER_TENANT,
    TenantTable,
    UsageLedger,
    read_ledger,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol

TOL = 1e-6


@pytest.fixture(scope="module")
def engines():
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    cache = {}

    def get(paged, kvq):
        key = (paged, kvq)
        if key not in cache:
            cache[key] = JaxEngine(
                registry=dict(registry),
                dtype=jnp.float32,
                paged_kv=paged,
                kv_quantize=kvq,
            )
        return cache[key]

    return get


def _em(res):
    return (res.extras or {}).get("energy_model")


def _books_real(sess, results):
    """(totals, retired+live+dropped) per conserved key, real session."""
    out = {}
    ems = [e for e in (_em(r) for r in results) if e]
    live = [row for row in sess.rows if row is not None]
    for key, em_key, attr in (
        ("wall", "wall_attr_s", "attr_wall"),
        ("J", "J", "attr_J"),
        ("J_low", "J_low", "attr_J_low"),
        ("J_high", "J_high", "attr_J_high"),
    ):
        billed = (
            sum(e[em_key] for e in ems)
            + sum(getattr(row, attr) for row in live)
            + sess._attr_dropped[key]
        )
        out[key] = (sess._attr_totals[key], billed)
    return out


def _assert_conserved_real(sess, results):
    for key, (total, billed) in _books_real(sess, results).items():
        assert abs(total - billed) < TOL, (key, total, billed)


def _books_fake(sess, results):
    ems = [e for e in (_em(r) for r in results) if e]
    live = sess._rows + sess._pending
    out = {}
    for key, em_key, attr in (
        ("wall", "wall_attr_s", "attr_wall"),
        ("J", "J", "attr_J"),
    ):
        billed = (
            sum(e[em_key] for e in ems)
            + sum(row.get(attr, 0.0) for row in live)
            + sess._attr_dropped[key]
        )
        out[key] = (sess._attr_totals[key], billed)
    return out


def _assert_conserved_fake(sess, results):
    for key, (total, billed) in _books_fake(sess, results).items():
        assert abs(total - billed) < TOL, (key, total, billed)


def _drain(sess, max_steps=8, limit=200):
    out = []
    for _ in range(limit):
        if not sess.active:
            break
        out.extend(sess.step(max_steps))
    assert not sess.active, "session did not drain"
    return out


# -- real engine: conservation across layouts, joiners, drops ------------------


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_conservation_all_layouts_with_chunked_joiner(engines, paged, kv):
    """Everything the session bills — decode slices AND a chunked
    joiner's prefill — closes out: totals == retired close-outs (+
    nothing live, nothing dropped) on every cache layout."""
    eng = engines(paged, kv)
    anchor = GenerationRequest(
        "tiny", "a" * 120, max_new_tokens=32, stop_at_eos=False, seed=1
    )
    short = GenerationRequest("tiny", "short row", max_new_tokens=8, seed=2)
    sess = eng.decode_open([anchor, short], reserve_rows=4)
    results = []
    results.extend(sess.step(4))
    joiner = GenerationRequest("tiny", "j" * 80, max_new_tokens=8, seed=3)
    assert sess.can_join(joiner)
    pj = sess.join_begin(joiner, chunk_tokens=32)
    while not sess.join_step(pj):
        # the companions keep decoding between prefill chunks
        results.extend(sess.step(2))
    sess.join_commit(pj)
    results.extend(_drain(sess))
    assert len(results) == 3
    for res in results:
        em = _em(res)
        assert em is not None
        assert em["window"] == "slice"
        assert em["slices"] >= 1
        assert em["wall_attr_s"] > 0
        assert em["J_low"] <= em["J"] <= em["J_high"]
        if res.generated_tokens:
            assert (
                abs(em["J_per_token"] - em["J"] / res.generated_tokens)
                < TOL
            )
    assert sess._attr_dropped["wall"] == 0.0
    _assert_conserved_real(sess, results)
    sess.close()


def test_conservation_cancel_moves_account_to_dropped(engines):
    eng = engines(False, None)
    keep = GenerationRequest(
        "tiny", "keeps decoding", max_new_tokens=16, stop_at_eos=False
    )
    victim = GenerationRequest(
        "tiny", "cancelled mid-flight", max_new_tokens=40,
        stop_at_eos=False, seed=7,
    )
    sess = eng.decode_open([keep, victim], reserve_rows=4)
    sess.step(4)
    billed_before = next(
        row for row in sess.rows
        if row is not None and row.request is victim
    ).attr_wall
    assert billed_before > 0  # the victim had already been billed
    assert sess.cancel(victim)
    assert sess._attr_dropped["wall"] >= billed_before - TOL
    results = _drain(sess)
    # the cancelled row never closed out; the survivor did
    assert [r.request for r in results] == [keep]
    _assert_conserved_real(sess, results)
    sess.close()


def test_conservation_join_abort_drops_chunk_bill(engines):
    eng = engines(True, None)
    anchor = GenerationRequest(
        "tiny", "anchor", max_new_tokens=16, stop_at_eos=False
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(2)
    pj = sess.join_begin(
        GenerationRequest("tiny", "j" * 90, max_new_tokens=8),
        chunk_tokens=32,
    )
    sess.join_step(pj)  # one chunk billed to the pending account
    assert pj.attr_wall > 0
    sess.join_abort(pj)
    assert sess._attr_dropped["wall"] >= pj.attr_wall - TOL
    results = _drain(sess)
    _assert_conserved_real(sess, results)
    sess.close()


@pytest.mark.parametrize(
    "paged,kv,policy",
    [(True, None, "swap"), (False, None, "recompute")],
    ids=["paged-swap", "contig-recompute"],
)
def test_conservation_preempt_resume(engines, paged, kv, policy):
    """A preempted row's account survives the park: pre-preempt slices
    plus the resume re-prefill plus post-resume slices all land in ONE
    close-out, and the session books still balance."""
    eng = engines(paged, kv)
    anchor = GenerationRequest(
        "tiny", "anchor keeps decoding", max_new_tokens=24,
        stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "victim row", max_new_tokens=16, stop_at_eos=False, seed=7
    )
    sess = eng.decode_open([anchor, victim], reserve_rows=4)
    sess.step(4)
    pr = sess.preempt(victim, policy=policy)
    assert pr is not None
    assert pr.attr_wall > 0  # the park carries the billed account
    sess.step(2)
    pend = sess.resume_begin(pr, 64)
    while not sess.join_step(pend):
        pass
    sess.join_commit(pend)
    results = _drain(sess)
    by_req = {id(r.request): r for r in results}
    em_v = _em(by_req[id(victim)])
    assert em_v is not None
    # the close-out covers at least what was billed before the park
    assert em_v["wall_attr_s"] >= pr.attr_wall - TOL
    assert sess._attr_dropped["wall"] == 0.0
    _assert_conserved_real(sess, results)
    sess.close()


def test_conservation_close_abandons_live_rows(engines):
    eng = engines(False, None)
    reqs = [
        GenerationRequest(
            "tiny", "abandoned a", max_new_tokens=40, stop_at_eos=False
        ),
        GenerationRequest(
            "tiny", "abandoned b", max_new_tokens=40, stop_at_eos=False,
            seed=3,
        ),
    ]
    sess = eng.decode_open(reqs)
    sess.step(4)
    assert sess._attr_totals["wall"] > 0
    sess.close()
    _assert_conserved_real(sess, [])  # everything moved to dropped
    assert sess._attr_dropped["wall"] > 0


# -- fake engine: exact synthetic identity + the same invariant ----------------


def test_fake_identity_and_conservation():
    """The fake's energy model is ``jpt × tokens``, so a retired row's
    slice-summed J equals the whole-request figure EXACTLY — and the
    joiner's prefill chunks bill wall only."""
    jpt = 0.25
    backend = FakeBackend(joules_per_token=jpt)
    reqs = [
        GenerationRequest("m", "row one", max_new_tokens=12),
        GenerationRequest("m", "row two", max_new_tokens=30),
    ]
    sess = backend.decode_open(reqs)
    results = []
    results.extend(sess.step(4))
    joiner = GenerationRequest("m", "j" * 64, max_new_tokens=8)
    pj = sess.join_begin(joiner, chunk_tokens=16)
    while not sess.join_step(pj):
        results.extend(sess.step(2))
    sess.join_commit(pj)
    results.extend(_drain(sess, max_steps=4))
    assert len(results) == 3
    for res in results:
        em = _em(res)
        assert em is not None and em["window"] == "slice"
        assert abs(em["J"] - jpt * res.generated_tokens) < TOL
    _assert_conserved_fake(sess, results)
    sess.close()


def test_fake_cancel_and_close_drop_exactly():
    jpt = 0.5
    backend = FakeBackend(joules_per_token=jpt)
    keep = GenerationRequest("m", "kept", max_new_tokens=8)
    gone = GenerationRequest("m", "cancelled", max_new_tokens=40)
    left = GenerationRequest("m", "abandoned at close", max_new_tokens=40)
    sess = backend.decode_open([keep, gone, left])
    sess.step(4)
    assert sess.cancel(gone)
    # 4 tokens were billed to the cancelled row before it left
    assert abs(sess._attr_dropped["J"] - jpt * 4) < TOL
    results = []
    for _ in range(10):
        results.extend(sess.step(4))
        if any(r.request is keep for r in results):
            break
    sess.close()  # the long row dies live
    _assert_conserved_fake(sess, results)
    assert sess._attr_dropped["J"] > jpt * 4  # close added the live row


def test_fake_preempt_resume_keeps_identity():
    """The row dict parks through preempt, so the resumed row's
    close-out is the FULL lifetime figure — pre-park tokens included —
    under both policies."""
    jpt = 0.125
    for policy in ("swap", "recompute"):
        backend = FakeBackend(joules_per_token=jpt)
        anchor = GenerationRequest("m", "anchor", max_new_tokens=24)
        victim = GenerationRequest("m", "victim", max_new_tokens=16)
        sess = backend.decode_open([anchor, victim])
        sess.step(4)
        pr = sess.preempt(victim, policy=policy)
        assert pr is not None
        sess.step(4)
        pend = sess.resume_begin(pr, 32)
        while not sess.join_step(pend):
            pass
        sess.join_commit(pend)
        results = _drain(sess, max_steps=4)
        by_req = {id(r.request): r for r in results}
        em_v = _em(by_req[id(victim)])
        assert abs(em_v["J"] - jpt * 16) < TOL, policy
        _assert_conserved_fake(sess, results)
        sess.close()


def test_fake_fully_rejected_spec_rounds_mirror_wasted():
    """Cross-source spec at acceptance 0: every round fully rejects, the
    draft burn mirrors into the owning row's close-out as ``wasted_J``
    — and the PRIMARY books (attr_J) stay the clean jpt × tokens
    figure, wasted never folds in."""
    jpt, draft_jpt = 0.25, 0.05
    backend = FakeBackend(
        joules_per_token=jpt,
        spec_k=4,
        spec_acceptance=0.0,
        spec_source="cross",
        spec_draft="draft:1b",
        model_joules={"m": jpt, "draft:1b": draft_jpt},
    )
    req = GenerationRequest("m", "rejected rows", max_new_tokens=12)
    sess = backend.decode_open([req])
    results = _drain(sess, max_steps=4)
    em = _em(results[0])
    assert abs(em["J"] - jpt * 12) < TOL
    # 12 rounds × k=4 drafted tokens, all rejected, at the draft price
    assert em.get("wasted_J", 0.0) == pytest.approx(
        12 * 4 * draft_jpt, abs=1e-5
    )
    _assert_conserved_fake(sess, results)
    sess.close()


# -- tenant table, account funnel, ledger --------------------------------------


def test_tenant_table_overflow_routes_to_other():
    t = TenantTable(max_tenants=2)
    assert t.resolve("a") == "a"
    assert t.resolve("b") == "b"
    assert t.resolve("c") == OTHER_TENANT  # past the bound
    assert t.resolve("a") == "a"  # first-come mapping is sticky
    # the default tenant and the overflow label never consume slots
    assert t.resolve(None) == DEFAULT_TENANT
    assert t.resolve(DEFAULT_TENANT) == DEFAULT_TENANT
    assert t.resolve(OTHER_TENANT) == OTHER_TENANT
    t.record("a", "ok", 10, 5, 1.5, {"retry": 0.25})
    t.record(t.resolve("c"), "ok", 1, 2, 0.5, None)
    t.record(t.resolve("d"), "error", 1, 0, 0.25, None)
    snap = t.snapshot()
    assert snap["a"] == {
        "requests": {"ok": 1},
        "tokens_in": 10,
        "tokens_out": 5,
        "joules": 1.5,
        "wasted_J": {"retry": 0.25},
    }
    # everything past the bound aggregates under one label
    assert snap[OTHER_TENANT]["requests"] == {"ok": 1, "error": 1}
    assert snap[OTHER_TENANT]["joules"] == 0.75


def _family_sum(name):
    fam = obs_metrics.REGISTRY.snapshot().get(name) or {}
    return sum(v for v in fam.values() if isinstance(v, (int, float)))


def test_account_request_funnel_counters_table_ledger(tmp_path):
    obs_tenants.reset_tenants()
    led = UsageLedger(str(tmp_path))
    prev = obs_tenants.install_ledger(led)
    j0 = _family_sum("llm_tenant_joules_total")
    try:
        obs_tenants.account_request(
            "acme", "ok", tokens_in=3, tokens_out=7, joules=1.25,
            wasted={"retry": 0.5}, model="m",
        )
        obs_tenants.account_request("acme", "cancelled")
        snap = obs_tenants.snapshot()
        acct = snap["tenants"]["acme"]
        assert acct["requests"] == {"ok": 1, "cancelled": 1}
        assert acct["tokens_in"] == 3 and acct["tokens_out"] == 7
        assert acct["joules"] == 1.25
        assert acct["wasted_J"] == {"retry": 0.5}
        assert snap["ledger"] == {"dir": str(tmp_path), "seq": 2}
        assert _family_sum("llm_tenant_joules_total") == pytest.approx(
            j0 + 1.25
        )
        records = read_ledger(str(tmp_path))
        assert [r["seq"] for r in records] == [1, 2]
        assert records[0]["tenant"] == "acme"
        assert records[0]["joules"] == 1.25
        assert records[0]["model"] == "m"
    finally:
        obs_tenants.install_ledger(prev)
        led.close()
        obs_tenants.reset_tenants()


def test_ledger_seq_resumes_across_reopen_and_torn_tail(tmp_path):
    led = UsageLedger(str(tmp_path))
    led.append({"tenant": "a", "outcome": "ok"})
    led.append({"tenant": "b", "outcome": "ok"})
    assert led.seq == 2
    led.close()
    # simulate a crash mid-write: a torn, unparseable tail line
    path = os.path.join(str(tmp_path), UsageLedger.LEDGER_NAME)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"seq": 99, "tenant"')
    led2 = UsageLedger(str(tmp_path))
    assert led2.seq == 2  # torn line ignored, sequence resumed
    led2.append({"tenant": "a", "outcome": "ok"})
    records = read_ledger(str(tmp_path))
    seqs = [r["seq"] for r in records]
    assert seqs == [1, 2, 3]  # strictly monotonic, no double-billing
    table = TenantTable()
    table.record("a", "ok", 1, 1, 0.5, None)
    led2.write_snapshot(table)
    with open(
        os.path.join(str(tmp_path), UsageLedger.SNAPSHOT_NAME),
        encoding="utf-8",
    ) as fh:
        snap = json.load(fh)
    assert snap["seq"] == 3
    assert "a" in snap["tenants"]
    led2.close(table)
    # appends after close are dropped, not crashed
    led2.append({"tenant": "a", "outcome": "ok"})
    assert [r["seq"] for r in read_ledger(str(tmp_path))] == [1, 2, 3]


# -- wire field ----------------------------------------------------------------


def test_x_tenant_wire_roundtrip():
    req = GenerationRequest("m", "p", max_new_tokens=4, tenant="acme")
    wire = protocol.request_to_wire(req)
    assert wire["x_tenant"] == "acme"
    assert protocol.request_from_wire(wire).tenant == "acme"
    # the default tenant stays off the wire entirely
    plain = protocol.request_to_wire(
        GenerationRequest("m", "p", max_new_tokens=4)
    )
    assert "x_tenant" not in plain
    assert protocol.request_from_wire(plain).tenant == DEFAULT_TENANT
    for bad in (7, "", ["a"]):
        with pytest.raises(ValueError):
            protocol.request_from_wire(
                {"model": "m", "prompt": "p", "x_tenant": bad}
            )


# -- kill switch: zero-alloc inertness -----------------------------------------


def test_kill_switch_disables_attribution_and_accounting():
    obs_metrics.disable()
    try:
        backend = FakeBackend(joules_per_token=0.25)
        sess = backend.decode_open(
            [GenerationRequest("m", "dark row", max_new_tokens=8)]
        )
        results = _drain(sess, max_steps=4)
        # no books were kept and no close-out was stamped
        assert sess._attr_totals == {
            "wall": 0.0, "J": 0.0, "J_low": 0.0, "J_high": 0.0
        }
        assert _em(results[0]) is None
        sess.close()
        obs_tenants.reset_tenants()
        obs_tenants.account_request("ghost", "ok", tokens_out=5, joules=1.0)
        assert obs_tenants.snapshot()["tenants"] == {}
    finally:
        obs_metrics.enable()
        obs_tenants.reset_tenants()


def test_debug_tenants_endpoint_404s_under_kill_switch():
    import urllib.error
    import urllib.request

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
        GenerationServer,
    )

    server = GenerationServer(
        FakeBackend(joules_per_token=0.1),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    server.start()
    try:
        url = (
            f"http://127.0.0.1:{server.port}"
            + protocol.DEBUG_TENANTS_PATH
        )
        with urllib.request.urlopen(url, timeout=10) as resp:
            payload = json.loads(resp.read())
        assert "tenants" in payload and "table_max" in payload
        assert payload["role"] == "mixed"
        obs_metrics.disable()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == 404
        finally:
            obs_metrics.enable()
        # re-enabled: the endpoint serves again
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert "tenants" in json.loads(resp.read())
    finally:
        server.stop()
