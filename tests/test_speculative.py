"""Speculative decoding: bit-identical to plain greedy, with real speedup
accounting (rounds/accepted counters). Hermetic on tiny CPU models."""

import dataclasses

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)


@pytest.fixture(scope="module")
def registry():
    target = get_model_config("qwen2:7b").tiny()
    draft = dataclasses.replace(
        get_model_config("qwen2:1.5b").tiny(), vocab_size=target.vocab_size
    )
    return {"target": target, "draft": draft}


@pytest.fixture(scope="module")
def engine(registry):
    return JaxEngine(registry=registry, dtype=jnp.float32)


def test_speculative_matches_plain_greedy(engine):
    req = GenerationRequest("target", "speculate on this", max_new_tokens=24)
    plain = engine.generate(req)
    spec = engine.generate_speculative(req, "draft", k=4)
    assert spec.tokens == plain.tokens
    assert spec.text == plain.text
    assert spec.extras is not None
    assert spec.extras["spec_rounds"] >= 1
    # an independent random draft rarely matches the target; the invariant
    # is correctness, not acceptance
    assert 0 <= spec.extras["spec_accepted"] <= 24


def test_self_draft_accepts_everything(registry):
    """A model drafting for itself agrees with itself: every round accepts
    all k drafts, so rounds ≈ budget/(k+1) — the mechanism demonstrably
    skips target steps."""
    engine = JaxEngine(registry=registry, dtype=jnp.float32)
    req = GenerationRequest("target", "agree", max_new_tokens=23)
    plain = engine.generate(req)
    spec = engine.generate_speculative(req, "target", k=4)
    assert spec.tokens == plain.tokens
    n_after_first = 22  # budget minus the prefill-sampled first token
    import math

    assert spec.extras["spec_rounds"] <= math.ceil(n_after_first / 5) + 1
    assert spec.extras["spec_accepted"] >= spec.extras["spec_rounds"] * 3


def test_speculative_routing_via_generate(registry):
    engine = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        speculative={"target": ("draft", 3)},
    )
    greedy = engine.generate(
        GenerationRequest("target", "routed", max_new_tokens=12)
    )
    assert greedy.extras is not None and greedy.extras["k"] == 3
    # sampled requests speculate too (ISSUE 16): the rejection-resampling
    # lane serves them through the same configured draft
    sampled = engine.generate(
        GenerationRequest(
            "target", "routed", max_new_tokens=12, temperature=0.9, seed=1
        )
    )
    spec_x = (sampled.extras or {}).get("spec")
    assert spec_x is not None and spec_x["source"] == "model"
    assert spec_x["draft_model"] == "draft"
    assert sampled.extras["spec_rounds"] >= 1
    assert sampled.generated_tokens <= 12


def test_speculative_respects_eos_and_budget(engine):
    # tiny budget: no decode rounds needed beyond the first token
    one = engine.generate_speculative(
        GenerationRequest("target", "x", max_new_tokens=1), "draft", k=4
    )
    assert one.generated_tokens <= 1
    # longer budgets never overshoot
    for budget in (2, 5, 17):
        r = engine.generate_speculative(
            GenerationRequest("target", "zz", max_new_tokens=budget),
            "draft",
            k=4,
        )
        assert r.generated_tokens <= budget


def test_speculative_rejects_vocab_mismatch(engine):
    small = get_model_config("gemma:2b").tiny()
    engine2 = JaxEngine(
        registry={
            "target": engine.registry["target"],
            "other": dataclasses.replace(small, vocab_size=32),
        },
        dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="vocab"):
        engine2.generate_speculative(
            GenerationRequest("target", "x", max_new_tokens=4), "other"
        )


def test_non_coresident_pair_falls_back_to_plain_decode(registry, monkeypatch):
    """When target+draft can't share the allocation budget, the request is
    served by plain greedy decode (same tokens) with a warning — a
    configured draft must never hard-fail a request plain decoding would
    serve (ADVICE round-2)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils import memory as mem
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    one = estimate_weight_bytes(registry["target"], None, 4)
    monkeypatch.setattr(mem, "LOAD_TRANSIENT_HEADROOM_BYTES", 0)
    # budget fits ONE model, never two
    monkeypatch.setenv("TPU_ALLOC_BUDGET_BYTES", str(int(1.2 * one)))
    engine = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        speculative={"target": ("draft", 4)},
    )
    req = GenerationRequest("target", "cannot be co-resident", max_new_tokens=12)
    result = engine.generate(req)  # must not raise
    assert result.generated_tokens > 0
    # plain path, not speculative (obs may attach energy extras)
    assert "spec_rounds" not in (result.extras or {})
    # token-identical to an unconfigured engine's plain decode
    plain = JaxEngine(registry=registry, dtype=jnp.float32).generate(req)
    assert result.tokens == plain.tokens


def test_speculative_rejects_repeat_penalty(engine):
    # Sampling no longer raises (ISSUE 16: rejection resampling serves
    # it); the presence penalty remains excluded — it perturbs the
    # modified distribution per EMITTED token, which a k-wide proposal
    # step cannot replicate mid-round.
    with pytest.raises(ValueError, match="repeat_penalty"):
        engine.generate_speculative(
            GenerationRequest(
                "target",
                "x",
                max_new_tokens=4,
                temperature=0.5,
                repeat_penalty=1.3,
            ),
            "draft",
        )


def test_routing_falls_back_when_margin_does_not_fit(registry):
    """Configuring a draft must never reject a request plain decode
    serves: tiny max_seq_len=256, prompt bucket 32 + gen bucket 128 fits
    plainly (160) but not with the speculative margin (+128 = 288)."""
    engine = JaxEngine(
        registry=registry,
        dtype=jnp.float32,
        speculative={"target": ("draft", 4)},
    )
    r = engine.generate(
        GenerationRequest("target", "long budget", max_new_tokens=128)
    )
    # plain path served it (obs may attach energy extras)
    assert "spec_rounds" not in (r.extras or {})
    assert r.generated_tokens >= 1


def test_spec_accepted_counts_only_emitted_drafts(registry):
    """Self-draft with stop_at_eos=False: accepted must never exceed the
    emitted post-first tokens, even when rounds clip at EOS."""
    engine = JaxEngine(registry=registry, dtype=jnp.float32)
    req = GenerationRequest(
        "target", "count", max_new_tokens=19, stop_at_eos=False
    )
    spec = engine.generate_speculative(req, "target", k=4)
    assert spec.extras["spec_accepted"] <= max(0, spec.generated_tokens - 1)


def test_spec_accepted_clipped_at_budget(registry):
    """Repro from review: self-draft with a budget smaller than a full
    round must not count overshoot drafts."""
    engine = JaxEngine(registry=registry, dtype=jnp.float32)
    for budget in (7, 3, 2):
        spec = engine.generate_speculative(
            GenerationRequest(
                "target", "clip", max_new_tokens=budget, stop_at_eos=False
            ),
            "target",
            k=4,
        )
        assert (
            spec.extras["spec_accepted"] <= max(0, spec.generated_tokens - 1)
        ), budget
