"""Serial power meter profiler with an injected fake reader (no hardware)."""

import time

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.serial_power import (
    SerialPowerMeterProfiler,
    parse_wattsup_frame,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import RunContext


def test_parse_wattsup_frames():
    assert parse_wattsup_frame("#d,-,3,1205,1187,412,x;") == {
        "power_W": 120.5,
        "volts_V": 118.7,
        "amps_A": 0.412,
    }
    assert parse_wattsup_frame("#h,header,stuff") is None
    assert parse_wattsup_frame("#d,too,short") is None
    assert parse_wattsup_frame("#d,a,b,notanumber,1,2") is None


class FakeSerial:
    """Emits one 100 W frame every ~10 ms."""

    def __init__(self):
        self.closed = False

    def readline(self):
        time.sleep(0.01)
        return b"#d,-,3,1000,1200,500,0;\r\n"

    def close(self):
        self.closed = True


def test_profiler_integrates_fake_meter(tmp_path):
    run_dir = tmp_path / "r"
    run_dir.mkdir()
    ctx = RunContext("r", 1, 1, {}, run_dir, tmp_path)
    fake = FakeSerial()
    prof = SerialPowerMeterProfiler(reader_factory=lambda: fake)
    prof.on_start(ctx)
    time.sleep(0.15)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert fake.closed
    assert data["wall_avg_power_W"] == 100.0
    assert data["wall_energy_J"] > 0
    assert (run_dir / "wall_power.csv").exists()


def test_profiler_graceful_without_reader(tmp_path):
    ctx = RunContext("r", 1, 1, {}, tmp_path, tmp_path)
    prof = SerialPowerMeterProfiler(reader_factory=lambda: None)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx) == {
        "wall_energy_J": None,
        "wall_avg_power_W": None,
    }
