"""The REAL jax.distributed boundary: a 2-process CPU group on localhost.

VERDICT round-2 item 4: ``parallel/distributed.py`` wrapped
``jax.distributed.initialize`` but no test ever spun up an actual
2-process runtime — only the env parsing was covered. This test forks two
fresh Python processes (clean JAX runtimes), joins them through a
localhost coordinator via ``initialize_distributed()``, asserts
``jax.process_count() == 2``, and runs one cross-process ``psum`` whose
result every process must agree on — the DCN machine boundary the
reference exercises with a second physical machine and ``.env SERVER_IP``
(experiment/RunnerConfig.py:122-131, README.md:25-31).
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent

WORKER = r"""
import json, os, sys

import jax

# The axon sitecustomize force-selects jax_platforms="axon,cpu" in every
# fresh interpreter regardless of the env var; beat it (tests/conftest.py
# does the same) so the workers never touch the real chip.
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

# clean runtime: the conftest's CPU forcing is inherited via env
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.distributed import (
    initialize_distributed,
    global_device_summary,
    is_coordinator,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.compat import shard_map

joined = initialize_distributed()
assert joined, "COORDINATOR_ADDRESS was set; initialize must join"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2  # one CPU device per process, global view

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

mesh = Mesh(jax.devices(), ("dcn",))

# each process contributes (process_index + 1); psum over the mesh axis
# must give 1 + 2 = 3 in BOTH processes.
local = jnp.asarray([float(jax.process_index() + 1)])
global_arr = multihost_utils.host_local_array_to_global_array(
    local, mesh, P("dcn")
)

summed = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(x, "dcn"),
        mesh=mesh,
        in_specs=P("dcn"),
        out_specs=P(),
    )
)(global_arr)

import numpy as np

local_value = float(
    np.asarray(
        multihost_utils.global_array_to_host_local_array(summed, mesh, P())
    )[0]
)
out = {
    "process_id": jax.process_index(),
    "process_count": jax.process_count(),
    "is_coordinator": is_coordinator(),
    "psum": local_value,
    "summary": global_device_summary(),
}
print("RESULT " + json.dumps(out))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(180)
def test_two_process_group_psum(tmp_path):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            # exactly one local CPU device per process (the conftest's
            # 8-virtual-device flag must not leak in)
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            PYTHONPATH=str(REPO_ROOT),
        )
        # each worker is a fresh interpreter → a fresh JAX runtime; the
        # parent process's JAX stays untouched
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                cwd=REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        line = next(
            line for line in out.splitlines() if line.startswith("RESULT ")
        )
        r = json.loads(line[len("RESULT "):])
        results[r["process_id"]] = r

    assert set(results) == {0, 1}
    for r in results.values():
        assert r["process_count"] == 2
        assert r["psum"] == pytest.approx(3.0)  # 1 + 2 across processes
    assert results[0]["is_coordinator"] and not results[1]["is_coordinator"]
    assert "2 process(es)" in results[0]["summary"]
