"""Dotenv loader."""

import os

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.env import (
    load_dotenv,
    parse_dotenv,
)


def test_parse_dotenv():
    text = """
# comment
SERVER_IP=10.0.0.5
export QUOTED="hello world"
SINGLE='x'
EMPTY=
BROKEN LINE
"""
    values = parse_dotenv(text)
    assert values == {
        "SERVER_IP": "10.0.0.5",
        "QUOTED": "hello world",
        "SINGLE": "x",
        "EMPTY": "",
    }


def test_load_dotenv_respects_existing(tmp_path, monkeypatch):
    env_file = tmp_path / ".env"
    env_file.write_text("TEST_DOTENV_VAR=from_file\n")
    monkeypatch.setenv("TEST_DOTENV_VAR", "preexisting")
    load_dotenv(env_file)
    assert os.environ["TEST_DOTENV_VAR"] == "preexisting"
    load_dotenv(env_file, override=True)
    assert os.environ["TEST_DOTENV_VAR"] == "from_file"


def test_load_dotenv_missing_file(tmp_path):
    assert load_dotenv(tmp_path / "nope.env") == {}
