"""Dotenv loader."""

import os

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.env import (
    load_dotenv,
    parse_dotenv,
)


def test_parse_dotenv():
    text = """
# comment
SERVER_IP=10.0.0.5
export QUOTED="hello world"
SINGLE='x'
EMPTY=
BROKEN LINE
"""
    values = parse_dotenv(text)
    assert values == {
        "SERVER_IP": "10.0.0.5",
        "QUOTED": "hello world",
        "SINGLE": "x",
        "EMPTY": "",
    }


def test_load_dotenv_respects_existing(tmp_path, monkeypatch):
    env_file = tmp_path / ".env"
    env_file.write_text("TEST_DOTENV_VAR=from_file\n")
    monkeypatch.setenv("TEST_DOTENV_VAR", "preexisting")
    load_dotenv(env_file)
    assert os.environ["TEST_DOTENV_VAR"] == "preexisting"
    load_dotenv(env_file, override=True)
    assert os.environ["TEST_DOTENV_VAR"] == "from_file"


def test_load_dotenv_missing_file(tmp_path):
    assert load_dotenv(tmp_path / "nope.env") == {}


# -- memory budget / weight estimation ---------------------------------------


def test_estimate_weight_bytes_matches_actual_quantized_params():
    """The fail-fast estimate must track what quantize_params actually
    allocates (within a couple of %, scales included)."""
    import jax
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        params_nbytes,
        quantize_params,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        init_params,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    # gemma ties embeddings; llama3.1/mistral don't — the untied case
    # exercises the lm_head's own per-row scale vector in the estimate
    # (ADVICE round-2: it was previously counted once, not twice).
    for base in ("qwen2:1.5b", "gemma:2b", "llama3.1:8b", "mistral:7b"):
        cfg = get_model_config(base).tiny()
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        for mode in (None, "int8", "int4"):
            actual = params_nbytes(
                quantize_params(params, mode=mode) if mode else params
            )
            est = estimate_weight_bytes(cfg, mode, dtype_bytes=4)
            assert abs(est - actual) / actual < 0.03, (base, mode, est, actual)


def test_load_model_fails_fast_when_over_budget(monkeypatch):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        ModelMemoryError,
    )

    monkeypatch.setenv("TPU_MEMORY_BUDGET_BYTES", "1000")
    engine = JaxEngine(
        registry={"tiny": get_model_config("qwen2:1.5b").tiny()},
        dtype=jnp.float32,
    )
    with pytest.raises(ModelMemoryError) as exc_info:
        engine.load_model("tiny")
    msg = str(exc_info.value)
    # actionable: both numbers, a remedy, and the override knob
    assert "GiB" in msg and "quantize" in msg and "TPU_MEMORY_BUDGET_BYTES" in msg
    assert "tiny" not in engine._models


def test_memory_budget_unknown_on_cpu(monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        device_memory_budget,
    )

    monkeypatch.delenv("TPU_MEMORY_BUDGET_BYTES", raising=False)
    assert device_memory_budget() is None  # tests run on CPU devices


# -- persistent compilation cache --------------------------------------------


def test_enable_compilation_cache_configures_jax(tmp_path):
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    before = jax.config.jax_compilation_cache_dir
    try:
        used = enable_compilation_cache(tmp_path / "cache")
        assert used.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(used)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_load_model_budget_counts_resident_models(monkeypatch):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        ModelMemoryError,
        estimate_weight_bytes,
    )

    cfg_a = get_model_config("qwen2:1.5b").tiny()
    cfg_b = get_model_config("gemma:2b").tiny()
    one = estimate_weight_bytes(cfg_a, None, 4)
    # budget fits one resident model plus half of the second — the second
    # load must fail BECAUSE of the resident one
    monkeypatch.setenv("TPU_MEMORY_BUDGET_BYTES", str(int(1.5 * one)))
    engine = JaxEngine(
        registry={"a": cfg_a, "b": cfg_b}, dtype=jnp.float32
    )
    engine.load_model("a")
    with pytest.raises(ModelMemoryError, match="already resident"):
        engine.load_model("b")
    engine.unload_all()
    engine.load_model("b")  # fits alone once the first is unloaded
