"""Dotenv loader."""

import os

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.env import (
    load_dotenv,
    parse_dotenv,
)


def test_parse_dotenv():
    text = """
# comment
SERVER_IP=10.0.0.5
export QUOTED="hello world"
SINGLE='x'
EMPTY=
BROKEN LINE
"""
    values = parse_dotenv(text)
    assert values == {
        "SERVER_IP": "10.0.0.5",
        "QUOTED": "hello world",
        "SINGLE": "x",
        "EMPTY": "",
    }


def test_load_dotenv_respects_existing(tmp_path, monkeypatch):
    env_file = tmp_path / ".env"
    env_file.write_text("TEST_DOTENV_VAR=from_file\n")
    monkeypatch.setenv("TEST_DOTENV_VAR", "preexisting")
    load_dotenv(env_file)
    assert os.environ["TEST_DOTENV_VAR"] == "preexisting"
    load_dotenv(env_file, override=True)
    assert os.environ["TEST_DOTENV_VAR"] == "from_file"


def test_load_dotenv_missing_file(tmp_path):
    assert load_dotenv(tmp_path / "nope.env") == {}


# -- memory budget / weight estimation ---------------------------------------


def test_estimate_weight_bytes_matches_actual_quantized_params():
    """The fail-fast estimate must track what quantize_params actually
    allocates (within a couple of %, scales included)."""
    import jax
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        params_nbytes,
        quantize_params,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        init_params,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_weight_bytes,
    )

    # gemma ties embeddings; llama3.1/mistral don't — the untied case
    # exercises the lm_head's own per-row scale vector in the estimate
    # (ADVICE round-2: it was previously counted once, not twice).
    for base in ("qwen2:1.5b", "gemma:2b", "llama3.1:8b", "mistral:7b"):
        cfg = get_model_config(base).tiny()
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        for mode in (None, "int8", "int4"):
            actual = params_nbytes(
                quantize_params(params, mode=mode) if mode else params
            )
            est = estimate_weight_bytes(cfg, mode, dtype_bytes=4)
            assert abs(est - actual) / actual < 0.03, (base, mode, est, actual)


def test_load_model_fails_fast_when_over_budget(monkeypatch):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        ModelMemoryError,
    )

    monkeypatch.setenv("TPU_MEMORY_BUDGET_BYTES", "1000")
    engine = JaxEngine(
        registry={"tiny": get_model_config("qwen2:1.5b").tiny()},
        dtype=jnp.float32,
    )
    with pytest.raises(ModelMemoryError) as exc_info:
        engine.load_model("tiny")
    msg = str(exc_info.value)
    # actionable: both numbers, a remedy, and the override knob
    assert "GiB" in msg and "quantize" in msg and "TPU_MEMORY_BUDGET_BYTES" in msg
    assert "tiny" not in engine._models


def test_memory_budget_unknown_on_cpu(monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        device_memory_budget,
    )

    monkeypatch.delenv("TPU_MEMORY_BUDGET_BYTES", raising=False)
    assert device_memory_budget() is None  # tests run on CPU devices


# -- persistent compilation cache --------------------------------------------


def test_enable_compilation_cache_configures_jax(tmp_path):
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    before = jax.config.jax_compilation_cache_dir
    try:
        used = enable_compilation_cache(tmp_path / "cache")
        assert used.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(used)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_load_model_budget_counts_resident_models(monkeypatch):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        ModelMemoryError,
        estimate_weight_bytes,
    )

    cfg_a = get_model_config("qwen2:1.5b").tiny()
    cfg_b = get_model_config("gemma:2b").tiny()
    one = estimate_weight_bytes(cfg_a, None, 4)
    # budget fits one resident model plus half of the second — the second
    # load must fail BECAUSE of the resident one
    monkeypatch.setenv("TPU_MEMORY_BUDGET_BYTES", str(int(1.5 * one)))
    engine = JaxEngine(
        registry={"a": cfg_a, "b": cfg_b}, dtype=jnp.float32
    )
    engine.load_model("a")
    with pytest.raises(ModelMemoryError, match="already resident"):
        engine.load_model("b")
    engine.unload_all()
    engine.load_model("b")  # fits alone once the first is unloaded


# -- decode bytes-per-step accounting (the energy model's HBM term) ----------


def test_decode_read_bytes_match_measured_traffic():
    """The bytes accounting must reproduce docs/PERF.md's measured decode
    traffic for qwen2:1.5b int8: ~1.31 GB transformer body + 233 MB
    logits head + ~9 MB KV at short context ⇒ ~1.55 GB/step."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_decode_read_bytes_per_step,
    )

    cfg = get_model_config("qwen2:1.5b")
    b = estimate_decode_read_bytes_per_step(cfg, "int8", 320)
    assert 1.45e9 < b < 1.65e9
    # bf16 doubles the matmul stream (PERF.md: 2.62 GB body)
    b16 = estimate_decode_read_bytes_per_step(cfg, None, 320)
    assert 2.7e9 < b16 < 3.3e9
    # int4 halves the matmul body relative to int8 (logits head stays int8)
    b4 = estimate_decode_read_bytes_per_step(cfg, "int4", 320)
    assert b4 < 0.75 * b
    # KV term grows linearly with context: qwen2's GQA cache is
    # 2·28·2·128·2 B = 28.7 KB per position
    delta = estimate_decode_read_bytes_per_step(
        cfg, "int8", 1320
    ) - estimate_decode_read_bytes_per_step(cfg, "int8", 320)
    assert delta == pytest.approx(1000 * 2 * 28 * 2 * 128 * 2)


def test_decode_read_bytes_kv_quantize_halves_cache_term():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_decode_read_bytes_per_step,
    )

    # phi3 is the KV-heavy family (32 full-width heads, PERF.md): at 2k
    # context its cache stream dominates, so int8 KV must cut the step's
    # bytes by roughly the cache half (minus the f32 position scales)
    cfg = get_model_config("phi3:3.8b")
    full = estimate_decode_read_bytes_per_step(cfg, "int8", 2048)
    kvq = estimate_decode_read_bytes_per_step(
        cfg, "int8", 2048, kv_quantize="int8"
    )
    kv_bf16 = 2 * 32 * 32 * 96 * 2048 * 2
    assert full - kvq == pytest.approx(
        kv_bf16 / 2 - 2 * 32 * 32 * 2048 * 4, rel=0.01
    )


def test_decode_read_bytes_moe_streams_active_experts_only():
    """Per decode step only the routed top-k experts leave HBM — an
    8-expert Mixtral layer streams 2 experts' MLPs, not 8 (matching
    flops_per_token's active-expert accounting)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        estimate_decode_read_bytes_per_step,
        estimate_weight_bytes,
    )

    cfg = get_model_config("mixtral:8x7b")
    per_step = estimate_decode_read_bytes_per_step(cfg, "int8", 128)
    resident = estimate_weight_bytes(cfg, "int8")
    # streamed bytes are far below residency (2 of 8 experts active) ...
    assert per_step < 0.45 * resident
    # ... but still dominated by the two active experts' MLPs
    active_mlp = cfg.n_layers * 3 * cfg.d_model * cfg.d_ff * 2
    assert per_step > active_mlp
