"""Continuous batching: the scheduler coalesces concurrent requests into
batched backend calls while preserving per-request results and ordering
guarantees. All hermetic (FakeBackend) — no accelerator, no network."""

import threading
import time

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
    RemoteHTTPBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
    BatchScheduler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


class RecordingBackend(FakeBackend):
    """FakeBackend that records every call's batch size."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.calls = []  # list of batch sizes (1 == single generate)

    def generate(self, request):
        self.calls.append(1)
        return super().generate(request)

    def generate_batch(self, requests):
        self.calls.append(len(requests))
        return [super(RecordingBackend, self).generate(r) for r in requests]


@pytest.fixture()
def backend():
    return RecordingBackend()


def _submit_concurrently(scheduler, requests):
    results = [None] * len(requests)
    errors = [None] * len(requests)

    def worker(i, req):
        try:
            results[i] = scheduler.submit(req)
        except BaseException as exc:  # noqa: BLE001
            errors[i] = exc

    threads = [
        threading.Thread(target=worker, args=(i, r))
        for i, r in enumerate(requests)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


def test_concurrent_compatible_requests_coalesce(backend):
    sched = BatchScheduler(backend, max_batch=8, window_s=0.2)
    sched.start()
    try:
        reqs = [
            GenerationRequest("m", f"prompt {i}", max_new_tokens=8, seed=i)
            for i in range(4)
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 4
        # each caller got its own request's result
        for req, res in zip(reqs, results):
            assert res.request == req
            assert res.tokens == backend.generate(req).tokens
        # at least one multi-row batch happened (timing-dependent how many)
        assert max(backend.calls) >= 2
    finally:
        sched.stop()


def test_incompatible_requests_split_into_separate_batches(backend):
    sched = BatchScheduler(backend, max_batch=8, window_s=0.15)
    sched.start()
    try:
        reqs = [
            GenerationRequest("model-a", "x", max_new_tokens=4),
            GenerationRequest("model-b", "y", max_new_tokens=4),
            GenerationRequest("model-a", "z", max_new_tokens=4, top_k=7),
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 3
        for req, res in zip(reqs, results):
            assert res.request == req
    finally:
        sched.stop()


def test_backend_error_fans_out_to_all_callers():
    class ExplodingBackend(FakeBackend):
        def generate(self, request):
            raise RuntimeError("boom")

        def generate_batch(self, requests):
            raise RuntimeError("boom")

    sched = BatchScheduler(ExplodingBackend(), window_s=0.1)
    sched.start()
    try:
        reqs = [GenerationRequest("m", "x", max_new_tokens=4) for _ in range(3)]
        results, errors = _submit_concurrently(sched, reqs)
        assert results == [None] * 3
        assert all(isinstance(e, RuntimeError) for e in errors)
    finally:
        sched.stop()


def test_stop_unblocks_pending_submits(backend):
    sched = BatchScheduler(backend, window_s=0.05)
    # never started: submit must refuse rather than hang
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit(GenerationRequest("m", "x", max_new_tokens=4))
    sched.start()
    sched.stop()
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit(GenerationRequest("m", "x", max_new_tokens=4))


def test_server_batches_concurrent_http_requests(backend):
    srv = GenerationServer(
        backend,
        host="127.0.0.1",
        port=0,
        quiet=True,
        batch_window_ms=150.0,
        max_batch=8,
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        reqs = [
            GenerationRequest("m", f"p{i}", max_new_tokens=6, seed=i)
            for i in range(4)
        ]
        results = [None] * 4

        def call(i):
            results[i] = client.generate(reqs[i])

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        reference = FakeBackend()
        for req, res in zip(reqs, results):
            assert res is not None
            assert res.tokens == reference.generate(req).tokens
        assert max(backend.calls) >= 2  # coalescing really happened
    finally:
        srv.stop()


def test_server_without_batching_stays_serial(backend):
    srv = GenerationServer(
        backend, host="127.0.0.1", port=0, quiet=True
    )  # batch_window_ms=0
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        req = GenerationRequest("m", "solo", max_new_tokens=4)
        client.generate(req)
        assert backend.calls == [1]
    finally:
        srv.stop()


def test_batch_failure_retries_singles():
    """A batch-level failure must not fail callers whose requests are
    individually fine."""

    class BatchAllergicBackend(FakeBackend):
        def generate_batch(self, requests):
            raise ValueError("combined batch exceeds max_seq_len")

    sched = BatchScheduler(BatchAllergicBackend(), window_s=0.2)
    sched.start()
    try:
        reqs = [
            GenerationRequest("m", f"p{i}", max_new_tokens=4, seed=i)
            for i in range(3)
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 3  # every caller served via single retry
        reference = FakeBackend()
        for req, res in zip(reqs, results):
            assert res.tokens == reference.generate(req).tokens
    finally:
        sched.stop()


def test_stop_during_inflight_batch_fails_leftovers_after_worker_exit():
    """stop() must keep draining until the worker thread has really exited:
    a batch executing across the shutdown can re-queue incompatible
    leftovers after a premature drain, stranding their callers forever."""

    class SlowBackend(FakeBackend):
        def generate(self, request):
            time.sleep(1.0)
            return super().generate(request)

    sched = BatchScheduler(SlowBackend(), window_s=0.3)
    sched.start()
    try:
        # A opens a batch; B (different model) arrives inside A's admission
        # window and becomes a leftover, re-queued when the window closes.
        reqs = [
            GenerationRequest("m1", "a", max_new_tokens=4),
            GenerationRequest("m2", "b", max_new_tokens=4),
        ]
        results = [None, None]
        errors = [None, None]

        def worker(i):
            try:
                results[i] = sched.submit(reqs[i])
            except BaseException as exc:  # noqa: BLE001
                errors[i] = exc

        t_a = threading.Thread(target=worker, args=(0,))
        t_a.start()
        time.sleep(0.1)
        t_b = threading.Thread(target=worker, args=(1,))
        t_b.start()
        time.sleep(0.1)  # both enqueued; A's batch still collecting/executing
        sched.stop()  # must block until the worker exited, then drain
        t_a.join(timeout=10)
        t_b.join(timeout=10)
        assert not t_a.is_alive() and not t_b.is_alive()
        # A was in flight → served; B was dropped at shutdown → failed, but
        # NOT stranded.
        assert results[0] is not None and errors[0] is None
        assert results[1] is not None or isinstance(errors[1], RuntimeError)
    finally:
        sched.stop()


def test_max_batch_default_is_backend_aware():
    """32 for backends with a real batched decode; 8 for backends on the
    base class's sequential generate_batch loop, where wider admission
    only multiplies every caller's wait for the sweep."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
    )

    class Batched(GenerationBackend):
        def generate_batch(self, requests):  # real batched path
            raise NotImplementedError

    assert BatchScheduler(FakeBackend()).max_batch == 8  # sequential base
    assert BatchScheduler(Batched()).max_batch == 32
    assert BatchScheduler(FakeBackend(), max_batch=16).max_batch == 16
