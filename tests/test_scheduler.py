"""Continuous batching: the scheduler coalesces concurrent requests into
batched backend calls while preserving per-request results and ordering
guarantees. All hermetic (FakeBackend) — no accelerator, no network."""

import threading
import time

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
    RemoteHTTPBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
    BatchScheduler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


class RecordingBackend(FakeBackend):
    """FakeBackend that records every call's batch size."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.calls = []  # list of batch sizes (1 == single generate)

    def generate(self, request):
        self.calls.append(1)
        return super().generate(request)

    def generate_batch(self, requests):
        self.calls.append(len(requests))
        return [super(RecordingBackend, self).generate(r) for r in requests]


@pytest.fixture()
def backend():
    return RecordingBackend()


def _submit_concurrently(scheduler, requests):
    results = [None] * len(requests)
    errors = [None] * len(requests)

    def worker(i, req):
        try:
            results[i] = scheduler.submit(req)
        except BaseException as exc:  # noqa: BLE001
            errors[i] = exc

    threads = [
        threading.Thread(target=worker, args=(i, r))
        for i, r in enumerate(requests)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


def test_concurrent_compatible_requests_coalesce(backend):
    sched = BatchScheduler(backend, max_batch=8, window_s=0.2)
    sched.start()
    try:
        reqs = [
            GenerationRequest("m", f"prompt {i}", max_new_tokens=8, seed=i)
            for i in range(4)
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 4
        # each caller got its own request's result
        for req, res in zip(reqs, results):
            assert res.request == req
            assert res.tokens == backend.generate(req).tokens
        # at least one multi-row batch happened (timing-dependent how many)
        assert max(backend.calls) >= 2
    finally:
        sched.stop()


def test_incompatible_requests_split_into_separate_batches(backend):
    sched = BatchScheduler(backend, max_batch=8, window_s=0.15)
    sched.start()
    try:
        reqs = [
            GenerationRequest("model-a", "x", max_new_tokens=4),
            GenerationRequest("model-b", "y", max_new_tokens=4),
            GenerationRequest("model-a", "z", max_new_tokens=4, top_k=7),
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 3
        for req, res in zip(reqs, results):
            assert res.request == req
    finally:
        sched.stop()


def test_backend_error_fans_out_to_all_callers():
    class ExplodingBackend(FakeBackend):
        def generate(self, request):
            raise RuntimeError("boom")

        def generate_batch(self, requests):
            raise RuntimeError("boom")

    sched = BatchScheduler(ExplodingBackend(), window_s=0.1)
    sched.start()
    try:
        reqs = [GenerationRequest("m", "x", max_new_tokens=4) for _ in range(3)]
        results, errors = _submit_concurrently(sched, reqs)
        assert results == [None] * 3
        assert all(isinstance(e, RuntimeError) for e in errors)
    finally:
        sched.stop()


def test_stop_unblocks_pending_submits(backend):
    sched = BatchScheduler(backend, window_s=0.05)
    # never started: submit must refuse rather than hang
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit(GenerationRequest("m", "x", max_new_tokens=4))
    sched.start()
    sched.stop()
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit(GenerationRequest("m", "x", max_new_tokens=4))


def test_server_batches_concurrent_http_requests(backend):
    # scheduler="window" pinned: RecordingBackend overrides generate_batch
    # AND inherits the fake's stepped API, so auto would pick continuous
    # and never dispatch through generate_batch (the call log asserted on)
    srv = GenerationServer(
        backend,
        host="127.0.0.1",
        port=0,
        quiet=True,
        batch_window_ms=150.0,
        max_batch=8,
        scheduler="window",
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        reqs = [
            GenerationRequest("m", f"p{i}", max_new_tokens=6, seed=i)
            for i in range(4)
        ]
        results = [None] * 4

        def call(i):
            results[i] = client.generate(reqs[i])

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        reference = FakeBackend()
        for req, res in zip(reqs, results):
            assert res is not None
            assert res.tokens == reference.generate(req).tokens
        assert max(backend.calls) >= 2  # coalescing really happened
    finally:
        srv.stop()


def test_server_without_batching_stays_serial(backend):
    srv = GenerationServer(
        backend, host="127.0.0.1", port=0, quiet=True
    )  # batch_window_ms=0
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        req = GenerationRequest("m", "solo", max_new_tokens=4)
        client.generate(req)
        assert backend.calls == [1]
    finally:
        srv.stop()


def test_batch_failure_retries_singles():
    """A batch-level failure must not fail callers whose requests are
    individually fine."""

    class BatchAllergicBackend(FakeBackend):
        def generate_batch(self, requests):
            raise ValueError("combined batch exceeds max_seq_len")

    sched = BatchScheduler(BatchAllergicBackend(), window_s=0.2)
    sched.start()
    try:
        reqs = [
            GenerationRequest("m", f"p{i}", max_new_tokens=4, seed=i)
            for i in range(3)
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 3  # every caller served via single retry
        reference = FakeBackend()
        for req, res in zip(reqs, results):
            assert res.tokens == reference.generate(req).tokens
    finally:
        sched.stop()


def test_stop_during_inflight_batch_fails_leftovers_after_worker_exit():
    """stop() must keep draining until the worker thread has really exited:
    a batch executing across the shutdown can re-queue incompatible
    leftovers after a premature drain, stranding their callers forever."""

    class SlowBackend(FakeBackend):
        def generate(self, request):
            time.sleep(1.0)
            return super().generate(request)

    sched = BatchScheduler(SlowBackend(), window_s=0.3)
    sched.start()
    try:
        # A opens a batch; B (different model) arrives inside A's admission
        # window and becomes a leftover, re-queued when the window closes.
        reqs = [
            GenerationRequest("m1", "a", max_new_tokens=4),
            GenerationRequest("m2", "b", max_new_tokens=4),
        ]
        results = [None, None]
        errors = [None, None]

        def worker(i):
            try:
                results[i] = sched.submit(reqs[i])
            except BaseException as exc:  # noqa: BLE001
                errors[i] = exc

        t_a = threading.Thread(target=worker, args=(0,))
        t_a.start()
        time.sleep(0.1)
        t_b = threading.Thread(target=worker, args=(1,))
        t_b.start()
        time.sleep(0.1)  # both enqueued; A's batch still collecting/executing
        sched.stop()  # must block until the worker exited, then drain
        t_a.join(timeout=10)
        t_b.join(timeout=10)
        assert not t_a.is_alive() and not t_b.is_alive()
        # A was in flight → served; B was dropped at shutdown → failed, but
        # NOT stranded.
        assert results[0] is not None and errors[0] is None
        assert results[1] is not None or isinstance(errors[1], RuntimeError)
    finally:
        sched.stop()


def test_batch_failure_fallback_isolates_by_bisection():
    """One pathological request must not serialise its companions behind
    a one-by-one retry sweep: the fallback bisects, so good tickets are
    re-served in BATCHES and only the poisoned one runs (and fails)
    alone — recorded on llm_sched_batch_fallback_total."""

    class OnePoisonBackend(FakeBackend):
        def __init__(self):
            super().__init__()
            self.batch_calls = []

        def generate(self, request):
            if request.prompt == "poison":
                raise ValueError("bad row")
            return super().generate(request)

        def generate_batch(self, requests):
            self.batch_calls.append(len(requests))
            if any(r.prompt == "poison" for r in requests):
                raise ValueError("bad row in batch")
            return [super(OnePoisonBackend, self).generate(r) for r in requests]

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )

    backend = OnePoisonBackend()
    sched = BatchScheduler(backend, max_batch=8, window_s=0.2)
    sched.start()
    try:
        before = (
            REGISTRY.counter("llm_sched_batch_fallback_total")
            .labels()
            .value
        )
        reqs = [
            GenerationRequest("m", p, max_new_tokens=4, seed=i)
            for i, p in enumerate(["a", "b", "poison", "c"])
        ]
        results, errors = _submit_concurrently(sched, reqs)
        # the three good callers are served; only the poisoned one errors
        for req, res, err in zip(reqs, results, errors):
            if req.prompt == "poison":
                assert isinstance(err, ValueError)
            else:
                assert err is None
                assert res.tokens == FakeBackend().generate(req).tokens
        # bisection really re-batched the survivors: at least one
        # multi-row batch call succeeded after the poisoned dispatch
        assert any(
            n > 1 for n in backend.batch_calls[1:]
        ), backend.batch_calls
        after = (
            REGISTRY.counter("llm_sched_batch_fallback_total")
            .labels()
            .value
        )
        assert after > before
    finally:
        sched.stop()


# -- continuous (iteration-level) scheduling ----------------------------------


def test_continuous_scheduler_serves_and_matches_fake():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    sched = ContinuousScheduler(FakeBackend(), slice_steps=8)
    sched.start()
    try:
        reqs = [
            GenerationRequest("m", f"prompt {i}", max_new_tokens=8 + i, seed=i)
            for i in range(4)
        ]
        results, errors = _submit_concurrently(sched, reqs)
        assert errors == [None] * 4
        reference = FakeBackend()
        for req, res in zip(reqs, results):
            assert res.tokens == reference.generate(req).tokens
            sched_extras = res.extras["sched"]
            assert sched_extras["ttft_s"] <= sched_extras["completion_s"]
    finally:
        sched.stop()


def test_continuous_scheduler_rejects_backend_without_stepped_api():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    with pytest.raises(ValueError, match="decode_open"):
        ContinuousScheduler(GenerationBackend())


def test_continuous_join_completes_before_long_anchor():
    """A short request arriving mid-decode joins the running session and
    its caller unblocks BEFORE the anchor's long decode drains — the
    latency property window dispatch cannot provide."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=200.0, simulate_delay=True), slice_steps=8
    )
    sched.start()
    try:
        long_req = GenerationRequest("m", "long", max_new_tokens=64)
        short_req = GenerationRequest("m", "short", max_new_tokens=8)
        done_order = []

        def go(name, req):
            sched.submit(req)
            done_order.append(name)

        t_long = threading.Thread(target=go, args=("long", long_req))
        t_long.start()
        time.sleep(0.08)  # the anchor session is mid-decode now
        t_short = threading.Thread(target=go, args=("short", short_req))
        t_short.start()
        t_short.join(timeout=15)
        t_long.join(timeout=15)
        assert done_order[0] == "short", done_order
    finally:
        sched.stop()


def test_continuous_shutdown_unblocks_queued_and_inflight():
    """Scheduler shutdown while a continuous decode is IN FLIGHT: queued
    and mid-flight tickets must all unblock with results or "server
    shutting down" errors, never strand on event.wait() (the stepped-loop
    extension of the PR-1 stop()/drain guarantees)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=50.0, simulate_delay=True), slice_steps=8
    )
    sched.start()
    outcomes = {}

    def worker(i, req):
        try:
            outcomes[i] = ("ok", sched.submit(req))
        except BaseException as exc:  # noqa: BLE001
            outcomes[i] = ("err", exc)

    # row 0 anchors a ~4 s decode; 1 joins it; 2 queues behind an
    # incompatible model so it is waiting un-dispatched at shutdown
    reqs = [
        GenerationRequest("m", "anchor", max_new_tokens=200),
        GenerationRequest("m", "joiner", max_new_tokens=200),
        GenerationRequest("other", "queued", max_new_tokens=200),
    ]
    threads = []
    for i, req in enumerate(reqs):
        t = threading.Thread(target=worker, args=(i, req))
        t.start()
        threads.append(t)
        time.sleep(0.08)
    time.sleep(0.2)  # decode well in flight
    sched.stop()
    for t in threads:
        t.join(timeout=15)
    assert all(not t.is_alive() for t in threads), "caller stranded"
    assert set(outcomes) == {0, 1, 2}
    for status, payload in outcomes.values():
        if status == "err":
            assert isinstance(payload, RuntimeError)
            assert "shutting down" in str(payload)
    # after stop, submits are refused rather than stranded
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit(GenerationRequest("m", "late", max_new_tokens=4))


def test_server_auto_scheduler_selection():
    """Auto mode: continuous for real batched backends speaking the
    stepped protocol (the JAX engines), window otherwise (fake)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    srv = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True,
        batch_window_ms=20,
    )
    assert srv.scheduler_mode == "window"
    srv.stop()

    class SteppedBatched(FakeBackend):
        def generate_batch(self, requests):  # a real batched path
            return [self.generate(r) for r in requests]

    srv2 = GenerationServer(
        SteppedBatched(), host="127.0.0.1", port=0, quiet=True,
        batch_window_ms=20,
    )
    assert srv2.scheduler_mode == "continuous"
    assert isinstance(srv2._scheduler, ContinuousScheduler)
    srv2.stop()

    # explicit override wins over auto
    srv3 = GenerationServer(
        SteppedBatched(), host="127.0.0.1", port=0, quiet=True,
        scheduler="window",
    )
    assert srv3.scheduler_mode == "window"
    srv3.stop()

    with pytest.raises(ValueError, match="scheduler"):
        GenerationServer(
            FakeBackend(), host="127.0.0.1", port=0, quiet=True,
            scheduler="bogus",
        )


def test_continuous_scheduler_with_jax_engine_matches_solo():
    """Scheduler-level token parity on the real engine: staggered
    concurrent submits through the continuous scheduler (anchors AND
    mid-flight joins) are bit-identical to solo generate()."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    reqs = [
        GenerationRequest(
            "tiny", "anchor row runs longest", max_new_tokens=40,
            stop_at_eos=False,
        ),
        GenerationRequest("tiny", "second row", max_new_tokens=8, seed=2),
        GenerationRequest(
            "tiny", "third arrives later", max_new_tokens=12, seed=3,
            temperature=0.8,
        ),
    ]
    solo = [engine.generate(r) for r in reqs]
    sched = ContinuousScheduler(engine, slice_steps=4)
    sched.start()
    try:
        results = [None] * len(reqs)
        errors = [None] * len(reqs)

        def go(i):
            try:
                results[i] = sched.submit(reqs[i])
            except BaseException as exc:  # noqa: BLE001
                errors[i] = exc

        threads = []
        for i in range(len(reqs)):
            t = threading.Thread(target=go, args=(i,))
            t.start()
            threads.append(t)
            time.sleep(0.05)  # staggered: later rows join mid-flight
        for t in threads:
            t.join(timeout=60)
        assert errors == [None] * len(reqs)
        for want, got in zip(solo, results):
            assert got.tokens == want.tokens
    finally:
        sched.stop()


def test_server_plumbs_slice_and_chunk_knobs():
    """GenerationServer hands --decode-slice-steps / --prefill-chunk-
    tokens through to the continuous scheduler (and the engine default
    applies when unset)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        DECODE_SLICE_STEPS,
    )

    srv = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True,
        scheduler="continuous", slice_steps=5, prefill_chunk_tokens=64,
    )
    assert srv._scheduler.slice_steps == 5
    assert srv._scheduler.prefill_chunk_tokens == 64
    srv.stop()

    srv2 = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True,
        scheduler="continuous",
    )
    assert srv2._scheduler.slice_steps == DECODE_SLICE_STEPS
    assert srv2._scheduler.prefill_chunk_tokens is None  # backend auto
    srv2.stop()


def test_continuous_chunked_join_progresses_round_robin():
    """A long-prompt joiner is admitted in MULTIPLE token-budgeted
    prefill chunks interleaved with the anchor's decode slices — its
    result carries the chunk count, and both callers complete."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=400.0, simulate_delay=True),
        slice_steps=8,
        prefill_chunk_tokens=32,
    )
    assert sched.chunked_joins
    sched.start()
    try:
        results = {}

        def go(name, req):
            results[name] = sched.submit(req)

        anchor = GenerationRequest("m", "anchor", max_new_tokens=96)
        joiner = GenerationRequest("m", "J" * 200, max_new_tokens=8)
        t_a = threading.Thread(target=go, args=("anchor", anchor))
        t_a.start()
        time.sleep(0.05)  # the anchor session is mid-decode
        t_j = threading.Thread(target=go, args=("joiner", joiner))
        t_j.start()
        t_a.join(timeout=15)
        t_j.join(timeout=15)
        assert set(results) == {"anchor", "joiner"}
        sched_extras = results["joiner"].extras["sched"]
        assert sched_extras["joined"] is True
        # 201 prompt tokens at a 32-token budget: several chunks, each
        # run between decode slices
        assert sched_extras["join_chunks"] >= 3
        assert "joined" not in results["anchor"].extras["sched"]
    finally:
        sched.stop()


def test_continuous_sync_join_mode_still_available():
    """chunked_joins=False restores the one-shot join (the ISSUE-3
    baseline the chunked_join bench A/Bs against): joins still work,
    with no chunk accounting."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=400.0, simulate_delay=True),
        slice_steps=8,
        chunked_joins=False,
    )
    sched.start()
    try:
        results = {}

        def go(name, req):
            results[name] = sched.submit(req)

        t_a = threading.Thread(
            target=go,
            args=("anchor", GenerationRequest("m", "a", max_new_tokens=64)),
        )
        t_a.start()
        time.sleep(0.05)
        t_j = threading.Thread(
            target=go,
            args=("joiner", GenerationRequest("m", "J" * 200, max_new_tokens=8)),
        )
        t_j.start()
        t_a.join(timeout=15)
        t_j.join(timeout=15)
        sched_extras = results["joiner"].extras["sched"]
        assert sched_extras["joined"] is True
        assert sched_extras["join_chunks"] == 0  # one-shot, no chunks
    finally:
        sched.stop()


def test_continuous_chunked_join_with_jax_engine_matches_solo():
    """End-to-end chunked-join parity on the REAL engine through the
    scheduler: a long-prompt joiner whose prefill streams in across
    slices, and the anchor decoding through it, both match solo."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    engine = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    anchor = GenerationRequest(
        "tiny", "a" * 120, max_new_tokens=48, stop_at_eos=False, seed=1
    )
    joiner = GenerationRequest("tiny", "j" * 100, max_new_tokens=8, seed=3)
    solo = {id(r): engine.generate(r).tokens for r in (anchor, joiner)}
    sched = ContinuousScheduler(
        engine, slice_steps=4, prefill_chunk_tokens=32
    )
    sched.start()
    try:
        results = {}

        def go(req):
            results[id(req)] = sched.submit(req)

        t_a = threading.Thread(target=go, args=(anchor,))
        t_a.start()
        time.sleep(0.2)  # anchor mid-decode (tiny CPU steps are ~ms)
        t_j = threading.Thread(target=go, args=(joiner,))
        t_j.start()
        t_a.join(timeout=60)
        t_j.join(timeout=60)
        assert results[id(anchor)].tokens == solo[id(anchor)]
        assert results[id(joiner)].tokens == solo[id(joiner)]
        j_extras = results[id(joiner)].extras["sched"]
        if j_extras.get("joined"):  # arrival raced the anchor's drain
            assert j_extras["join_chunks"] >= 3
    finally:
        sched.stop()


def test_window_ttft_fallback_excludes_queue_wait():
    """The window-path TTFT estimate no longer folds queue wait in
    (ISSUE-4 satellite): a request queued behind another model's long
    batch reports a TTFT near its own prefill, not its queue wait —
    comparable with the continuous histogram; the wait itself stays on
    llm_sched_queue_wait_seconds."""
    sched = BatchScheduler(
        FakeBackend(tokens_per_s=100.0, simulate_delay=True), window_s=0.02
    )
    sched.start()
    try:
        results = {}

        def go(name, req):
            results[name] = sched.submit(req)

        # ~0.64 s batch the second request must queue behind (different
        # model → its own later batch)
        t_a = threading.Thread(
            target=go,
            args=("first", GenerationRequest("m1", "x", max_new_tokens=64)),
        )
        t_a.start()
        time.sleep(0.05)
        t_b = threading.Thread(
            target=go,
            args=("second", GenerationRequest("m2", "y", max_new_tokens=8)),
        )
        t_b.start()
        t_a.join(timeout=15)
        t_b.join(timeout=15)
        sched_extras = results["second"].extras["sched"]
        # completion includes ~0.6 s of queue wait; the TTFT estimate
        # must not
        assert sched_extras["completion_s"] > 0.4
        assert sched_extras["ttft_s"] < 0.3
    finally:
        sched.stop()


def test_max_batch_default_is_backend_aware():
    """32 for backends with a real batched decode; 8 for backends on the
    base class's sequential generate_batch loop, where wider admission
    only multiplies every caller's wait for the sweep."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
    )

    class Batched(GenerationBackend):
        def generate_batch(self, requests):  # real batched path
            raise NotImplementedError

    assert BatchScheduler(FakeBackend()).max_batch == 8  # sequential base
    assert BatchScheduler(Batched()).max_batch == 32
    assert BatchScheduler(FakeBackend(), max_batch=16).max_batch == 16


def test_fake_backend_speaks_spec_protocol_with_fallback():
    """ISSUE 9 hermetic twin: FakeBackend(spec_k>0) sessions run the
    synthetic draft-verify protocol — rows advance by 1 + accepted per
    round, llm_spec_* move, per-row spec fields surface in debug_state —
    and a measured acceptance below the scheduler's floor flips the
    session to plain advancement (llm_spec_fallback_total)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    def counter(name):
        # llm_spec_* families carry a {source} label (ISSUE 16): sum
        # every child so the pre-label arithmetic still pins exactly.
        return sum(REGISTRY.snapshot().get(name, {}).values())

    fb = FakeBackend(spec_k=4, spec_acceptance=0.75)
    sess = fb.decode_open(
        [GenerationRequest("m", "probe", max_new_tokens=32)]
    )
    rounds0 = counter("llm_spec_rounds_total")
    sess.step(4)  # 4 rounds × (1 + 3 accepted) = 16 tokens
    state = sess.debug_state()
    assert state["spec"]["active"] and state["spec"]["k"] == 4
    assert state["rows"][0]["spec_rounds"] == 4
    assert state["rows"][0]["spec_accepted"] == 12
    assert counter("llm_spec_rounds_total") == rounds0 + 4
    retired = sess.step(4)  # 32 tokens total: row retires
    assert retired and retired[0].extras["spec"]["accepted"] == 24
    sess.close()

    # scheduler floor → decode_open override → fallback at acceptance 0
    fallbacks0 = counter("llm_spec_fallback_total")
    sched = ContinuousScheduler(
        FakeBackend(spec_k=4, spec_acceptance=0.0), spec_accept_floor=0.25
    )
    sched.start()
    try:
        res = sched.submit(GenerationRequest("m", "zero", max_new_tokens=64))
    finally:
        sched.stop()
    assert res.extras["spec"]["fallback"] is True
    assert counter("llm_spec_fallback_total") == fallbacks0 + 1


def test_fake_spec_adaptive_k_shrinks_then_restores():
    """ISSUE 19 adaptive draft-k (hermetic twin): a below-floor slice
    HALVES the session's live k instead of abandoning speculation —
    the per-round advance (the acceptance step) follows the live k —
    and a recovered acceptance restores k toward the configured
    length. llm_spec_k_adapt_total{direction} moves both ways and the
    session never falls back."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )

    def adapt(direction):
        return (
            REGISTRY.snapshot()
            .get("llm_spec_k_adapt_total", {})
            .get(f"source=model,direction={direction}", 0)
        )

    fb = FakeBackend(
        spec_k=4, spec_acceptance=0.75, spec_accept_floor=0.25
    )
    sess = fb.decode_open(
        [GenerationRequest("m", "adaptive", max_new_tokens=512)]
    )
    down0, up0 = adapt("down"), adapt("up")
    sess.step(4)  # healthy window: k stays at the configured 4
    assert sess.spec_k == 4 and sess.spec_active
    row = sess.debug_state()["rows"][0]
    # acceptance 0.75 at k=4: each round advances 1 + 3 accepted
    assert row["generated_tokens"] == 16

    fb.spec_acceptance = 0.0  # rough patch: every draft rejected
    before = sess.debug_state()["rows"][0]["generated_tokens"]
    sess.step(4)
    assert sess.spec_k == 2 and sess.spec_active  # shrink, no fallback
    assert adapt("down") == down0 + 1
    # the rough-patch acceptance step: all rejected → each round
    # advanced exactly the target's own 1 token (k=4 during the slice;
    # the shrink lands at its end)
    assert sess.debug_state()["rows"][0]["generated_tokens"] == before + 4
    sess.step(4)
    assert sess.spec_k == 1 and sess.spec_active
    assert adapt("down") == down0 + 2

    fb.spec_acceptance = 0.75  # recovery: restore toward k0
    sess.step(4)
    assert sess.spec_k == 2 and adapt("up") == up0 + 1
    sess.step(4)
    assert sess.spec_k == 4 and adapt("up") == up0 + 2
    assert sess.spec_active and not sess.spec_fallback
    sess.close()
