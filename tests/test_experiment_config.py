"""The LLM-energy study config, run hermetically on the fake backend."""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
    LlmEnergyConfig,
    MODELS,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.topics import (
    TOPICS,
    pick_topic,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
    TpuEnergyModelProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import RunContext
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.controller import (
    ExperimentController,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import (
    RunTableStore,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress


def test_topics_pool_and_seeded_pick():
    assert len(TOPICS) >= 100
    assert len(set(TOPICS)) == len(TOPICS)
    assert pick_topic(seed=42) == pick_topic(seed=42)
    assert any(pick_topic(seed=i) != pick_topic(seed=0) for i in range(1, 10))


def test_default_sweep_shape():
    config = LlmEnergyConfig()
    model = config.create_run_table_model()
    # 7 models × 2 locations × 3 lengths (experiment/RunnerConfig.py:80-88)
    assert len(model.variations()) == 7 * 2 * 3
    assert len(MODELS) == 7
    # Cooldown is channel-typed: the reference's 90 s thermal discipline
    # (RunnerConfig.py:55) when any measured energy channel is active,
    # 2 s when every energy column is modelled (thermal-state-free).
    expect = (
        LlmEnergyConfig.MEASURED_CHANNEL_COOLDOWN_MS
        if any(getattr(p, "measured_channel", False) for p in config.profilers)
        else LlmEnergyConfig.MODELLED_ONLY_COOLDOWN_MS
    )
    assert config.time_between_runs_in_ms == expect


def test_cooldown_policy_follows_channel_type(monkeypatch):
    """Explicit cooldown always wins; otherwise a measured channel re-grows
    the reference's 90 s thermal discipline (VERDICT round-2 item 9)."""
    config = LlmEnergyConfig(cooldown_ms=1234)
    assert config.time_between_runs_in_ms == 1234

    # A measured channel present at construction → the reference's 90 s.
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import (
        native_host,
    )

    monkeypatch.setattr(
        native_host.NativeHostProfiler,
        "measured_channel",
        property(lambda self: True),
    )
    config = LlmEnergyConfig()
    assert (
        config.time_between_runs_in_ms
        == LlmEnergyConfig.MEASURED_CHANNEL_COOLDOWN_MS
    )


def test_energy_model_profiler_math(tmp_path):
    prof = TpuEnergyModelProfiler(
        peak_tflops=100.0, peak_w=200.0, idle_w=50.0, mxu_active_w=150.0
    )
    ctx = RunContext("r", 1, 1, {}, tmp_path, tmp_path)
    ctx.scratch["generation_stats"] = {
        "flops": 50.0e12,  # half of peak over 1 s → util 0.5
        "duration_s": 1.0,
        "generated_tokens": 100,
    }
    prof.on_start(ctx)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    # 50 W idle + 0.5 MXU duty × 150 W engine coefficient = 125 J over 1 s
    assert data["energy_model_J"] == pytest.approx(125.0)
    assert data["joules_per_token"] == pytest.approx(1.25)
    assert data["tpu_util_est"] == 0.5
    assert data["tpu_power_model_W"] == pytest.approx(125.0)


def test_energy_model_profiler_without_stats(tmp_path):
    prof = TpuEnergyModelProfiler()
    ctx = RunContext("r", 1, 1, {}, tmp_path, tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx)["energy_model_J"] is None


def test_energy_window_excludes_transport_time(tmp_path):
    """Modelled energy's idle-power window is the fence-timed DECODE loop
    (the serving side's own clock), not the request wall time — HTTP and
    tunnel-dispatch jitter (both ``total_s`` and the dispatch-dominated
    ``prefill_s`` of short prompts) must not leak into Joules; prefill is
    charged through the FLOPs term (VERDICT round-2 item 1)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationResult,
    )

    class TransportyBackend(FakeBackend):
        def generate(self, request):
            r = super().generate(request)
            return GenerationResult(
                request=r.request,
                tokens=r.tokens,
                text=r.text,
                prompt_tokens=r.prompt_tokens,
                generated_tokens=r.generated_tokens,
                prefill_s=0.01,
                decode_s=0.5,
                total_s=3.0,  # ~2.5 s of wire/transport time
            )

    be = TransportyBackend()
    config = LlmEnergyConfig(
        models=["qwen2:1.5b"],
        locations=["on_device"],
        lengths=[100],
        repetitions=1,
        cooldown_ms=0,
        backends={"on_device": be},
        results_output_path=tmp_path,
    )
    ctx = RunContext(
        "run_0_repetition_0",
        1,
        1,
        {"model": "qwen2:1.5b", "location": "on_device", "length": 100},
        tmp_path,
        tmp_path,
    )
    config.start_run(ctx)
    config.interact(ctx)
    stats = ctx.scratch["generation_stats"]
    assert stats["duration_s"] == pytest.approx(0.5)  # decode_s only
    # flops cover ALL processed tokens — prefill's compute is charged
    # through the FLOPs term, not a dispatch-dominated wall window
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        MODEL_REGISTRY,
    )

    cfg = MODEL_REGISTRY["qwen2:1.5b"]
    r = ctx.scratch["result"]
    total = r.prompt_tokens + r.generated_tokens
    assert stats["flops"] == pytest.approx(cfg.flops_per_token(total) * total)
    # and execution_time_s (the reference's client-observed wall time)
    # still records the full request duration
    data = config.populate_run_data(ctx)
    assert data["execution_time_s"] == pytest.approx(3.0)


def test_recompute_energy_reproduces_modelled_columns(tmp_path):
    """Modelled energy is a pure function of persisted raw measurements:
    recomputing an existing table under the current model reproduces the
    live-run values exactly (and lets a model refinement be applied
    post-hoc, like the reference's derived J column)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    config = _hermetic_config(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    exp = tmp_path / "llm_energy_tpu"
    before = {
        r["__run_id"]: r["energy_model_J"] for r in RunTableStore(exp).read()
    }
    assert any(v is not None for v in before.values())
    n = recompute_energy(exp, reanalyze=False)
    after = {
        r["__run_id"]: r["energy_model_J"] for r in RunTableStore(exp).read()
    }
    assert n == len(before)
    for rid, v in before.items():
        assert after[rid] == pytest.approx(v, rel=1e-6), rid


def _hermetic_config(tmp_path, **kw):
    # simulate_delay gives each run a real ~30 ms measurement window so the
    # sampling profilers observe a nonzero span.
    fake = FakeBackend(tokens_per_s=5000.0, simulate_delay=True)
    return LlmEnergyConfig(
        models=["qwen2:1.5b", "gemma:2b"],
        locations=["on_device", "remote"],
        lengths=[100],
        repetitions=2,
        results_output_path=tmp_path,
        cooldown_ms=0,
        backends={"on_device": fake, "remote": fake},
        shuffle=True,
        **kw,
    )


def test_full_study_lifecycle_on_fake_backend(tmp_path):
    config = _hermetic_config(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    rows = RunTableStore(tmp_path / "llm_energy_tpu").read()
    assert len(rows) == 2 * 2 * 1 * 2
    assert all(r["__done"] == RunProgress.DONE for r in rows)
    for row in rows:
        assert row["topic"] in TOPICS
        assert row["generated_tokens"] == 134  # ceil(100 * 4/3)
        assert row["execution_time_s"] > 0
        assert row["tokens_per_s"] > 0
        assert row["cpu_usage"] is not None  # host profiler columns present
    # analysis report written by after_experiment
    assert (tmp_path / "llm_energy_tpu" / "analysis_report.json").exists()


def test_study_resume_reuses_topic(tmp_path):
    config = _hermetic_config(tmp_path)
    ctrl = ExperimentController(config, echo=False)
    first_id = ctrl.rows[0]["__run_id"]
    ctrl.do_experiment()
    stored = {r["__run_id"]: r["topic"] for r in ctrl.store.read()}
    # same run id → same seeded topic on a fresh config instance
    import zlib

    config2 = _hermetic_config(tmp_path)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.topics import (
        pick_topic as pick,
    )

    topic2 = pick(seed=zlib.crc32(f"{config2.seed}|{first_id}".encode()))
    assert stored[first_id] == topic2


def test_remote_runs_do_not_poison_on_device_chip_count(tmp_path):
    """Regression (found in a real TPU capstone run): the shared energy
    profiler's n_chips is mutated per run; when the target count was read
    back from an aliased profiler instance, one remote run (8 chips)
    permanently poisoned every later on_device run. before_run must set
    the count from plain config data."""
    cfg = LlmEnergyConfig(
        models=["m"],
        lengths=[100],
        repetitions=1,
        cooldown_ms=0,
        results_output_path=tmp_path,
        backends={"on_device": FakeBackend(), "remote": FakeBackend()},
    )

    def ctx(location):
        return RunContext(
            run_id="r",
            run_nr=1,
            total_runs=2,
            variation={"model": "m", "location": location, "length": 100},
            run_dir=tmp_path,
            experiment_dir=tmp_path,
        )

    idx = cfg._model_profiler_index()
    cfg.before_run(ctx("remote"))
    assert cfg.profilers[idx].n_chips == 8
    cfg.before_run(ctx("on_device"))
    assert cfg.profilers[idx].n_chips == 1  # failed when read from the alias
    cfg.before_run(ctx("remote"))
    assert cfg.profilers[idx].n_chips == 8


def test_backend_column_recorded_per_run(tmp_path):
    config = _hermetic_config(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    rows = RunTableStore(tmp_path / "llm_energy_tpu").read()
    assert all(row["backend"] for row in rows)
    # both treatments are served by the same FakeBackend object → remote
    # rows must be flagged as aliased so nobody mistakes them for a real
    # machine boundary
    for row in rows:
        if row["location"] == "remote":
            assert "aliased-on_device" in row["backend"]
        else:
            assert "aliased" not in row["backend"]


def test_describe_backend_for_http_and_engine():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
        RemoteHTTPBackend,
    )

    cfg = LlmEnergyConfig(
        models=["m"],
        lengths=[100],
        repetitions=1,
        backends={
            "on_device": FakeBackend(),
            "remote": RemoteHTTPBackend("http://10.0.0.5:11434"),
        },
    )
    assert cfg.describe_backend("on_device") == "FakeBackend[1chip]"
    assert cfg.describe_backend("remote") == "http:http://10.0.0.5:11434"


def test_energy_channels_report_written(tmp_path):
    config = _hermetic_config(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    import json

    path = tmp_path / "llm_energy_tpu" / "energy_channels.json"
    assert path.exists()
    payload = json.loads(path.read_text())
    assert {c["name"] for c in payload["channels"]} >= {"rapl", "hwmon"}


def test_on_device_url_builds_http_backend_and_checks_health(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend as FB,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
        GenerationServer,
    )

    srv = GenerationServer(FB(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        cfg = LlmEnergyConfig(
            models=["m"],
            lengths=[100],
            repetitions=1,
            results_output_path=tmp_path,
            on_device_url=url,
            remote_url=url,
        )
        cfg.experiment_path = tmp_path / "exp"
        cfg.before_experiment()
        assert cfg.describe_backend("on_device") == f"http:{url}"
        # same URL for both treatments → one serving process, one chip:
        # the remote rows are aliased and must say so (the round-3
        # capstone recorded identical URLs unmarked, hiding that its
        # remote timings were single-chip; VERDICT round-3 missing #3)
        assert (
            cfg.describe_backend("remote")
            == f"http:{url}[aliased-on_device]"
        )

        # a genuinely distinct remote server keeps its own identity
        srv2 = GenerationServer(FB(), host="127.0.0.1", port=0, quiet=True)
        srv2.start()
        try:
            url2 = f"http://127.0.0.1:{srv2.port}"
            cfg2 = LlmEnergyConfig(
                models=["m"],
                lengths=[100],
                repetitions=1,
                results_output_path=tmp_path,
                on_device_url=url,
                remote_url=url2,
            )
            cfg2.experiment_path = tmp_path / "exp2"
            cfg2.before_experiment()
            assert cfg2.describe_backend("remote") == f"http:{url2}"
        finally:
            srv2.stop()
    finally:
        srv.stop()


def test_on_device_url_unreachable_fails_fast(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import (
        ExperimentError,
    )

    cfg = LlmEnergyConfig(
        models=["m"],
        lengths=[100],
        repetitions=1,
        results_output_path=tmp_path,
        on_device_url="http://127.0.0.1:9",  # discard port: nothing listens
    )
    cfg.experiment_path = tmp_path / "exp"
    with pytest.raises(ExperimentError, match="unreachable"):
        cfg.before_experiment()


def test_aliased_remote_rows_get_modeled_mesh_duration(tmp_path):
    """Single-chip hosts serve the remote treatment from the aliased
    on-device backend; billing the 8-chip mesh for the single chip's wall
    time made remote '8× power for identical time' — the opposite of the
    reference's remote-is-faster finding (VERDICT round-3 missing #3).
    Aliased remote rows must carry the TP-roofline modelled window in
    ``remote_modeled_decode_s``, bill energy on it, and keep the raw
    measured ``decode_s`` untouched."""
    config = _hermetic_config(tmp_path)
    ExperimentController(config, echo=False).do_experiment()
    rows = RunTableStore(tmp_path / "llm_energy_tpu").read()
    on_device = [r for r in rows if r["location"] == "on_device"]
    remote = [r for r in rows if r["location"] == "remote"]
    assert all(r["remote_modeled_decode_s"] is None for r in on_device)
    assert all(r["quantize"] == "int8" for r in rows)
    for r in remote:
        assert "[aliased-on_device]" in r["backend"]
        assert r["remote_modeled_decode_s"] is not None
        # the mesh window is modelled, not the measured single-chip time
        assert r["remote_modeled_decode_s"] != r["decode_s"]
        # energy was billed on the modelled window: 8 chips × the
        # modelled duration bounds it from above at peak power
        from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
            V5E_IDLE_W,
            V5E_PEAK_W,
        )

        lo = 8 * V5E_IDLE_W * r["remote_modeled_decode_s"]
        hi = 8 * V5E_PEAK_W * r["remote_modeled_decode_s"]
        assert lo * 0.99 <= r["energy_model_J"] <= hi * 1.01


def test_recompute_energy_fallback_aliasing_for_legacy_tables(tmp_path):
    """Tables from before the backend/quantize columns: a remote row with
    chips>1 could only have come from an aliased single-chip run, so
    recompute applies the mesh-duration model to it (and int8, the study
    default, for bytes)."""
    import csv

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    exp = tmp_path / "legacy"
    exp.mkdir()
    cols = [
        "__run_id", "__done", "model", "location", "length", "chips",
        "prompt_tokens", "generated_tokens", "execution_time_s",
        "prefill_s", "decode_s", "tokens_per_s", "energy_model_J",
        "joules_per_token", "tpu_util_est",
    ]
    with (exp / "run_table.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for i, (loc, chips) in enumerate(
            [("on_device", 1), ("remote", 8)] * 2
        ):
            w.writerow({
                "__run_id": f"run_{i}_repetition_0", "__done": "DONE",
                "model": "qwen2:1.5b", "location": loc, "length": 100,
                "chips": chips, "prompt_tokens": 64,
                "generated_tokens": 134, "execution_time_s": 0.6,
                "prefill_s": 0.1, "decode_s": 0.45, "tokens_per_s": 297.8,
                "energy_model_J": "", "joules_per_token": "",
                "tpu_util_est": "",
            })
    n = recompute_energy(exp, reanalyze=False)
    assert n == 4
    rows = RunTableStore(exp).read()
    by_loc = {}
    for r in rows:
        by_loc.setdefault(r["location"], []).append(r)
    for r in by_loc["on_device"]:
        assert r["remote_modeled_decode_s"] is None
        # bandwidth duty, not FLOPs duty: util is a real working fraction
        assert r["tpu_util_est"] > 0.3
    for r in by_loc["remote"]:
        assert r["remote_modeled_decode_s"] is not None
        assert r["remote_modeled_decode_s"] < r["decode_s"]  # mesh is faster


def test_generation_stats_bill_replicated_kv_per_chip():
    """sharding.py replicates the KV cache when n_kv_heads % tp != 0:
    every mesh chip then streams the FULL cache, so the mesh's total
    bytes are W + n·KV, not W + KV (code-review round-4 finding). phi3's
    32 heads shard cleanly → no multiplier."""
    import types

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        generation_stats_from,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        decode_kv_stream_bytes,
        decode_weight_stream_bytes,
    )

    result = types.SimpleNamespace(
        prompt_tokens=64, generated_tokens=200, decode_s=0.6, total_s=0.7
    )
    mid = 64 + 100
    qwen = get_model_config("qwen2:1.5b")  # 2 KV heads: 2 % 8 != 0
    s1 = generation_stats_from(qwen, result, quantize="int8", n_chips=1)
    s8 = generation_stats_from(
        qwen, result, quantize="int8", n_chips=8, aliased=True
    )
    kv = decode_kv_stream_bytes(qwen, mid) * 200
    w = decode_weight_stream_bytes(qwen, "int8") * 200
    assert s1["bytes"] == pytest.approx(w + kv)
    assert s8["bytes"] == pytest.approx(w + 8 * kv)

    phi3 = get_model_config("phi3:3.8b")  # 32 % 8 == 0 → sharded
    p8 = generation_stats_from(
        phi3, result, quantize="int8", n_chips=8, aliased=True
    )
    assert p8["bytes"] == pytest.approx(
        (decode_weight_stream_bytes(phi3, "int8")
         + decode_kv_stream_bytes(phi3, mid)) * 200
    )


def test_generation_stats_unknown_model_warns_on_aliased_mesh(capsys):
    """A model missing from the registry cannot be mesh-modelled: the
    aliased remote row keeps the measured window and the study says so
    out loud instead of silently reverting to idle-billing (code-review
    round-4 finding)."""
    import types

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        generation_stats_from,
    )

    result = types.SimpleNamespace(
        prompt_tokens=10, generated_tokens=20, decode_s=0.1, total_s=0.2,
        request=types.SimpleNamespace(model="mystery:13b"),
    )
    stats = generation_stats_from(
        None, result, quantize="int8", n_chips=8, aliased=True
    )
    assert "bytes" not in stats and "modeled_decode_s" not in stats
    err = capsys.readouterr()
    assert "mystery:13b" in err.out + err.err


def test_aliased_detection_canonicalizes_urls(tmp_path):
    """localhost and 127.0.0.1 (and a trailing slash) are one server —
    one chip. Equivalent spellings must still be detected as aliasing
    (code-review round-4 finding)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
        FakeBackend as FB,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
        GenerationServer,
    )

    srv = GenerationServer(FB(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        cfg = LlmEnergyConfig(
            models=["m"],
            lengths=[100],
            repetitions=1,
            results_output_path=tmp_path,
            on_device_url=f"http://127.0.0.1:{srv.port}",
            remote_url=f"http://localhost:{srv.port}/",
        )
        cfg.experiment_path = tmp_path / "exp"
        cfg.before_experiment()
        assert cfg.describe_backend("remote").endswith("[aliased-on_device]")
    finally:
        srv.stop()


def test_recompute_energy_skips_rows_missing_raw_inputs(tmp_path):
    """A legacy table with a hole in ANY raw input column skips that row
    instead of aborting the whole recompute (code-review round-4
    finding)."""
    import csv

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    exp = tmp_path / "holes"
    exp.mkdir()
    cols = [
        "__run_id", "__done", "model", "location", "length",
        "prompt_tokens", "generated_tokens", "execution_time_s",
        "decode_s",
    ]
    with (exp / "run_table.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        base = {
            "__done": "DONE", "model": "qwen2:1.5b",
            "location": "on_device", "length": 100,
            "prompt_tokens": 64, "generated_tokens": 134,
            "execution_time_s": 0.6, "decode_s": 0.45,
        }
        w.writerow({**base, "__run_id": "run_0_repetition_0"})
        w.writerow(
            {**base, "__run_id": "run_1_repetition_0", "prompt_tokens": ""}
        )
        w.writerow(
            {**base, "__run_id": "run_2_repetition_0",
             "execution_time_s": ""}
        )
    assert recompute_energy(exp, reanalyze=False) == 1


def test_recompute_energy_warning_names_the_model(tmp_path, capsys):
    import csv

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    exp = tmp_path / "unknown"
    exp.mkdir()
    cols = [
        "__run_id", "__done", "model", "location", "length", "chips",
        "prompt_tokens", "generated_tokens", "execution_time_s", "decode_s",
    ]
    with (exp / "run_table.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        w.writerow({
            "__run_id": "run_0_repetition_0", "__done": "DONE",
            "model": "mystery:13b", "location": "remote", "length": 100,
            "chips": 8, "prompt_tokens": 64, "generated_tokens": 134,
            "execution_time_s": 0.6, "decode_s": 0.45,
        })
    recompute_energy(exp, reanalyze=False)
    out = capsys.readouterr()
    assert "mystery:13b" in out.out + out.err


def test_recompute_cross_row_aliasing_canonicalizes_backend_urls(tmp_path):
    """A legacy table recorded with localhost for one treatment and
    127.0.0.1 for the other (one loopback server) must still be detected
    as aliased by recompute (code-review round-4 finding)."""
    import csv

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    exp = tmp_path / "spellings"
    exp.mkdir()
    cols = [
        "__run_id", "__done", "model", "location", "length", "backend",
        "chips", "prompt_tokens", "generated_tokens",
        "execution_time_s", "decode_s",
    ]
    with (exp / "run_table.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for i, (loc, url, chips) in enumerate([
            ("on_device", "http:http://127.0.0.1:11434", 1),
            ("remote", "http:http://localhost:11434/", 8),
        ]):
            w.writerow({
                "__run_id": f"run_{i}_repetition_0", "__done": "DONE",
                "model": "qwen2:1.5b", "location": loc, "length": 100,
                "backend": url, "chips": chips, "prompt_tokens": 64,
                "generated_tokens": 134, "execution_time_s": 0.6,
                "decode_s": 0.45,
            })
    recompute_energy(exp, reanalyze=False)
    rows = {r["location"]: r for r in RunTableStore(exp).read()}
    assert rows["remote"]["remote_modeled_decode_s"] is not None
    assert rows["on_device"]["remote_modeled_decode_s"] is None


def test_recompute_does_not_bake_default_chips(tmp_path):
    """Without an explicit --chips map the fallback topology is USED but
    not persisted — a later recompute with the correct map must still
    take effect (code-review round-4 finding)."""
    import csv

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    exp = tmp_path / "nochips"
    exp.mkdir()
    cols = [
        "__run_id", "__done", "model", "location", "length",
        "prompt_tokens", "generated_tokens", "execution_time_s", "decode_s",
    ]
    with (exp / "run_table.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        w.writerow({
            "__run_id": "run_0_repetition_0", "__done": "DONE",
            "model": "qwen2:1.5b", "location": "remote", "length": 100,
            "prompt_tokens": 64, "generated_tokens": 134,
            "execution_time_s": 0.6, "decode_s": 0.45,
        })
    recompute_energy(exp, reanalyze=False)  # default topology: remote=8
    (row,) = RunTableStore(exp).read()
    e_default = row["energy_model_J"]
    assert row["chips"] is None  # fallback not baked in
    # the corrected topology still takes effect on a second pass...
    recompute_energy(
        exp, reanalyze=False, n_chips_by_location={"remote": 4}
    )
    (row,) = RunTableStore(exp).read()
    assert row["energy_model_J"] != e_default
    # ...and an operator-asserted map IS persisted
    assert row["chips"] == 4


def test_full_study_on_fake_counter_channel_prefers_measured(
    tmp_path, monkeypatch
):
    """VERDICT round-5 directive #6 e2e: with a live power counter (fake
    source injected at the module seam the profiler's default chain
    reads), the full study records tpu_energy_J per run AND the study's
    own post-hoc analysis selects the MEASURED channel as the energy
    metric — H2 runs unrestricted (no definitional exclusions). This is
    the path a real counter-bearing TPU VM takes with zero config
    changes; it caught after_experiment's fixed metric list silently
    excluding measured channels."""
    import json

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import tpu

    monkeypatch.setattr(tpu, "_try_read_power_w", lambda: 120.0)
    # a slower fake: each run's window must span several 0.1 s counter
    # sampling periods or the trapezoid integration has nothing to sum
    slow_fake = FakeBackend(tokens_per_s=400.0, simulate_delay=True)
    config = LlmEnergyConfig(
        models=["qwen2:1.5b", "gemma:2b"],
        locations=["on_device", "remote"],
        lengths=[100],
        repetitions=2,
        results_output_path=tmp_path,
        cooldown_ms=0,
        backends={"on_device": slow_fake, "remote": slow_fake},
        shuffle=True,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        TpuPowerCounterProfiler,
    )

    assert any(
        isinstance(p, TpuPowerCounterProfiler) for p in config.profilers
    ), "a live counter source must wire the profiler into the study"
    ExperimentController(config, echo=False).do_experiment()
    exp = tmp_path / "llm_energy_tpu"
    rows = RunTableStore(exp).read()
    assert rows and all(r["__done"] == RunProgress.DONE for r in rows)
    for r in rows:
        assert r["tpu_energy_J"] is not None and r["tpu_energy_J"] > 0
        assert r["tpu_avg_power_W"] == pytest.approx(120.0, rel=0.05)
    report = json.loads((exp / "analysis_report.json").read_text())
    assert "tpu_energy_J" in report["metrics"]
    # measured channel outranks the model as THE energy metric
    assert report["variance_check"]["metric"] == "tpu_energy_J"
    assert report.get("h2_energy_is_modelled") is False
    # unrestricted H2: nothing annotated definitional
    for per_metric in report["h2_spearman"].values():
        assert not any(h.get("definitional") for h in per_metric.values())
