"""Streaming delivery + cancellation (ISSUE 6): per-slice token egress,
SSE framing, disconnect-driven retirement with exact page accounting,
and deadline SLOs — scheduler-level (fake + real engine, all four cache
layouts) and over the real HTTP wire."""

import json
import socket
import threading
import time

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import REGISTRY
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
    RemoteHTTPBackend,
    RemoteServerError,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
    ContinuousScheduler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.stream import (
    DeadlineExceeded,
    StreamCancelled,
    TokenStream,
)


def _retired(reason: str) -> float:
    return (
        REGISTRY.counter("llm_sched_rows_retired_total", labels=("reason",))
        .labels(reason=reason)
        .value
    )


def _drain_stream(channel, timeout_s: float = 30.0):
    """Consume a channel fully; returns (delta_tokens, delta_text, result)."""
    tokens, text, result = [], [], None
    for event in channel.events(timeout_s=timeout_s):
        if event.kind == "delta":
            tokens.extend(event.tokens)
            text.append(event.text)
        elif event.kind == "done":
            result = event.result
        else:
            raise event.error
    return tokens, "".join(text), result


# -- SSE framing ---------------------------------------------------------------


def test_sse_framing_golden():
    """The exact wire bytes of one SSE event are a contract clients
    parse byte-by-byte — pin them."""
    assert protocol.sse_event({"response": "hi", "done": False}) == (
        b'data: {"response":"hi","done":false}\n\n'
    )
    assert protocol.sse_event({}) == b"data: {}\n\n"


def test_sse_records_round_trip():
    payloads = [{"a": 1}, {"response": "x", "x_tokens": [7, 8]}, {"done": True}]
    wire = b"".join(protocol.sse_event(p) for p in payloads)
    lines = [ln + "\n" for ln in wire.decode().split("\n")]
    assert list(protocol.sse_records(lines)) == payloads


def test_sse_records_tolerates_comments_and_crlf():
    lines = [": keepalive\r\n", 'data: {"v": 1}\r\n', "\r\n"]
    assert list(protocol.sse_records(lines)) == [{"v": 1}]


def test_deadline_ms_round_trips_on_wire():
    req = GenerationRequest("m", "x", max_new_tokens=4, deadline_ms=1500)
    assert protocol.request_from_wire(protocol.request_to_wire(req)) == req
    # absent on the wire -> None, and never emitted when unset
    plain = GenerationRequest("m", "x", max_new_tokens=4)
    wire = protocol.request_to_wire(plain)
    assert "x_deadline_ms" not in wire
    assert protocol.request_from_wire(wire).deadline_ms is None
    with pytest.raises(ValueError, match="deadline_ms"):
        GenerationRequest("m", "x", max_new_tokens=4, deadline_ms=0)


# -- the egress channel --------------------------------------------------------


def test_token_stream_orders_deltas_before_final():
    chan = TokenStream()
    assert chan.push("ab", [1, 2])
    assert chan.push("c", [3])
    result = FakeBackend().generate(
        GenerationRequest("m", "x", max_new_tokens=3)
    )
    chan.finish(result)
    tokens, text, final = _drain_stream(chan, timeout_s=2.0)
    assert tokens == [1, 2, 3] and text == "abc"
    assert final is result


def test_token_stream_cancel_unblocks_producer_and_refuses_pushes():
    chan = TokenStream(maxsize=2)
    assert chan.push("a", [1])
    chan.cancel()
    assert chan.cancelled and chan.cancel_cause == "explicit"
    assert not chan.push("b", [2])  # consumer gone


def test_token_stream_full_queue_is_backpressure_cancellation(monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import stream

    monkeypatch.setattr(stream, "PUSH_TIMEOUT_S", 0.05)
    chan = TokenStream(maxsize=1)
    assert chan.push("a", [1])
    assert not chan.push("b", [2])  # nobody draining -> backpressure
    assert chan.cancelled and chan.cancel_cause == "backpressure"


def test_token_stream_terminal_survives_full_queue():
    chan = TokenStream(maxsize=1)
    assert chan.push("a", [1])
    chan.fail(RuntimeError("boom"))  # must not block; supersedes the delta
    events = list(chan.events(timeout_s=2.0))
    assert events[-1].kind == "error"


# -- scheduler-level streaming -------------------------------------------------


def test_stream_matches_buffered_on_fake_backend():
    sched = ContinuousScheduler(FakeBackend(), slice_steps=8)
    sched.start()
    try:
        req = GenerationRequest("m", "parity", max_new_tokens=24, seed=9)
        tokens, _, result = _drain_stream(sched.submit_stream(req))
        buffered = sched.submit(req)
        assert result.tokens == buffered.tokens
        assert tokens == buffered.tokens  # concatenated deltas, exactly
        # TTFT-at-first-chunk rides the usual sched extras
        assert result.extras["sched"]["ttft_s"] >= 0
    finally:
        sched.stop()


def test_window_scheduler_stream_degenerates_to_final_event():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
    )

    sched = BatchScheduler(FakeBackend(), window_s=0.02)
    sched.start()
    try:
        req = GenerationRequest("m", "w", max_new_tokens=8, seed=2)
        tokens, _, result = _drain_stream(sched.submit_stream(req))
        assert tokens == []  # no per-slice producer under window dispatch
        assert result.tokens == FakeBackend().generate(req).tokens
    finally:
        sched.stop()


def test_cancel_mid_stream_retires_row_and_frees_slot():
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    sched = ContinuousScheduler(backend, slice_steps=8)
    sched.start()
    try:
        before = _retired("cancelled")
        req = GenerationRequest("m", "long", max_new_tokens=400)
        chan = sched.submit_stream(req)
        events = chan.events(timeout_s=10.0)
        got = 0
        for event in events:
            assert event.kind == "delta"
            got += len(event.tokens)
            if got >= 8:
                break
        chan.cancel()
        # the reap runs between slices: the terminal error arrives and
        # the cancelled-retirement counter moves within a slice or two
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if _retired("cancelled") > before:
                break
            time.sleep(0.02)
        assert _retired("cancelled") > before
    finally:
        sched.stop()


def test_deadline_rejects_queued_ticket_before_admission():
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    sched = ContinuousScheduler(backend, slice_steps=8)
    sched.start()
    try:
        done = {}

        def anchor():
            done["a"] = sched.submit(
                GenerationRequest("m", "anchor", max_new_tokens=300)
            )

        t = threading.Thread(target=anchor)
        t.start()
        time.sleep(0.1)  # the anchor session is mid-decode
        # incompatible model -> must wait for the session to drain; its
        # deadline passes IN THE QUEUE and it is shed pre-admission
        with pytest.raises(DeadlineExceeded, match="queued"):
            sched.submit(
                GenerationRequest(
                    "other", "q", max_new_tokens=4, deadline_ms=200
                )
            )
        t.join(timeout=20)
        assert done["a"].generated_tokens == 300
    finally:
        sched.stop()


def test_deadline_retires_in_flight_row():
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    sched = ContinuousScheduler(backend, slice_steps=8)
    sched.start()
    try:
        before = _retired("deadline")
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded, match="mid-flight"):
            sched.submit(
                GenerationRequest(
                    "m", "slow", max_new_tokens=1000, deadline_ms=250
                )
            )
        # enforced within ~one slice of the deadline, not at drain
        assert time.monotonic() - t0 < 2.0
        assert _retired("deadline") > before
    finally:
        sched.stop()


def test_ttft_slo_rejects_stale_queued_ticket():
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    sched = ContinuousScheduler(backend, slice_steps=8, ttft_slo_ms=150)
    sched.start()
    try:
        def anchor():
            sched.submit(GenerationRequest("m", "anchor", max_new_tokens=300))

        t = threading.Thread(target=anchor)
        t.start()
        time.sleep(0.1)
        with pytest.raises(DeadlineExceeded, match="TTFT SLO"):
            sched.submit(GenerationRequest("other", "q", max_new_tokens=4))
        t.join(timeout=20)
    finally:
        sched.stop()


# -- real engine: cancellation page accounting + 4-layout parity ---------------


@pytest.fixture(scope="module")
def registry():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    return {"tiny": get_model_config("qwen2:1.5b").tiny()}


def test_disconnect_returns_pages_to_pool_exactly(registry):
    """The acceptance invariant: a cancelled streaming row's pages are
    recycled and the pool's free count returns EXACTLY to its
    pre-admission level, within one decode slice of the cancel."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    eng = JaxEngine(registry=dict(registry), dtype=jnp.float32, paged_kv=True)
    anchor = GenerationRequest(
        "tiny", "anchor", max_new_tokens=60, stop_at_eos=False
    )
    victim = GenerationRequest(
        "tiny", "victim row to cancel", max_new_tokens=60,
        stop_at_eos=False, seed=3,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    free_before_join = sess.pool.free_pages
    sess.step(4)
    sess.join(victim)
    victim_pages = next(
        row.pages for row in sess.rows
        if row is not None and row.request is victim
    )
    assert sess.pool.free_pages == free_before_join - len(victim_pages)
    sess.step(4)
    assert sess.cancel(victim)
    # exact restoration: every page the victim held is back on the free
    # list; the anchor's holdings are untouched
    assert sess.pool.free_pages == free_before_join
    assert sess.active == 1
    # and the anchor decodes on, unperturbed, to its solo stream
    results = []
    while sess.active:
        results.extend(sess.step(8))
    assert results[0].tokens == eng.generate(anchor).tokens
    sess.close()


def test_cancelled_rows_never_credit_goodput(registry):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    goodput = REGISTRY.counter("llm_engine_goodput_tokens_total").labels()
    eng = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    req = GenerationRequest("tiny", "wasted", max_new_tokens=40,
                            stop_at_eos=False)
    sess = eng.decode_open([req], reserve_rows=2)
    sess.step(4)
    before = goodput.value
    assert sess.cancel(req)
    assert goodput.value == before  # abandoned work is waste, not goodput
    sess.close()


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_stream_matches_buffered_all_layouts(registry, paged, kv):
    """Stream-vs-buffered token parity on every cache layout: the
    streamed final result AND the concatenated per-slice deltas equal
    the buffered (solo) stream."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    eng = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=paged,
        kv_quantize=kv,
    )
    req = GenerationRequest(
        "tiny", "stream parity row", max_new_tokens=18,
        stop_at_eos=False, seed=4,
    )
    solo = eng.generate(req)
    sched = ContinuousScheduler(eng, slice_steps=4)
    sched.start()
    try:
        tokens, _, result = _drain_stream(
            sched.submit_stream(req), timeout_s=120.0
        )
    finally:
        sched.stop()
    assert result.tokens == solo.tokens
    assert tokens == solo.tokens


# -- the real HTTP wire --------------------------------------------------------


@pytest.fixture()
def sse_server():
    srv = GenerationServer(
        FakeBackend(tokens_per_s=300.0, simulate_delay=True),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    yield srv
    srv.stop()


def test_http_stream_is_sse_and_token_identical(sse_server):
    client = RemoteHTTPBackend(f"http://127.0.0.1:{sse_server.port}")
    req = GenerationRequest("m", "wire parity", max_new_tokens=24, seed=7)
    chunks = list(client.generate_stream(req))
    assert chunks[-1].done
    final = chunks[-1].result
    buffered = FakeBackend().generate(req)
    assert final.tokens == buffered.tokens
    assert final.text == buffered.text
    assert [t for c in chunks[:-1] for t in c.tokens] == buffered.tokens
    # extras (sched attribution) ride the final SSE event
    assert "sched" in (final.extras or {})


def test_http_stream_content_type_is_event_stream(sse_server):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{sse_server.port}/api/generate",
        data=json.dumps(
            {
                "model": "m",
                "prompt": "ct",
                "stream": True,
                "options": {"num_predict": 4},
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.headers.get("Content-Type") == protocol.STREAM_CONTENT_TYPE
        resp.read()


def test_http_disconnect_mid_stream_cancels_server_side(sse_server):
    """Kill the socket mid-stream: the server's next SSE write fails,
    the channel cancels, and the scheduler retires the row
    (reason="cancelled") — observable on /metrics and in free slots."""
    before = _retired("cancelled")
    client = RemoteHTTPBackend(f"http://127.0.0.1:{sse_server.port}")
    req = GenerationRequest("m", "to be cancelled", max_new_tokens=600)
    gen = client.generate_stream(req)
    got = 0
    for chunk in gen:
        got += len(chunk.tokens)
        if got >= 8:
            break
    gen.close()  # early close = the documented cancellation trigger
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _retired("cancelled") > before:
            break
        time.sleep(0.05)
    assert _retired("cancelled") > before


def test_http_stream_unknown_model_is_clean_404(sse_server):
    client = RemoteHTTPBackend(f"http://127.0.0.1:{sse_server.port}")
    sse_server.models.extend(["m"])  # allowlist excludes "nope"
    with pytest.raises(RemoteServerError) as exc_info:
        list(client.generate_stream(GenerationRequest("nope", "x", 4)))
    assert exc_info.value.status == 404


def test_http_deadline_maps_to_504():
    srv = GenerationServer(
        FakeBackend(tokens_per_s=150.0, simulate_delay=True),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        with pytest.raises(RemoteServerError) as exc_info:
            client.generate(
                GenerationRequest(
                    "m", "slow", max_new_tokens=1000, deadline_ms=200
                )
            )
        assert exc_info.value.status == 504
    finally:
        srv.stop()


def test_server_plumbs_ttft_slo_knob():
    srv = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True,
        scheduler="continuous", ttft_slo_ms=250.0,
    )
    assert srv._scheduler.ttft_slo_ms == 250.0
    assert srv._scheduler.debug_state()["ttft_slo_ms"] == 250.0
    srv.stop()


def test_streamed_ticket_failure_ends_channel():
    """Every scheduler failure path must terminate the egress channel —
    a consumer can never be stranded (here: shutdown mid-stream)."""
    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=100.0, simulate_delay=True), slice_steps=8
    )
    sched.start()
    chan = sched.submit_stream(
        GenerationRequest("m", "orphaned", max_new_tokens=500)
    )
    events = chan.events(timeout_s=10.0)
    next(events)  # stream is live
    sched.stop()
    terminal = list(events)[-1]
    assert terminal.kind == "error"
    assert "shutting down" in str(terminal.error)


def test_stream_cancelled_exception_type():
    """The explicit cancel path surfaces as StreamCancelled on the
    ticket (the server closes quietly; in-process callers can match)."""
    backend = FakeBackend(tokens_per_s=200.0, simulate_delay=True)
    sched = ContinuousScheduler(backend, slice_steps=8)
    sched.start()
    try:
        chan = sched.submit_stream(
            GenerationRequest("m", "x", max_new_tokens=400)
        )
        events = chan.events(timeout_s=10.0)
        next(events)
        chan.cancel()
        terminal = list(events)
        # cancel() drained the queue; the terminal error may be the only
        # event left — and it must be the cancellation
        assert terminal and terminal[-1].kind == "error"
        assert isinstance(terminal[-1].error, StreamCancelled)
    finally:
        sched.stop()


# -- SSE keep-alive during idle prefill gaps (ISSUE 7 satellite) ---------------


def test_sse_keepalive_comment_golden():
    """The keep-alive comment's wire bytes are a contract (SSE spec: a
    ':'-prefixed line every parser must skip) — pin them."""
    assert protocol.SSE_KEEPALIVE == b": keep-alive\n\n"
    # our own parser skips it, deltas survive around it
    lines = [": keep-alive\n", "\n", 'data: {"v": 2}\n', "\n"]
    assert list(protocol.sse_records(lines)) == [{"v": 2}]


def test_token_stream_events_yield_keepalives_when_idle():
    """A silent producer yields NON-terminal keepalive events every
    keepalive_s; the overall timeout_s still terminates the stream."""
    chan = TokenStream()
    kinds = [
        e.kind for e in chan.events(timeout_s=0.25, keepalive_s=0.05)
    ]
    assert kinds[-1] == "error"  # the overall bound still fires
    assert kinds.count("keepalive") >= 2  # comments flowed in between


def test_token_stream_keepalive_resets_on_activity():
    """An event arriving resets the silence clock: a stream with
    activity inside every keepalive window never yields keepalives."""
    chan = TokenStream()

    def producer():
        for i in range(4):
            time.sleep(0.02)
            chan.push("x", [i])
        chan.finish(
            FakeBackend().generate(GenerationRequest("m", "x", 4))
        )

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    events = list(chan.events(timeout_s=5.0, keepalive_s=0.5))
    t.join()
    assert [e.kind for e in events] == ["delta"] * 4 + ["done"]


def test_http_keepalive_comments_flow_during_idle_gaps(monkeypatch):
    """End-to-end pin of the ISSUE 6 follow-on: with slices far apart
    (a long idle gap between deltas — the shape of a chunked join's
    prefill), the SSE socket carries ': keep-alive' comments between
    events, and the client still parses the stream to an identical
    final result (comments are invisible to sse_records)."""
    import urllib.request

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import (
        server as srv_mod,
    )

    monkeypatch.setattr(srv_mod, "STREAM_KEEPALIVE_S", 0.05)
    srv = GenerationServer(
        # 16-step slices at 40 tok/s = 0.4 s between delta pushes —
        # many keep-alive windows of producer silence per gap
        FakeBackend(tokens_per_s=40.0, simulate_delay=True),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/api/generate",
            data=json.dumps(
                {
                    "model": "m",
                    "prompt": "keepalive probe",
                    "stream": True,
                    "options": {"num_predict": 48},
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            raw = resp.read()
        assert b": keep-alive\n\n" in raw  # comments hit the wire
        records = list(
            protocol.sse_records(
                ln + "\n" for ln in raw.decode().split("\n")
            )
        )
        assert records and records[-1].get("done") is True
        solo = FakeBackend().generate(
            GenerationRequest("m", "keepalive probe", max_new_tokens=48)
        )
        assert records[-1]["x_text"] == solo.text  # parity through comments
    finally:
        srv.stop()
