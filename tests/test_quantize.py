"""Int8 weight-only quantization: accuracy, size, engine + TP integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import JaxEngine
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
    DEFAULT_QUANT_KEYS,
    is_quantized,
    maybe_dequant,
    params_nbytes,
    quantize_params,
    quantize_tensor,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
    forward,
    logits_for,
)


def test_quantize_tensor_round_trip_accuracy():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128)) * 0.05
    q = quantize_tensor(w)
    assert q["q"].dtype == jnp.int8
    deq = maybe_dequant(q, jnp.float32)
    # symmetric int8: relative error bounded by ~1/127 of the channel max
    err = np.abs(np.asarray(deq) - np.asarray(w))
    per_channel_max = np.abs(np.asarray(w)).max(axis=0)
    assert (err <= per_channel_max / 127.0 * 1.01 + 1e-8).all()


def test_maybe_dequant_passthrough():
    w = jnp.ones((4, 4))
    assert maybe_dequant(w) is w


def test_quantize_params_halves_size():
    cfg = get_model_config("qwen2:1.5b").tiny()
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.bfloat16)
    qparams = quantize_params(tf.params)
    for key in DEFAULT_QUANT_KEYS:
        assert is_quantized(qparams[key])
    # embeddings quantize too (int8 in every mode): the logits matmul
    # streams them every decode step
    assert is_quantized(qparams["embed"])
    assert params_nbytes(qparams) < 0.6 * params_nbytes(tf.params)


def test_quantized_forward_close_to_full_precision():
    cfg = get_model_config("mistral:7b").tiny()
    tf = Transformer.initialise(cfg, seed=1, dtype=jnp.float32)
    toks = jnp.array([[3, 7, 11, 2]], dtype=jnp.int32)
    k0, v0 = tf.init_cache(1, 8, dtype=jnp.float32)
    hidden_fp, _, _ = forward(tf.params, cfg, toks, jnp.int32(0), k0, v0)
    logits_fp = logits_for(tf.params, cfg, hidden_fp[:, -1])
    qparams = quantize_params(tf.params)
    hidden_q, _, _ = forward(qparams, cfg, toks, jnp.int32(0), k0, v0)
    logits_q = logits_for(qparams, cfg, hidden_q[:, -1])
    # int8 weight noise shifts logits slightly; ranking of the top token is
    # a weak ask for random weights, so compare the distributions
    corr = np.corrcoef(
        np.asarray(logits_fp).ravel(), np.asarray(logits_q).ravel()
    )[0, 1]
    assert corr > 0.99


def test_engine_int8_generates_and_shrinks():
    registry = {"t": get_model_config("qwen2:1.5b").tiny()}
    fp = JaxEngine(registry=registry, dtype=jnp.float32)
    q8 = JaxEngine(registry=registry, dtype=jnp.float32, quantize="int8")
    r = q8.generate(GenerationRequest("t", "quantized", 10))
    assert r.generated_tokens <= 10
    fp.load_model("t")
    assert params_nbytes(q8._models["t"].params) < params_nbytes(
        fp._models["t"].params
    )


def test_engine_rejects_unknown_quantize():
    with pytest.raises(ValueError, match="unsupported quantize"):
        JaxEngine(quantize="fp4")


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_tp_engine_with_int8():
    import dataclasses

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    cfg = dataclasses.replace(
        get_model_config("mistral:7b").tiny(),
        n_heads=8,
        n_kv_heads=8,
        d_ff=128,
        d_model=64,
        d_head=16,
    )
    registry = {"t8": cfg}
    single = JaxEngine(registry=registry, dtype=jnp.float32, quantize="int8")
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()),
        registry=registry,
        dtype=jnp.float32,
        quantize="int8",
    )
    req = GenerationRequest("t8", "int8 tensor parallel", max_new_tokens=10)
    assert single.generate(req).tokens == tp.generate(req).tokens

def test_int4_pack_roundtrip():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        maybe_dequant,
        quantize_tensor_int4,
    )

    w = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8), jnp.float32)
    leaf = quantize_tensor_int4(w)
    assert leaf["q4"].shape == (2, 8, 8)  # packed along the input axis
    assert leaf["q4"].dtype == jnp.int8
    back = maybe_dequant(leaf, jnp.float32)
    assert back.shape == w.shape
    # 4-bit symmetric in [-7,7]: worst-case error is scale/2
    err = jnp.max(jnp.abs(back - w))
    assert float(err) <= float(jnp.max(leaf["s"])) / 2 + 1e-6
    # odd input dim rejected
    with pytest.raises(ValueError, match="even"):
        quantize_tensor_int4(jnp.ones((3, 8)))


def test_int4_forward_close_to_full_precision():
    cfg = get_model_config("mistral:7b").tiny()
    tf = Transformer.initialise(cfg, seed=1, dtype=jnp.float32)
    toks = jnp.array([[3, 7, 11, 2]], dtype=jnp.int32)
    shape = (cfg.n_layers, 1, cfg.n_kv_heads, 8, cfg.d_head)
    z = jnp.zeros(shape, jnp.float32)
    hidden, _, _ = forward(tf.params, cfg, toks, jnp.int32(0), z, z, None)
    full = logits_for(tf.params, cfg, hidden)
    qp = quantize_params(tf.params, mode="int4")
    hidden_q, _, _ = forward(qp, cfg, toks, jnp.int32(0), z, z, None)
    quant = logits_for(qp, cfg, hidden_q)
    # int4 is coarse; the ranking should broadly survive on tiny models
    assert full.shape == quant.shape
    corr = jnp.corrcoef(full.ravel(), quant.ravel())[0, 1]
    assert float(corr) > 0.95


def test_engine_int4_generates_and_shrinks():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        params_nbytes,
    )

    cfg = get_model_config("qwen2:1.5b").tiny()
    full = JaxEngine(registry={"m": cfg}, dtype=jnp.float32)
    full.load_model("m")
    q4 = JaxEngine(registry={"m": cfg}, dtype=jnp.float32, quantize="int4")
    q4.load_model("m")
    assert params_nbytes(q4._models["m"].params) < 0.45 * params_nbytes(
        full._models["m"].params
    )
    r = q4.generate(GenerationRequest("m", "hello int4", max_new_tokens=8))
    assert r.generated_tokens >= 1


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_tp_engine_with_int4():
    import dataclasses

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    cfg = dataclasses.replace(
        get_model_config("mistral:7b").tiny(),
        n_heads=8,
        n_kv_heads=8,
        d_ff=128,
        d_model=64,
        d_head=16,
    )
    registry = {"t4": cfg}
    single = JaxEngine(registry=registry, dtype=jnp.float32, quantize="int4")
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()),
        registry=registry,
        dtype=jnp.float32,
        quantize="int4",
    )
    req = GenerationRequest("t4", "int4 tensor parallel", max_new_tokens=10)
    assert single.generate(req).tokens == tp.generate(req).tokens

    # the i32-lane nibble layout shards the same way ({"q32","s"} leaves)
    single_i = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, quantize="int4-i32"
    )
    tp_i = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()),
        registry=dict(registry),
        dtype=jnp.float32,
        quantize="int4-i32",
    )
    assert single_i.generate(req).tokens == tp_i.generate(req).tokens


def test_int4_pallas_matmul_matches_dequant():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        quantize_tensor_int4,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_quant import (
        int4_matmul,
        int4_matmul_supported,
    )

    w = jax.random.normal(jax.random.PRNGKey(0), (512, 256), jnp.float32) * 0.1
    leaf = quantize_tensor_int4(w)
    assert int4_matmul_supported(1, 256, 256)
    # The kernel contracts in bf16 (MXU-native; 4-bit weights are exact in
    # bf16, activations are bf16 in the real decode path) — the reference
    # therefore truncates the activations the same way.
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 512), jnp.float32)
    got = int4_matmul(x, leaf["q4"], leaf["s"])
    x16 = x.astype(jnp.bfloat16).astype(jnp.float32)
    want = x16 @ maybe_dequant(leaf, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )
    # multi-row (speculative verify window) and non-square blocks
    x5 = jax.random.normal(jax.random.PRNGKey(2), (5, 512), jnp.float32)
    got5 = int4_matmul(x5, leaf["q4"], leaf["s"])
    want5 = x5.astype(jnp.bfloat16).astype(jnp.float32) @ maybe_dequant(
        leaf, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(got5), np.asarray(want5), rtol=1e-4, atol=1e-4
    )


def test_int4_i32_pack_roundtrip_and_kernel_parity():
    """The i32-lane nibble layout (VERDICT round-2 item 8 experiment):
    pack/dequant round-trips exactly against the halves layout, and the
    i32 kernel matches the dequantized reference."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        quantize_tensor_int4,
        quantize_tensor_int4_i32,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_quant import (
        int4_matmul_i32,
    )

    w = jax.random.normal(jax.random.PRNGKey(0), (2048, 256), jnp.float32) * 0.1
    leaf_h = quantize_tensor_int4(w)
    leaf_i = quantize_tensor_int4_i32(w)
    assert leaf_i["q32"].shape == (256, 256)
    assert leaf_i["q32"].dtype == jnp.int32
    # identical quantized values, independent of packing layout
    np.testing.assert_array_equal(
        np.asarray(maybe_dequant(leaf_i, jnp.float32)),
        np.asarray(maybe_dequant(leaf_h, jnp.float32)),
    )

    for rows in (1, 5):
        x = jax.random.normal(jax.random.PRNGKey(rows), (rows, 2048), jnp.float32)
        got = int4_matmul_i32(x, leaf_i["q32"], leaf_i["s"])
        want = x.astype(jnp.bfloat16).astype(jnp.float32) @ maybe_dequant(
            leaf_i, jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )


def test_int4_dense_dot_routes_and_matches():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        dense_dot,
        quantize_tensor_int4,
    )

    w = jax.random.normal(jax.random.PRNGKey(3), (512, 128), jnp.float32) * 0.1
    leaf = quantize_tensor_int4(w)
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 512), jnp.float32)
    kernel_out = dense_dot(x, leaf)  # decode shape → kernel path
    x16 = x.astype(jnp.bfloat16).astype(jnp.float32)
    xla_out = jnp.einsum("bsd,dh->bsh", x16, maybe_dequant(leaf, x.dtype))
    # bf16-contracting kernel vs f32 einsum on bf16-truncated activations
    np.testing.assert_allclose(
        np.asarray(kernel_out), np.asarray(xla_out), rtol=1e-4, atol=1e-4
    )
    # prefill shape falls back to the einsum path, same numbers
    xp = jax.random.normal(jax.random.PRNGKey(5), (1, 32, 512), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(dense_dot(xp, leaf)),
        np.asarray(jnp.einsum("bsd,dh->bsh", xp, maybe_dequant(leaf, xp.dtype))),
        rtol=2e-5,
        atol=2e-5,
    )


def test_embed_rowwise_scales_resist_outlier_rows():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        embed_lookup,
        quantize_tensor_rowwise,
    )

    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.02
    w = w.at[7].set(w[7] * 500.0)  # one outlier vocab row
    leaf = quantize_tensor_rowwise(w)
    assert leaf["s"].shape == (64, 1)  # one scale per vocab row
    deq = maybe_dequant(leaf, jnp.float32)
    # non-outlier rows keep their own resolution
    err = jnp.abs(deq[:7] - w[:7])
    assert float(jnp.max(err)) <= float(jnp.max(jnp.abs(w[:7]))) / 127 * 1.01
    # gather path dequantizes row-local
    rows = embed_lookup(leaf, jnp.asarray([[1, 7]]), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(rows[0, 0]), np.asarray(deq[1]), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(rows[0, 1]), np.asarray(deq[7]), atol=1e-6
    )


def test_int4_kernel_disabled_context_uses_einsum(monkeypatch):
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_quant as pq
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        dense_dot,
        int4_kernel_disabled,
        quantize_tensor_int4,
    )

    w = jax.random.normal(jax.random.PRNGKey(3), (512, 128)) * 0.1
    leaf = quantize_tensor_int4(w)
    x = jnp.ones((1, 1, 512), jnp.float32)

    def boom(*a, **k):
        raise AssertionError("kernel must not run under the disabled context")

    monkeypatch.setattr(pq, "int4_matmul", boom)
    with int4_kernel_disabled():
        out = dense_dot(x, leaf)  # einsum path despite decode shape
    assert out.shape == (1, 1, 128)
