"""Test env: force JAX onto 8 virtual CPU devices before jax is imported.

SURVEY.md §4: multi-chip paths are tested with
``--xla_force_host_platform_device_count`` virtual devices rather than real
slices; the accelerator-free kernel tests don't touch JAX at all.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
