"""Test env: force JAX onto 8 virtual CPU devices before jax is imported.

SURVEY.md §4: multi-chip paths are tested with
``--xla_force_host_platform_device_count`` virtual devices rather than real
slices; the accelerator-free kernel tests don't touch JAX at all.
"""

import os
import tempfile

# Flight-recorder crash dumps (obs/flight.py) default to the working
# directory; the suite's deliberate poison-batch tests must not litter
# the repo root (tests that assert on dumps monkeypatch their own dir).
os.environ.setdefault(
    "TPU_LLM_CRASH_DIR", tempfile.mkdtemp(prefix="flight_crash_test_")
)

# Force (not setdefault): this environment globally sets JAX_PLATFORMS=axon
# (the real-TPU tunnel); tests must run on virtual CPU devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# XLA:CPU compiles each distinct op shape at ~0.5-1 s in this environment;
# a warm persistent cache cuts the suite from minutes to seconds.
import jax  # noqa: E402

# The axon sitecustomize force-selects jax_platforms="axon,cpu" (real-TPU
# tunnel) regardless of the env var; the config update below beats it so
# tests run on the 8 virtual CPU devices and never touch the one real chip.
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
# This environment's default matmul precision truncates f32 matmul inputs to
# bf16 (observed ~6e-3 abs error on unit-scale data), which would drown the
# numerical parity tests; force full f32 for tests only.
jax.config.update("jax_default_matmul_precision", "highest")
