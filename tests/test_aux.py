"""Auxiliary subsystems: weight checkpointing, distributed helpers, trace
profiler, analyze CLI."""

import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.checkpoint import (
    WeightCache,
    load_params,
    save_params,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.distributed import (
    distributed_config_from_env,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.cli import main


def test_params_checkpoint_round_trip(tmp_path):
    cfg = get_model_config("qwen2:1.5b").tiny()
    tf = Transformer.initialise(cfg, seed=3, dtype=jnp.float32)
    path = save_params(tf.params, tmp_path / "ckpt")
    restored = load_params(path)
    np.testing.assert_array_equal(
        np.asarray(restored["wq"]), np.asarray(tf.params["wq"])
    )
    assert set(restored) == set(tf.params)


def test_weight_cache_initialises_once(tmp_path):
    cfg = get_model_config("qwen2:1.5b").tiny()
    calls = []

    def init_fn():
        calls.append(1)
        return Transformer.initialise(cfg, seed=0, dtype=jnp.float32).params

    cache = WeightCache(tmp_path)
    p1 = cache.get_or_init("qwen2:1.5b", 0, init_fn)
    p2 = cache.get_or_init("qwen2:1.5b", 0, init_fn)
    assert len(calls) == 1  # second call restored from disk
    np.testing.assert_array_equal(np.asarray(p1["wq"]), np.asarray(p2["wq"]))


def test_engine_uses_weight_cache(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    registry = {"t": get_model_config("qwen2:1.5b").tiny()}
    eng1 = JaxEngine(
        registry=registry, dtype=jnp.float32, weight_cache_dir=str(tmp_path)
    )
    r1 = eng1.generate(GenerationRequest("t", "cached weights", 8))
    eng2 = JaxEngine(
        registry=registry, dtype=jnp.float32, weight_cache_dir=str(tmp_path)
    )
    r2 = eng2.generate(GenerationRequest("t", "cached weights", 8))
    assert r1.tokens == r2.tokens  # identical weights from the cache


def test_distributed_config_absent(monkeypatch, tmp_path):
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.chdir(tmp_path)  # no .env here
    assert distributed_config_from_env() is None


def test_distributed_config_from_dotenv(tmp_path, monkeypatch):
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("NUM_PROCESSES", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    env = tmp_path / ".env"
    env.write_text("COORDINATOR_ADDRESS=10.0.0.1:1234\nNUM_PROCESSES=4\nPROCESS_ID=2\n")
    config = distributed_config_from_env(env)
    assert config == {
        "coordinator_address": "10.0.0.1:1234",
        "num_processes": 4,
        "process_id": 2,
    }
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("NUM_PROCESSES", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)


def test_analyze_cli(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import (
        RunTableStore,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import (
        RunProgress,
    )

    rows = []
    for i, (loc, e) in enumerate(
        [("on_device", 100.0), ("on_device", 110.0), ("remote", 20.0), ("remote", 22.0)]
        * 3
    ):
        rows.append(
            {
                "__run_id": f"run_{i}_repetition_0",
                "__done": RunProgress.DONE,
                "model": "m",
                "location": loc,
                "length": 100,
                "energy_model_J": e + i * 0.1,  # the study's actual column
                "execution_time_s": e / 10,
            }
        )
    exp = tmp_path / "exp"
    RunTableStore(exp).write(rows)
    assert main(["analyze", str(exp)]) == 0
    report = (exp / "analysis_report.md").read_text()
    # detected metrics include the modelled-energy column → H1 present
    assert "energy_model_J" in report
    assert "H1: energy" in report
    assert main(["analyze", str(tmp_path / "nothing")]) == 2


def test_weight_cache_keyed_by_config_and_dtype(tmp_path):
    """A checkpoint for one architecture/dtype must never restore for another."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    tiny = get_model_config("qwen2:1.5b").tiny()
    smaller = get_model_config("qwen2:1.5b").tiny(vocab_size=256)
    e1 = JaxEngine(
        registry={"m": tiny}, dtype=jnp.float32, weight_cache_dir=str(tmp_path)
    )
    e1.load_model("m")
    e2 = JaxEngine(
        registry={"m": smaller}, dtype=jnp.float32, weight_cache_dir=str(tmp_path)
    )
    e2.load_model("m")  # different config → fresh init, not the cached one
    assert e2._models["m"].params["embed"].shape[0] == 256
    # and a dtype change also misses the cache
    e3 = JaxEngine(
        registry={"m": tiny}, dtype=jnp.bfloat16, weight_cache_dir=str(tmp_path)
    )
    e3.load_model("m")
    assert e3._models["m"].params["wq"].dtype == jnp.bfloat16


def test_host_profiler_columns_stable_across_implementations():
    """Native and Python host profilers must offer the same column union so
    resume's column-equality check survives availability flips."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.host import (
        HostResourceProfiler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.native_host import (
        NativeHostProfiler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.rapl import (
        RaplEnergyProfiler,
    )

    python_cols = set(HostResourceProfiler.data_columns) | set(
        RaplEnergyProfiler.data_columns
    )
    assert set(NativeHostProfiler.data_columns) == python_cols


def test_jax_trace_profiler_graceful(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.jax_trace import (
        JaxTraceProfiler,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import (
        RunContext,
    )

    run_dir = tmp_path / "r"
    run_dir.mkdir()
    ctx = RunContext("r", 1, 1, {}, run_dir, tmp_path)
    prof = JaxTraceProfiler()
    prof.on_start(ctx)
    _ = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert "trace_dir" in data
