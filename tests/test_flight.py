"""Flight recorder, debug introspection endpoints, and streaming
anomaly detection (ISSUE 5).

Acceptance surface: the ring buffer survives concurrent writers with
drop-oldest accounting and no event tearing; a poisoned ticket through
the window scheduler's bisection fallback leaves a crash dump (last
events + live scheduler state); ``/debug/state`` and ``/debug/flight``
return live session state and seq-ordered events with trace ids linking
back to spans (and 404 under the kill switch); the Welford cell-CV
tracker and the rolling-median spike detector fire anomaly events; the
stepped decode path exports goodput counters.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu import obs
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
    CellCvTracker,
    SpikeDetector,
    Welford,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
    EV_ANOMALY,
    EV_REQUEST_ADMITTED,
    EV_ROW_RETIRED,
    EV_SLICE,
    FLIGHT,
    FlightRecorder,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    REGISTRY,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    yield
    (obs.enable if was else obs.disable)()


@pytest.fixture
def obs_off():
    was = obs.enabled()
    obs.disable()
    yield
    (obs.enable if was else obs.disable)()


def _get_json(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def _post_generate(base: str, prompt: str, num_predict: int):
    req = urllib.request.Request(
        f"{base}/api/generate",
        data=json.dumps(
            {
                "model": "m",
                "prompt": prompt,
                "options": {"num_predict": num_predict},
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


# -- ring buffer ---------------------------------------------------------------


def test_ring_records_schema_and_order(obs_on):
    rec = FlightRecorder(capacity=16)
    rec.emit("a", trace=7, x=1)
    rec.emit("b")
    events = rec.events()
    assert [e["type"] for e in events] == ["a", "b"]
    assert events[0]["seq"] < events[1]["seq"]
    assert events[0]["trace"] == 7 and events[0]["x"] == 1
    assert "trace" not in events[1]  # no request context, no key
    assert rec.summary()["by_type"] == {"a": 1, "b": 1}
    assert rec.summary()["dropped"] == 0


def test_ring_drop_oldest_counts_dropped(obs_on):
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.emit("e", i=i)
    events = rec.events()
    assert len(events) == 4
    # oldest aged out: the ring holds the LAST four, in order
    assert [e["i"] for e in events] == [6, 7, 8, 9]
    s = rec.summary()
    assert s["dropped"] == 6 and s["events_total"] == 10


def test_ring_filters_and_limits(obs_on):
    rec = FlightRecorder(capacity=64)
    for i in range(6):
        rec.emit("a" if i % 2 else "b", i=i)
    assert [e["i"] for e in rec.events(n=2)] == [4, 5]
    assert [e["i"] for e in rec.events(type_="a")] == [1, 3, 5]


def test_ring_concurrent_writers_no_tearing(obs_on):
    """8 writers × 200 events through a 256-slot ring: every surviving
    event is whole (all schema fields, writer-local order preserved),
    accounting is exact (total == seq high-water == kept + dropped)."""
    rec = FlightRecorder(capacity=256)
    n_threads, per_thread = 8, 200

    def writer(tid):
        for i in range(per_thread):
            rec.emit("w", tid=tid, i=i)

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.events()
    s = rec.summary()
    assert s["events_total"] == n_threads * per_thread
    assert len(events) == 256
    assert s["dropped"] == n_threads * per_thread - 256
    # no tearing: every event carries its full schema and the ring is
    # strictly seq-ordered
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    per_writer = {}
    for e in events:
        assert {"seq", "t_s", "type", "tid", "i"} <= set(e)
        per_writer.setdefault(e["tid"], []).append(e["i"])
    # writer-local order survives interleaving
    for order in per_writer.values():
        assert order == sorted(order)


def test_ring_export_jsonl(obs_on, tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.emit("x", k="v")
    out = tmp_path / "flight.jsonl"
    assert rec.export_jsonl(out) == 1
    line = json.loads(out.read_text().splitlines()[0])
    assert line["type"] == "x" and line["k"] == "v"


def test_ring_emit_noop_when_disabled(obs_off):
    rec = FlightRecorder(capacity=8)
    assert rec.emit("dead") is None
    assert rec.events() == []
    assert rec.summary()["events_total"] == 0
    assert rec.crash_dump("dead") is None


# -- crash dump ----------------------------------------------------------------


def test_crash_dump_writes_events_and_state(obs_on, tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.emit("before_crash", step=1)
    path = rec.crash_dump(
        "test failure", state={"queue_depth": 3}, path=tmp_path / "dump.json"
    )
    payload = json.loads((tmp_path / "dump.json").read_text())
    assert path == str(tmp_path / "dump.json")
    assert payload["reason"] == "test failure"
    assert payload["state"] == {"queue_depth": 3}
    assert any(e["type"] == "before_crash" for e in payload["events"])
    # the dump itself is on the record
    assert rec.events(type_="crash_dump")


def test_crash_dump_never_raises(obs_on, tmp_path):
    rec = FlightRecorder(capacity=8)
    # unwritable destination: returns None instead of raising
    assert (
        rec.crash_dump("x", path=tmp_path / "no" / "such" / "dir" / "f.json")
        is None
    )


def test_poisoned_window_batch_leaves_crash_dump(obs_on, tmp_path, monkeypatch):
    """A poisoned ticket that kills the window batch dispatch triggers
    the bisection fallback AND writes a crash dump (last events + live
    scheduler state) into TPU_LLM_CRASH_DIR."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
    )

    monkeypatch.setenv("TPU_LLM_CRASH_DIR", str(tmp_path))

    class OnePoisonBackend(FakeBackend):
        def generate(self, request):
            if request.prompt == "poison":
                raise RuntimeError("bad request")
            return super().generate(request)

        def generate_batch(self, requests):
            if any(r.prompt == "poison" for r in requests):
                raise RuntimeError("batch poisoned")
            return [self.generate(r) for r in requests]

    sched = BatchScheduler(OnePoisonBackend(), window_s=0.05, max_batch=8)
    sched.start()
    results, errors = {}, {}

    def call(prompt):
        try:
            results[prompt] = sched.submit(
                GenerationRequest("m", prompt, max_new_tokens=4)
            )
        except Exception as exc:  # noqa: BLE001
            errors[prompt] = exc

    try:
        threads = [
            threading.Thread(target=call, args=(p,))
            for p in ("a", "b", "poison", "c")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        sched.stop()
    assert set(results) == {"a", "b", "c"} and set(errors) == {"poison"}
    dumps = list(tmp_path.glob("flight_crash_*.json"))
    assert dumps, "no crash dump written"
    payload = json.loads(dumps[0].read_text())
    assert "window batch dispatch failed" in payload["reason"]
    assert payload["state"]["mode"] == "window"
    # the dump's event tail contains the batch's admissions and the
    # fallback that killed it
    types = {e["type"] for e in payload["events"]}
    assert EV_REQUEST_ADMITTED in types
    assert "batch_fallback" in types


# -- debug endpoints -----------------------------------------------------------


def test_debug_endpoints_serve_live_state_and_events(obs_on):
    FLIGHT.clear()
    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = _post_generate(base, "hello", 8)
        assert body.get("done"), body

        state = _get_json(f"{base}/debug/state")
        assert state["scheduler_mode"] == "continuous"
        assert state["backend"] == "FakeBackend"
        assert state["scheduler"]["mode"] == "continuous"
        assert state["scheduler"]["queue_depth"] == 0
        assert state["flight"]["events_total"] > 0

        flight = _get_json(f"{base}/debug/flight?n=100")
        events = flight["events"]
        types = [e["type"] for e in events]
        assert EV_REQUEST_ADMITTED in types
        assert EV_SLICE in types
        assert EV_ROW_RETIRED in types
        # seq-ordered, and the request's admitted precedes its retired
        # with ONE trace id linking them (and the span tree)
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        admitted = next(e for e in events if e["type"] == EV_REQUEST_ADMITTED)
        retired = next(
            e
            for e in events
            if e["type"] == EV_ROW_RETIRED
            and e.get("trace") == admitted.get("trace")
        )
        assert admitted.get("trace") is not None
        assert admitted["seq"] < retired["seq"]

        # ?type= filter and ?n= bound
        only = _get_json(f"{base}/debug/flight?n=2&type={EV_SLICE}")
        assert all(e["type"] == EV_SLICE for e in only["events"])
        assert len(only["events"]) <= 2
    finally:
        srv.stop()


def test_debug_flight_rejects_bad_n(obs_on):
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/flight?n=bogus", timeout=10
            )
        assert exc_info.value.code == 400
    finally:
        srv.stop()


def test_debug_endpoints_404_when_disabled(obs_off):
    """Kill-switch completeness: the debug surface is OFF with telemetry
    off — same contract as /metrics."""
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        for path in ("/debug/state", "/debug/flight", "/debug/flight?n=5"):
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=10
                )
            assert exc_info.value.code == 404, path
    finally:
        srv.stop()


def test_kill_switch_served_request_emits_no_events(obs_off):
    """With telemetry off a served request leaves ZERO flight events —
    the scheduler/engine emit calls are no-ops."""
    before = FLIGHT.summary()["events_total"]
    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = _post_generate(base, "quiet", 4)
        assert body.get("done"), body
    finally:
        srv.stop()
    assert FLIGHT.summary()["events_total"] == before


# -- goodput accounting --------------------------------------------------------


def test_goodput_counters_from_stepped_session(obs_on):
    """The stepped decode path exports llm_engine_goodput_tokens_total
    (tokens on completed rows) vs llm_engine_stepped_tokens_total (every
    row x step the bucket executed): goodput <= stepped, both move."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
        GOODPUT_C,
        STEPPED_C,
    )

    good0 = GOODPUT_C.labels().value
    step0 = STEPPED_C.labels().value
    backend = FakeBackend()
    session = backend.decode_open(
        [
            GenerationRequest("m", "one", max_new_tokens=6),
            GenerationRequest("m", "two", max_new_tokens=20),
        ]
    )
    while session.active:
        session.step(8)
    session.close()
    good = GOODPUT_C.labels().value - good0
    stepped = STEPPED_C.labels().value - step0
    assert good == 6 + 20
    # rows step whole slices: the 6-token row rode 8 steps, the 20-token
    # row 24 — the overshoot is exactly the wasted-step fraction
    assert stepped > good
    text = REGISTRY.exposition()
    assert "llm_engine_goodput_tokens_total" in text
    assert "llm_engine_stepped_tokens_total" in text


def test_goodput_counters_real_engine_stepped(obs_on):
    """Same invariant on the REAL stepped engine (tiny CPU config)."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
        GOODPUT_C,
        STEPPED_C,
    )

    good0 = GOODPUT_C.labels().value
    step0 = STEPPED_C.labels().value
    engine = JaxEngine(
        registry={"tiny": get_model_config("qwen2:1.5b").tiny()},
        dtype=jnp.float32,
    )
    session = engine.decode_open(
        [
            GenerationRequest(
                "tiny", "a", max_new_tokens=4, stop_at_eos=False
            ),
            GenerationRequest(
                "tiny", "bb", max_new_tokens=10, stop_at_eos=False
            ),
        ]
    )
    while session.active:
        session.step()
    session.close()
    good = GOODPUT_C.labels().value - good0
    stepped = STEPPED_C.labels().value - step0
    # both rows completed: first tokens came from prefill, the decode
    # loop sampled the rest (max_new_tokens - 1 each at minimum)
    assert good >= (4 - 1) + (10 - 1)
    assert stepped > good  # padding slots + the short row's done steps


# -- Welford / cell CV ---------------------------------------------------------


def test_welford_matches_statistics():
    import statistics

    xs = [3.1, 2.9, 3.0, 3.3, 2.8, 3.05]
    w = Welford()
    for x in xs:
        w.update(x)
    assert w.count == len(xs)
    assert w.mean == pytest.approx(statistics.fmean(xs))
    assert w.std == pytest.approx(statistics.stdev(xs))
    assert w.cv == pytest.approx(statistics.stdev(xs) / statistics.fmean(xs))


def test_welford_cv_none_until_two_runs():
    w = Welford()
    assert w.cv is None
    w.update(5.0)
    assert w.cv is None
    w.update(5.0)
    assert w.cv == 0.0


def test_cell_cv_gauge_and_anomaly_once_per_breach(obs_on):
    FLIGHT.clear()
    tracker = CellCvTracker(threshold=0.05, min_runs=3)
    # a stable cell: CV well under the threshold, no anomaly
    for x in (100.0, 101.0, 99.5, 100.4):
        tracker.observe_run("qwen2:1.5b", 100, "on_device", energy_J=x)
    assert not FLIGHT.events(type_=EV_ANOMALY)
    # a noisy cell breaches after min_runs... once, not per run
    for x in (100.0, 160.0, 60.0, 150.0):
        tracker.observe_run("qwen2:1.5b", 500, "remote", energy_J=x)
    anomalies = FLIGHT.events(type_=EV_ANOMALY)
    assert len(anomalies) == 1
    a = anomalies[0]
    assert a["kind"] == "cell_cv" and a["model"] == "qwen2:1.5b"
    assert a["location"] == "remote" and a["cv"] > 0.05
    # the gauge family is exported with the cell's labels
    text = REGISTRY.exposition()
    assert "llm_run_cell_cv" in text
    assert (
        'llm_run_cell_cv{metric="energy_J",model="qwen2:1.5b",'
        'length="500",location="remote"}' in text
    )
    snap = tracker.snapshot()
    assert snap["energy_J|qwen2:1.5b|500|remote"]["breached"] is True
    assert snap["energy_J|qwen2:1.5b|100|on_device"]["breached"] is False


def test_cell_cv_rearm_after_recovery(obs_on):
    FLIGHT.clear()
    tracker = CellCvTracker(threshold=0.05, min_runs=2)
    tracker.observe_run("m", 1, "l", wall_s=1.0)
    tracker.observe_run("m", 1, "l", wall_s=2.0)  # breach #1
    assert len(FLIGHT.events(type_=EV_ANOMALY)) == 1
    # many identical runs drag the CV back under the threshold → re-arm
    for _ in range(200):
        tracker.observe_run("m", 1, "l", wall_s=1.5)
    key = ("wall_s", "m", "1", "l")
    assert key not in tracker._breached
    tracker.observe_run("m", 1, "l", wall_s=30.0)  # breach #2 fires again
    assert len(FLIGHT.events(type_=EV_ANOMALY)) == 2


def test_cell_cv_noop_when_disabled(obs_off):
    tracker = CellCvTracker()
    out = tracker.observe_run("m", 1, "l", energy_J=5.0, wall_s=1.0)
    assert out == {} and tracker.snapshot() == {}


def test_cell_cv_wired_through_study_run_data(obs_on, tmp_path):
    """The runner path: populate_run_data folds the run's modelled J and
    wall into the cell tracker (llm_run_cell_cv visible mid-study)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationResult,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import CELL_CV
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import (
        RunContext,
    )

    CELL_CV.reset()
    config = LlmEnergyConfig(
        models=["qwen2:1.5b"], locations=["on_device"], lengths=[100],
        repetitions=2, backends={"on_device": FakeBackend()},
    )
    for i in range(2):
        run_dir = tmp_path / f"run_{i}"
        run_dir.mkdir()
        ctx = RunContext(
            run_id=f"run_{i}",
            run_nr=i + 1,
            total_runs=2,
            variation={
                "model": "qwen2:1.5b", "location": "on_device", "length": 100,
            },
            run_dir=run_dir,
            experiment_dir=tmp_path,
        )
        request = GenerationRequest("qwen2:1.5b", "t", max_new_tokens=8)
        result = GenerationResult(
            request=request, tokens=[1] * 8, text="x", prompt_tokens=2,
            generated_tokens=8, prefill_s=0.01, decode_s=0.4 + 0.01 * i,
            total_s=0.41 + 0.01 * i,
        )
        ctx.scratch["result"] = result
        ctx.scratch["topic"] = "t"
        ctx.scratch["generation_stats"] = {
            "flops": 1e9, "bytes": 1e8, "vpu_ops": 0.0,
            "duration_s": result.decode_s,
            "generated_tokens": 8,
        }
        row = config.populate_run_data(ctx)
        assert row is not None
    snap = CELL_CV.snapshot()
    key = "energy_J|qwen2:1.5b|100|on_device"
    assert snap[key]["runs"] == 2
    assert snap[key]["cv"] is not None
    assert "wall_s|qwen2:1.5b|100|on_device" in snap


# -- spike detection -----------------------------------------------------------


def test_spike_detector_fires_with_exemplar(obs_on):
    FLIGHT.clear()
    FLIGHT.emit("slice", i=1)
    FLIGHT.emit("slice", i=2)
    det = SpikeDetector("test_stream", multiple=4.0, min_samples=8)
    for _ in range(10):
        assert det.observe(0.010) is False
    assert det.observe(0.100, trace=42) is True
    anomalies = FLIGHT.events(type_=EV_ANOMALY)
    assert len(anomalies) == 1
    a = anomalies[0]
    assert a["kind"] == "step_spike" and a["stream"] == "test_stream"
    assert a["trace"] == 42
    assert a["dur_s"] == pytest.approx(0.1)
    assert a["median_s"] == pytest.approx(0.01)
    # the exemplar carries the recorder's recent context
    assert [e["type"] for e in a["exemplar"]][:2] == ["slice", "slice"]


def test_spike_excluded_from_window(obs_on):
    """A spike must not drag the median up and mask its successors."""
    det = SpikeDetector("s", multiple=4.0, min_samples=4)
    for _ in range(8):
        det.observe(0.010)
    assert det.observe(1.0) is True
    # an identical second spike still fires: the first never entered
    # the window
    assert det.observe(1.0) is True


def test_spike_detector_quiet_before_min_samples(obs_on):
    det = SpikeDetector("s", multiple=4.0, min_samples=8)
    for _ in range(7):
        assert det.observe(0.01) is False
    assert det.observe(5.0) is False  # window not yet armed


def test_spike_detector_noop_when_disabled(obs_off):
    det = SpikeDetector("s", min_samples=1)
    for _ in range(10):
        assert det.observe(0.01) is False
    assert det.observe(100.0) is False
