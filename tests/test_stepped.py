"""Iteration-level (stepped) decode sessions: token parity with solo
generate() — including rows admitted mid-flight — early row retirement,
and in-flight page recycling (engine/stepped.py; the engine half of the
continuous scheduler).

Parity discipline is the PR-1 batch-parity machinery: for a fixed
request set, every row's token stream under the stepped session must be
identical to its solo ``generate()`` stream, whatever the cache layout
(contiguous / paged × bf16 / int8-KV)."""

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)


@pytest.fixture(scope="module")
def registry():
    return {"tiny": get_model_config("qwen2:1.5b").tiny()}


@pytest.fixture(scope="module")
def engine(registry):
    return JaxEngine(registry=dict(registry), dtype=jnp.float32)


def _drain(session, max_steps=8, limit=200):
    """Step the session dry; returns results in retirement order."""
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


def test_stepped_matches_solo_and_retires_early(engine):
    reqs = [
        GenerationRequest("tiny", "first prompt", max_new_tokens=6),
        GenerationRequest(
            "tiny", "second, longer-running row", max_new_tokens=40,
            stop_at_eos=False,
        ),
        GenerationRequest(
            "tiny", "third", max_new_tokens=12, temperature=0.9, seed=5
        ),
    ]
    solo = [engine.generate(r) for r in reqs]
    sess = engine.decode_open(reqs)
    results = {}
    retired_while_running = False
    while sess.active:
        for res in sess.step(8):
            results[id(res.request)] = res
            if sess.active:
                retired_while_running = True
    # short rows retired mid-flight, not at batch end
    assert retired_while_running
    for r, s in zip(reqs, solo):
        got = results[id(r)]
        assert got.tokens == s.tokens
        assert got.text == s.text
        assert got.prompt_tokens == s.prompt_tokens
        assert got.extras["stepped"] is True
        assert got.extras["retire_reason"] in ("eos", "budget")


def test_stepped_join_mid_flight_is_solo_identical(engine):
    long = GenerationRequest(
        "tiny", "anchor runs long", max_new_tokens=48, stop_at_eos=False
    )
    sess = engine.decode_open([long], reserve_rows=4)
    assert sess.free_slots >= 1
    sess.step(4)  # the anchor is mid-flight now
    joiner = GenerationRequest(
        "tiny", "late arrival", max_new_tokens=10, seed=3
    )
    assert sess.can_join(joiner)
    sess.join(joiner)
    assert sess.active == 2
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(long)].tokens == engine.generate(long).tokens
    assert results[id(joiner)].tokens == engine.generate(joiner).tokens


def test_stepped_join_refuses_incompatible(engine, registry):
    sess = engine.decode_open(
        [GenerationRequest("tiny", "anchor", max_new_tokens=8)],
        reserve_rows=4,
    )
    # wrong top_k
    assert not sess.can_join(
        GenerationRequest("tiny", "x", max_new_tokens=4, top_k=7)
    )
    # budget whose generation bucket cannot fit the session cache
    assert not sess.can_join(
        GenerationRequest("tiny", "x", max_new_tokens=200)
    )
    _drain(sess)
    # a drained session has no live rows and still refuses joins once closed
    sess.close()
    assert not sess.can_join(GenerationRequest("tiny", "x", max_new_tokens=4))


def test_stepped_mixed_sampling_knobs_parity(engine):
    reqs = [
        GenerationRequest(
            "tiny", "nucleus row", max_new_tokens=10, temperature=1.0,
            top_p=0.9, seed=1,
        ),
        GenerationRequest(
            "tiny", "penalised row", max_new_tokens=10,
            repeat_penalty=1.5,
        ),
        GenerationRequest("tiny", "plain row", max_new_tokens=10),
    ]
    sess = engine.decode_open(reqs)
    results = {id(r.request): r for r in _drain(sess, max_steps=4)}
    for r in reqs:
        assert results[id(r)].tokens == engine.generate(r).tokens


def test_stepped_budget_one_row_retires_with_prefill_token(engine):
    req = GenerationRequest("tiny", "one token only", max_new_tokens=1)
    sess = engine.decode_open([req])
    results = _drain(sess)
    want = engine.generate(req)
    assert results[0].tokens == want.tokens


def test_stepped_paged_recycles_pages_mid_flight(registry):
    """The acceptance criterion: a retired row's pages return to the pool
    BEFORE the batch's last row finishes — the free-page count recovers
    mid-flight — and its result was handed back while the long row was
    still decoding."""
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    plain = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    reqs = [
        GenerationRequest("tiny", "short", max_new_tokens=6),
        GenerationRequest(
            "tiny", "the long-running companion row", max_new_tokens=100,
            stop_at_eos=False,
        ),
    ]
    sess = paged.decode_open(reqs, reserve_rows=4)
    free0 = sess.pool.free_pages
    results = {}
    recovered_mid_flight = False
    retired_before_end = False
    while sess.active:
        for res in sess.step(8):
            results[id(res.request)] = res
            if sess.active:
                retired_before_end = True
        if sess.active and sess.pool.free_pages > free0:
            recovered_mid_flight = True
    assert recovered_mid_flight
    assert retired_before_end
    for r in reqs:
        assert results[id(r)].tokens == plain.generate(r).tokens


def test_stepped_paged_join_allocates_freed_pages(registry):
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    plain = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    long = GenerationRequest(
        "tiny", "anchor decodes on", max_new_tokens=60, stop_at_eos=False
    )
    sess = paged.decode_open([long], reserve_rows=4)
    sess.step(8)
    joiner = GenerationRequest("tiny", "joins late", max_new_tokens=12, seed=9)
    assert sess.can_join(joiner)
    free_before = sess.pool.free_pages
    sess.join(joiner)
    assert sess.pool.free_pages < free_before  # pages really allocated
    results = {id(r.request): r for r in _drain(sess, max_steps=16)}
    assert results[id(long)].tokens == plain.generate(long).tokens
    assert results[id(joiner)].tokens == plain.generate(joiner).tokens


@pytest.mark.parametrize("paged", [False, True])
def test_stepped_int8_kv_parity_with_join(registry, paged):
    """Stepped sessions compose with the int8 KV cache on both layouts:
    every row (including a mid-flight joiner) matches the same engine's
    solo stream."""
    e8 = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        kv_quantize="int8",
        paged_kv=paged,
    )
    reqs = [
        GenerationRequest("tiny", "alpha", max_new_tokens=8, seed=1),
        GenerationRequest(
            "tiny", "beta beta", max_new_tokens=24, temperature=1.1, seed=2,
            stop_at_eos=False,
        ),
    ]
    sess = e8.decode_open(reqs, reserve_rows=4)
    sess.step(4)
    joiner = GenerationRequest("tiny", "gamma joins", max_new_tokens=10, seed=3)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    for r in reqs + [joiner]:
        assert results[id(r)].tokens == e8.generate(r).tokens


def test_stepped_close_frees_pages(registry):
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", "row a", max_new_tokens=40),
        GenerationRequest("tiny", "row b", max_new_tokens=40),
    ]
    sess = paged.decode_open(reqs)
    total = sess.pool.n_pages
    held = total - sess.pool.free_pages
    assert held > 1  # rows + the parking page
    sess.close()
    assert sess.pool.free_pages == total - 1  # only parking stays held
    with pytest.raises(RuntimeError, match="closed"):
        sess.step()


def _layout_engine(registry, paged, kv):
    return JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=paged,
        kv_quantize=kv,
    )


@pytest.mark.parametrize(
    "paged,kv",
    [(False, None), (False, "int8"), (True, None), (True, "int8")],
    ids=["contig-bf16", "contig-int8", "paged-bf16", "paged-int8"],
)
def test_chunked_join_parity_all_layouts(registry, paged, kv):
    """The ISSUE-4 tentpole invariant: a joiner whose prefill streams in
    as MULTIPLE token-budgeted chunks — interleaved with decode slices
    the companion keeps generating through — produces a stream
    bit-identical to its solo generate(), and so does the companion that
    decoded across the whole chunked join. All four cache layouts."""
    eng = _layout_engine(registry, paged, kv)
    anchor = GenerationRequest(
        "tiny", "a" * 120, max_new_tokens=40, stop_at_eos=False, seed=1
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    sess.step(4)  # the anchor is mid-flight
    joiner = GenerationRequest(
        "tiny", "j" * 100, max_new_tokens=12, seed=3
    )
    assert sess.can_join(joiner)
    pj = sess.join_begin(joiner, chunk_tokens=32)
    assert pj.total_chunks >= 3  # 101 prompt ids at 32-token chunks
    assert sess.free_slots == sess.b_bucket - 2  # slot reserved
    done = False
    while not done:
        done = sess.join_step(pj)
        if not done:
            # the companion keeps decoding BETWEEN prefill chunks —
            # exactly the scheduler's interleave
            sess.step(2)
    assert sess.active == 1  # joiner not live until commit
    sess.join_commit(pj)
    assert sess.active == 2
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(anchor)].tokens == eng.generate(anchor).tokens
    assert results[id(joiner)].tokens == eng.generate(joiner).tokens


def test_chunked_join_single_chunk_matches_sync_join(engine):
    """A short-prompt joiner through the chunked protocol is the
    one-shot join (the sync path is implemented over it)."""
    anchor = GenerationRequest(
        "tiny", "anchor stays", max_new_tokens=32, stop_at_eos=False
    )
    sess = engine.decode_open([anchor], reserve_rows=4)
    sess.step(4)
    joiner = GenerationRequest("tiny", "quick", max_new_tokens=8, seed=5)
    pj = sess.join_begin(joiner)
    assert pj.total_chunks == 1
    assert sess.join_step(pj)
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(joiner)].tokens == engine.generate(joiner).tokens


def test_join_abort_releases_slot_and_pages(registry):
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    anchor = GenerationRequest(
        "tiny", "anchor", max_new_tokens=40, stop_at_eos=False
    )
    sess = paged.decode_open([anchor], reserve_rows=4)
    free0 = sess.pool.free_pages
    slots0 = sess.free_slots
    pj = sess.join_begin(
        GenerationRequest("tiny", "j" * 80, max_new_tokens=8), chunk_tokens=32
    )
    assert sess.pool.free_pages < free0  # pages reserved at begin
    assert sess.free_slots == slots0 - 1
    sess.join_step(pj)
    sess.join_abort(pj)
    assert sess.pool.free_pages == free0
    assert sess.free_slots == slots0
    sess.close()


def test_can_join_rejects_prompt_over_session_bucket(engine):
    """A prompt whose bucketed alloc + generation bucket exceeds the
    session's cache must be refused BEFORE any prefill is paid (it would
    overflow the contiguous row cache)."""
    sess = engine.decode_open(
        [GenerationRequest("tiny", "tiny anchor", max_new_tokens=16)],
        reserve_rows=4,
    )
    # session cache: prompt bucket 32 + gen bucket 16 = 48 slots
    assert sess.cache_len == 48
    long_prompt = GenerationRequest("tiny", "x" * 100, max_new_tokens=8)
    assert not sess.can_join(long_prompt)
    with pytest.raises(RuntimeError, match="cannot join"):
        sess.join_begin(long_prompt)
    _drain(sess)


def test_can_join_rejects_when_pool_drained(registry):
    """Paged admission probe: a joiner whose pages don't fit the pool's
    free list right now is deferred, not failed."""
    paged = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    sess = paged.decode_open(
        [GenerationRequest(
            "tiny", "anchor", max_new_tokens=24, stop_at_eos=False
        )],
        reserve_rows=4,
    )
    joiner = GenerationRequest("tiny", "late", max_new_tokens=8)
    assert sess.can_join(joiner)
    hog = sess.pool.alloc(sess.pool.free_pages)  # drain the free list
    assert not sess.can_join(joiner)
    sess.pool.free(hog)
    assert sess.can_join(joiner)
    sess.close()


def test_stepped_validates_inputs(engine):
    with pytest.raises(ValueError, match="one model"):
        engine.decode_open(
            [
                GenerationRequest("tiny", "x", max_new_tokens=4),
                GenerationRequest("other", "y", max_new_tokens=4),
            ]
        )
    with pytest.raises(ValueError, match="at least one"):
        engine.decode_open([])
