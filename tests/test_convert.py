"""HF-checkpoint conversion: logit-level parity with ``transformers``.

The strongest correctness evidence for the model implementations: for each
reference family, a randomly-initialised HuggingFace model's logits must
match our transformer's logits on the converted weights (both float32).
The reference itself never validates model outputs (generation is Ollama's
problem, experiment/RunnerConfig.py:128-131); here it is a test invariant.
"""

import dataclasses

import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.convert import (
    convert_hf_state_dict,
    family_of,
    hf_config_for,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def tiny_cfg(registry_name: str, **overrides):
    """Structure-preserving miniature with d_model == n_heads · d_head so
    every HF family accepts it (phi3 derives head_dim from the quotient)."""
    base = get_model_config(registry_name)
    defaults = dict(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_head=16,
        d_ff=96,
        max_seq_len=128,
    )
    defaults.update(overrides)
    return dataclasses.replace(base, **defaults)


FAMILIES = [
    tiny_cfg("llama3.1:8b"),
    tiny_cfg("mistral:7b"),
    tiny_cfg("qwen2:1.5b"),  # qkv_bias + tied embeddings
    tiny_cfg("gemma:2b", n_kv_heads=1),  # gelu + (1+w) norm + embed scaling
    tiny_cfg("phi3:3.8b", n_kv_heads=4),  # fused qkv_proj / gate_up_proj
    tiny_cfg("mixtral:8x7b", n_experts=4),  # block-sparse MoE + top-2 router
]


def hf_model_for(cfg):
    hf_cfg = hf_config_for(cfg)
    model = transformers.AutoModelForCausalLM.from_config(
        hf_cfg, attn_implementation="eager"
    )
    model.eval()
    return model


@pytest.mark.parametrize("cfg", FAMILIES, ids=[family_of(c) for c in FAMILIES])
def test_logits_match_hf(cfg):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        forward,
        logits_for,
    )

    torch.manual_seed(0)
    model = hf_model_for(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, size=(2, 9))

    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens)).logits.numpy()

    params = convert_hf_state_dict(model.state_dict(), cfg, dtype=jnp.float32)
    shape = (cfg.n_layers, 2, cfg.n_kv_heads, 16, cfg.d_head)
    k_cache = jnp.zeros(shape, dtype=jnp.float32)
    v_cache = jnp.zeros(shape, dtype=jnp.float32)
    hidden, _, _ = forward(
        params, cfg, jnp.asarray(tokens, dtype=jnp.int32), jnp.int32(0),
        k_cache, v_cache,
    )
    ours = np.asarray(logits_for(params, cfg, hidden))

    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-4)


def test_phi3_fused_split_matches_unfused_shapes():
    cfg = tiny_cfg("phi3:3.8b", n_kv_heads=4)
    model = hf_model_for(cfg)
    sd = model.state_dict()
    assert "model.layers.0.self_attn.qkv_proj.weight" in sd
    params = convert_hf_state_dict(sd, cfg)
    assert params["wq"].shape == (2, 64, 64)
    assert params["wk"].shape == (2, 64, 64)
    assert params["w_gate"].shape == (2, 64, 96)
    assert params["w_up"].shape == (2, 64, 96)


def test_missing_key_reports_model_and_key():
    cfg = tiny_cfg("llama3.1:8b")
    with pytest.raises(KeyError, match="embed_tokens"):
        convert_hf_state_dict({}, cfg)


def test_engine_serves_converted_checkpoint(tmp_path):
    """JaxEngine loads a local HF checkpoint dir instead of random weights."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationRequest,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    cfg = tiny_cfg("mistral:7b")
    model = hf_model_for(cfg)
    ckpt_dir = tmp_path / "ckpt"
    model.save_pretrained(ckpt_dir)

    engine = JaxEngine(
        registry={cfg.name: cfg},
        dtype=jnp.float32,
        hf_checkpoints={cfg.name: str(ckpt_dir)},
    )
    result = engine.generate(GenerationRequest(cfg.name, "hello", max_new_tokens=4))
    assert result.generated_tokens >= 1
    # The loaded params are the converted checkpoint, not a random init
    expected = convert_hf_state_dict(model.state_dict(), cfg, dtype=jnp.float32)
    loaded = engine._models[cfg.name].params
    np.testing.assert_array_equal(
        np.asarray(loaded["embed"]), np.asarray(expected["embed"])
    )


def test_registry_configs_all_map_to_hf():
    """Every entry in the 7-model sweep has a valid HF config mapping with
    consistent dimensions (guards registry hyperparameter typos)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        MODEL_REGISTRY,
    )

    for cfg in MODEL_REGISTRY.values():
        hf_cfg = hf_config_for(cfg)
        assert hf_cfg.hidden_size == cfg.d_model
        assert hf_cfg.num_attention_heads == cfg.n_heads
        assert getattr(hf_cfg, "head_dim", cfg.d_head) == cfg.d_head
