"""Mesh/sharding/TP/ring/train on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import JaxEngine
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.attention import (
    prefill_attention,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.ring import (
    make_ring_attention,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.sharding import (
    param_specs,
    shard_model,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
    TensorParallelEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.train import (
    make_train_step,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def test_mesh_spec_resolution():
    assert MeshSpec.tp_only().resolve(8) == {"tp": 8}
    assert MeshSpec.dp_tp(2, 4).resolve(8) == {"dp": 2, "tp": 4}
    assert MeshSpec.dp_tp(2, -1).resolve(8) == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        MeshSpec.dp_tp(3, 4).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(axes=(("dp", -1), ("tp", -1))).resolve(8)


def test_build_mesh_shape():
    mesh = build_mesh(MeshSpec.dp_tp(2, 4))
    assert mesh.shape == {"dp": 2, "tp": 4}


def _tiny8():
    """A tiny config whose head/ff dims divide tp=8."""
    import dataclasses

    return dataclasses.replace(
        get_model_config("mistral:7b").tiny(),
        n_heads=8,
        n_kv_heads=8,
        d_ff=128,
        d_model=64,
        d_head=16,
    )


def test_param_specs_follow_divisibility():
    cfg = _tiny8()
    mesh = build_mesh(MeshSpec.tp_only())
    specs = param_specs(cfg, mesh)
    assert specs["wq"] == jax.sharding.PartitionSpec(None, None, "tp")
    assert specs["wo"] == jax.sharding.PartitionSpec(None, "tp", None)
    assert specs["attn_norm"] == jax.sharding.PartitionSpec()
    # vocab 512 divides 8 → embed sharded
    assert specs["embed"] == jax.sharding.PartitionSpec("tp", None)


def test_shard_model_places_leaves():
    cfg = _tiny8()
    mesh = build_mesh(MeshSpec.tp_only())
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    sharded = shard_model(tf.params, cfg, mesh)
    wq = sharded["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, None, "tp")
    # one shard holds 1/8 of the head dim
    shard = wq.addressable_shards[0]
    assert shard.data.shape[-1] == wq.shape[-1] // 8


def test_tp_engine_matches_single_device_greedy():
    """The golden TP test: 8-way tensor-parallel decode must produce the
    same greedy tokens as the single-device engine."""
    cfg = _tiny8()
    registry = {"tiny8": cfg}
    single = JaxEngine(registry=registry, dtype=jnp.float32)
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()), registry=registry, dtype=jnp.float32
    )
    req = GenerationRequest(model="tiny8", prompt="tensor parallel", max_new_tokens=12)
    r_single = single.generate(req)
    r_tp = tp.generate(req)
    assert r_single.tokens == r_tp.tokens


def test_tp_generate_batch_matches_single_requests():
    """The TP engine's batched decode (VERDICT round-2 item 5: previously
    untested) — every row token-identical to its own TP generate()."""
    cfg = _tiny8()
    registry = {"tiny8": cfg}
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()), registry=registry, dtype=jnp.float32
    )
    reqs = [
        GenerationRequest("tiny8", "first sharded row", max_new_tokens=10),
        GenerationRequest("tiny8", "second row differs", max_new_tokens=12),
        GenerationRequest("tiny8", "third", max_new_tokens=6),
    ]
    batch = tp.generate_batch(reqs)
    for r, req in zip(batch, reqs):
        assert r.tokens == tp.generate(req).tokens


def test_tp_generate_stream_matches_monolithic():
    cfg = _tiny8()
    registry = {"tiny8": cfg}
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()), registry=registry, dtype=jnp.float32
    )
    req = GenerationRequest("tiny8", "streamed over the mesh", max_new_tokens=12)
    mono = tp.generate(req)
    chunks = list(tp.generate_stream(req, chunk_tokens=4))
    streamed = [t for c in chunks[:-1] for t in c.tokens]
    assert streamed == mono.tokens
    assert chunks[-1].result.tokens == mono.tokens


def test_tp_speculative_matches_plain_greedy():
    """Speculative decoding on the sharded engine: draft+target both live
    on the mesh; accepted tokens must equal TP plain greedy."""
    import dataclasses

    cfg = _tiny8()
    draft_cfg = dataclasses.replace(cfg, n_layers=1)
    registry = {"tiny8": cfg, "draft8": draft_cfg}
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only()), registry=registry, dtype=jnp.float32
    )
    req = GenerationRequest("tiny8", "speculate on the mesh", max_new_tokens=16)
    plain = tp.generate(req)
    spec = tp.generate_speculative(req, "draft8", k=4)
    assert spec.tokens == plain.tokens
    assert spec.extras is not None and spec.extras["spec_rounds"] >= 1


def test_ring_attention_matches_reference():
    mesh = build_mesh(MeshSpec(axes=(("sp", 8),)))
    b, s, hq, hkv, d = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype=jnp.float32)
    ref = prefill_attention(q, k, v, causal=True)
    ring = make_ring_attention(mesh)
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_train_step_dp_tp_runs_and_learns():
    cfg = _tiny8()
    mesh = build_mesh(MeshSpec.dp_tp(2, 4))
    tf = Transformer.initialise(cfg, seed=0, dtype=jnp.float32)
    init_fn, step = make_train_step(cfg, mesh, learning_rate=1e-2, remat=True)
    params, opt_state = init_fn(tf.params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    # memorising a fixed batch: loss must drop
    assert losses[-1] < losses[0]


# -- TP decode-time roofline (the remote treatment's duration model) ---------


def test_roofline_single_chip_matches_measured():
    """n=1 (no ICI term) must reproduce the measured single-chip decode:
    qwen2:1.5b int8 runs 3.0-3.07 ms/step on the real chip
    (docs/PERF.md component ablation). The model's only inputs are the
    bytes accounting and the calibrated ~490 GB/s sustained stream, so
    landing within ~7% validates both."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
        modeled_tp_decode_step_s,
    )

    cfg = get_model_config("qwen2:1.5b")
    t = modeled_tp_decode_step_s(cfg, "int8", 1, 320)
    assert 0.00293 * 0.95 < t < 0.00307 * 1.07


def test_roofline_tp_mesh_is_faster_but_sublinear():
    """The mesh must be FASTER than one chip (the reference's remote
    machine is faster, BASELINE.md:27-32) but SUBLINEAR: per-layer psums
    sit on the ICI latency floor, so a small model cannot speed up 8×."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
        modeled_tp_decode_step_s,
    )

    small = get_model_config("qwen2:1.5b")
    big = get_model_config("llama3.1:8b")
    for cfg in (small, big):
        t1 = modeled_tp_decode_step_s(cfg, "int8", 1, 320)
        t8 = modeled_tp_decode_step_s(cfg, "int8", 8, 320)
        assert t8 < t1
        assert t1 / t8 < 8.0
    # the bigger model amortises the latency floor better: its speedup
    # must exceed the small model's
    s_small = modeled_tp_decode_step_s(
        small, "int8", 1, 320
    ) / modeled_tp_decode_step_s(small, "int8", 8, 320)
    s_big = modeled_tp_decode_step_s(
        big, "int8", 1, 320
    ) / modeled_tp_decode_step_s(big, "int8", 8, 320)
    assert s_big > s_small


def test_roofline_kv_replication_rule_follows_sharding():
    """sharding.py replicates the KV cache when n_kv_heads % tp != 0
    (qwen2's 2 KV heads on tp=8); replicated cache bytes must NOT shrink
    with the mesh. phi3's 32 heads shard cleanly — its long-context KV
    stream does shrink, so its TP speedup at 2k context beats qwen2-like
    replication."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
        modeled_tp_decode_step_s,
    )

    phi3 = get_model_config("phi3:3.8b")  # 32 % 8 == 0 → sharded
    assert phi3.n_kv_heads % 8 == 0
    t1 = modeled_tp_decode_step_s(phi3, "int8", 8, 2048)
    # force the replicated branch by comparing against a 3-chip mesh
    # (32 % 3 != 0): KV replicated, weights still sharded
    t3 = modeled_tp_decode_step_s(phi3, "int8", 3, 2048)
    kv_bytes = 2 * 32 * 32 * 96 * 2048 * 2
    # the 8-way mesh keeps only 1/8 of the KV stream per chip; the 3-way
    # mesh pays it in full — check the modelled per-chip KV cost gap
    # is visible in the step times (t3's mem term carries full KV)
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
        V5E_SUSTAINED_HBM_GBPS,
    )

    bw = V5E_SUSTAINED_HBM_GBPS * 1e9
    assert t3 > kv_bytes / bw  # full KV alone bounds the 3-chip step
    assert t1 < t3


def test_roofline_whole_generation_uses_mid_context():
    """The closed-form loop sum: N steps at the mid-loop context equal
    the linear model's exact sum."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.roofline import (
        modeled_tp_decode_s,
        modeled_tp_decode_step_s,
    )

    cfg = get_model_config("qwen2:1.5b")
    total = modeled_tp_decode_s(cfg, "int8", 8, 64, 256)
    per_mid = modeled_tp_decode_step_s(cfg, "int8", 8, 64 + 128)
    assert total == pytest.approx(256 * per_mid)
    assert modeled_tp_decode_s(cfg, "int8", 8, 64, 0) == 0.0


def test_tp_stacked_paged_parts_kernel_parity():
    """VERDICT round-5 directive #5: TP serving × paged pool must compose
    through the PARTS kernel (shard_map, heads sharded over tp), not the
    measured-worst gather fallback — with every row token-identical to
    the single-device paged engine."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
    )

    cfg = _tiny8()
    registry = {"tiny8": cfg}
    mesh = build_mesh(MeshSpec.tp_only())  # tp=8 over the virtual devices
    tp_paged = TensorParallelEngine(
        mesh=mesh,
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
        decode_attention=pallas_decode_attention,  # force kernels on CPU
    )
    # the partition rule must engage: heads (8) divide tp (8)
    assert tp_paged._paged_decode_attention(cfg) is not None
    # ... and must NOT engage for a model whose heads don't divide
    import dataclasses

    odd = dataclasses.replace(cfg, n_kv_heads=2, n_heads=2)
    assert tp_paged._paged_decode_attention(odd) is None

    single_paged = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
        decode_attention=pallas_decode_attention,
    )
    assert single_paged._paged_decode_attention(cfg) is not None

    reqs = [
        GenerationRequest("tiny8", "stacked parts row one", max_new_tokens=8),
        GenerationRequest(
            "tiny8",
            "a somewhat longer second prompt for the paged pool",
            max_new_tokens=14,
        ),
        GenerationRequest(
            "tiny8", "sampled third row", max_new_tokens=10,
            temperature=0.8, seed=7,
        ),
    ]
    want = single_paged.generate_batch(reqs)
    got = tp_paged.generate_batch(reqs)
    for g, w in zip(got, want):
        assert g.tokens == w.tokens
        assert g.text == w.text


def test_roofline_terms_match_aot_lowering():
    """VERDICT round-5 directive #7: the roofline's structural terms must
    match the SPMD partitioner's actual output. Fast pin of the full
    sweep in scripts/roofline_aot_check.py (committed artifact:
    docs/roofline_aot.json): per-layer all-reduces == 2, entry == 1
    all-reduce + 2 gathers (sharded KV) / 6 (replicated), KV-sharded
    body gather-free, replicated body carries the cache-slice gather."""
    import dataclasses
    import importlib.util
    from pathlib import Path

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
        Transformer,
        forward,
        logits_for,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.sharding import (
        cache_shardings,
        param_specs,
    )

    spec = importlib.util.spec_from_file_location(
        "roofline_aot_check",
        Path(__file__).parent.parent / "scripts" / "roofline_aot_check.py",
    )
    aot = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(aot)

    cfg = dataclasses.replace(
        get_model_config("qwen2:1.5b").tiny(), n_kv_heads=2, n_heads=4
    )
    cache_len = 64
    for tp, kv_sharded in ((2, True), (4, False)):
        mesh = build_mesh(
            MeshSpec.tp_only(tp), jax.devices()[:tp]
        )
        specs = param_specs(cfg, mesh)
        shapes = jax.eval_shape(
            lambda: Transformer.initialise(
                cfg, seed=0, dtype=jnp.float32
            ).params
        )
        pshard = {
            k: jax.sharding.NamedSharding(
                mesh, specs.get(k, jax.sharding.PartitionSpec())
            )
            for k in shapes
        }
        cache = jax.ShapeDtypeStruct(
            (cfg.n_layers, 1, cfg.n_kv_heads, cache_len, cfg.d_head),
            jnp.float32,
        )
        repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

        def step(params, tokens, offset, kc, vc):
            h, kc, vc = forward(params, cfg, tokens, offset, kc, vc, None)
            return jnp.argmax(logits_for(params, cfg, h[:, -1]), -1), kc, vc

        hlo = (
            jax.jit(
                step,
                in_shardings=(
                    pshard, repl, repl,
                    cache_shardings(cfg, mesh), cache_shardings(cfg, mesh),
                ),
            )
            .lower(
                shapes,
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                cache,
                cache,
            )
            .compile()
            .as_text()
        )
        parts = aot.analyze_lowering(hlo)
        assert parts["body"]["all-reduce"] == 2, (tp, parts)
        assert parts["outside"]["all-reduce"] == 1, (tp, parts)
        if kv_sharded:
            assert parts["body"]["all-gather"] == 0, parts
            assert parts["outside"]["all-gather"] == 2, parts
        else:
            assert parts["outside"]["all-gather"] == 6, parts
            # the replicated regime's dominant extra: a cache-slice gather
            assert any(
                f"{cache_len},{cfg.d_head}]" in s
                for s in parts["body_gather_shapes"]
            ), parts
