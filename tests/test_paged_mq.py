"""Multi-query paged parts kernels (ISSUE 10).

The acceptance matrix, pinned in interpret mode so CPU CI holds parity
without a chip: per-layer / stacked-``layer`` pools × bf16 / int8 ×
q ∈ {1, k+1}, against the gather-then-attend multi-query reference
(`paged_mq_attention_reference`) — and the q = 1 reduction, where the
multi-query kernels must reproduce the existing single-query parts
kernels bit-for-bit (same grid, same accumulation body, the limit
column collapsing to the scalar length).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
    paged_mq_attention_reference,
    pallas_paged_decode_attention_mq_parts,
    pallas_paged_decode_attention_mq_parts_int8,
    pallas_paged_decode_attention_parts,
    pallas_paged_decode_attention_parts_int8,
)

B, HQ, HKV, D, PAGE, JMAX, POOL = 3, 8, 2, 128, 8, 4, 16


def _setup(seed=0, dp=D):
    q1 = jax.random.normal(jax.random.PRNGKey(seed), (B, 5, HQ, D))
    kp = jax.random.normal(jax.random.PRNGKey(seed + 1), (POOL, HKV, PAGE, dp))
    vp = jax.random.normal(jax.random.PRNGKey(seed + 2), (POOL, HKV, PAGE, dp))
    # scattered page permutation — the indirection the kernels exist for
    table = jax.random.permutation(jax.random.PRNGKey(seed + 3), jnp.arange(POOL))
    table = table[: B * JMAX].reshape(B, JMAX)
    lengths = jnp.asarray([5, 17, 30], jnp.int32)
    # offsets straddle the cached lengths so the per-query causal cut
    # actually bites (kpos <= offsets+j < lengths for some (b, j))
    offsets = jnp.asarray([2, 17, 33], jnp.int32)
    return q1, kp, vp, table, lengths, offsets


def _quant(pool):
    s = jnp.maximum(jnp.max(jnp.abs(pool), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(pool / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


@pytest.mark.parametrize("qlen", [1, 5])
def test_mq_parts_matches_reference(qlen):
    q, kp, vp, table, lengths, offsets = _setup()
    acc, m, l = pallas_paged_decode_attention_mq_parts(
        q[:, :qlen], kp, vp, table, lengths, offsets, interpret=True
    )
    ra, rm, rl = paged_mq_attention_reference(
        q[:, :qlen], kp, vp, table, lengths, offsets
    )
    assert np.allclose(acc, ra, atol=1e-4)
    assert np.allclose(m, rm, atol=1e-5)
    assert np.allclose(l, rl, atol=1e-4)


@pytest.mark.parametrize("qlen", [1, 5])
def test_mq_parts_int8_matches_dequantized_reference(qlen):
    q, kp, vp, table, lengths, offsets = _setup(seed=7)
    kq, ks = _quant(kp)
    vq, vs = _quant(vp)
    acc, m, l = pallas_paged_decode_attention_mq_parts_int8(
        q[:, :qlen], kq, ks, vq, vs, table, lengths, offsets,
        interpret=True,
    )
    ra, rm, rl = paged_mq_attention_reference(
        q[:, :qlen],
        kq.astype(jnp.float32) * ks[..., None],
        vq.astype(jnp.float32) * vs[..., None],
        table, lengths, offsets,
    )
    assert np.allclose(acc, ra, atol=1e-3)
    assert np.allclose(m, rm, atol=1e-4)
    assert np.allclose(l, rl, atol=1e-3)


def test_mq_q1_reduces_to_single_query_parts_kernel():
    """The acceptance criterion directly: at q = 1 with the causal cut
    past the cached length (the stacked-verify regime), the MQ kernel
    IS the existing parts kernel."""
    q, kp, vp, table, lengths, _ = _setup(seed=3)
    off = lengths + 4  # every cached token visible — the q=1 decode mask
    a1, m1, l1 = pallas_paged_decode_attention_mq_parts(
        q[:, :1], kp, vp, table, lengths, off, interpret=True
    )
    a0, m0, l0 = pallas_paged_decode_attention_parts(
        q[:, 0], kp, vp, table, lengths, interpret=True
    )
    assert np.array_equal(np.asarray(a1[:, 0]), np.asarray(a0))
    assert np.array_equal(np.asarray(m1[:, 0]), np.asarray(m0))
    assert np.array_equal(np.asarray(l1[:, 0]), np.asarray(l0))
    kq, ks = _quant(kp)
    vq, vs = _quant(vp)
    a1, m1, l1 = pallas_paged_decode_attention_mq_parts_int8(
        q[:, :1], kq, ks, vq, vs, table, lengths, off, interpret=True
    )
    a0, m0, l0 = pallas_paged_decode_attention_parts_int8(
        q[:, 0], kq, ks, vq, vs, table, lengths, interpret=True
    )
    assert np.array_equal(np.asarray(a1[:, 0]), np.asarray(a0))
    assert np.array_equal(np.asarray(m1[:, 0]), np.asarray(m0))
    assert np.array_equal(np.asarray(l1[:, 0]), np.asarray(l0))


@pytest.mark.parametrize("int8", [False, True])
def test_mq_stacked_layer_form_matches_per_layer(int8):
    """The whole-stacked-pool ``layer=`` flavor folds the layer into
    the DMA offset — same parts as slicing the layer out first."""
    L = 3
    q, _, _, table, lengths, offsets = _setup(seed=5)
    kp = jax.random.normal(jax.random.PRNGKey(11), (L, POOL, HKV, PAGE, D))
    vp = jax.random.normal(jax.random.PRNGKey(12), (L, POOL, HKV, PAGE, D))
    for layer in (0, 2):
        if int8:
            kq, ks = _quant(kp)
            vq, vs = _quant(vp)
            a_st, m_st, l_st = pallas_paged_decode_attention_mq_parts_int8(
                q, kq, ks, vq, vs, table, lengths, offsets,
                layer=jnp.int32(layer), interpret=True,
            )
            a_pl, m_pl, l_pl = pallas_paged_decode_attention_mq_parts_int8(
                q, kq[layer], ks[layer], vq[layer], vs[layer],
                table, lengths, offsets, interpret=True,
            )
        else:
            a_st, m_st, l_st = pallas_paged_decode_attention_mq_parts(
                q, kp, vp, table, lengths, offsets,
                layer=jnp.int32(layer), interpret=True,
            )
            a_pl, m_pl, l_pl = pallas_paged_decode_attention_mq_parts(
                q, kp[layer], vp[layer], table, lengths, offsets,
                interpret=True,
            )
        assert np.array_equal(np.asarray(a_st), np.asarray(a_pl))
        assert np.array_equal(np.asarray(m_st), np.asarray(m_pl))
        assert np.array_equal(np.asarray(l_st), np.asarray(l_pl))


def test_mq_parts_rejects_unpadded_head_dim():
    q, _, _, table, lengths, offsets = _setup()
    pool = jnp.zeros((POOL, HKV, PAGE, 96))  # 96 % 128 != 0
    with pytest.raises(ValueError, match="pre-padded"):
        pallas_paged_decode_attention_mq_parts(
            q[..., :96], pool, pool, table, lengths, offsets,
            interpret=True,
        )
