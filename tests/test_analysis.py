"""Statistics primitives and the analysis pipeline."""

import math

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.pipeline import (
    analyze,
    analyze_experiment,
    apply_iqr_filter,
    render_markdown,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.stats import (
    cliffs_delta,
    descriptives,
    iqr_mask,
    significance_stars,
    spearman,
    wilcoxon_rank_sum,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import (
    RunTableStore,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress

scipy = pytest.importorskip("scipy")


def test_iqr_mask_flags_outliers():
    values = [10.0] * 20 + [1000.0]
    mask = iqr_mask(values)
    assert mask[:-1].all() and not mask[-1]


def test_descriptives():
    d = descriptives([1.0, 2.0, 3.0, 4.0, None])
    assert d.n == 4 and d.mean == 2.5 and d.median == 2.5
    assert d.minimum == 1.0 and d.maximum == 4.0
    empty = descriptives([])
    assert empty.n == 0 and math.isnan(empty.mean)


def test_cliffs_delta_extremes_and_labels():
    delta, mag = cliffs_delta([10, 11, 12], [1, 2, 3])
    assert delta == 1.0 and mag == "large"
    delta, mag = cliffs_delta([1, 2, 3], [10, 11, 12])
    assert delta == -1.0 and mag == "large"
    delta, mag = cliffs_delta([1, 2, 3, 4], [1, 2, 3, 4])
    assert delta == 0.0 and mag == "negligible"


def test_cliffs_delta_matches_bruteforce():
    import random

    rng = random.Random(0)
    a = [rng.gauss(0, 1) for _ in range(40)]
    b = [rng.gauss(0.5, 1) for _ in range(30)]
    delta, _ = cliffs_delta(a, b)
    brute = sum(
        (1 if x > y else -1 if x < y else 0) for x in a for y in b
    ) / (len(a) * len(b))
    assert delta == pytest.approx(brute, abs=1e-12)


def test_wilcoxon_detects_shift():
    a = [i + 100 for i in range(30)]
    b = list(range(30))
    _, p = wilcoxon_rank_sum(a, b)
    assert p < 1e-6


def test_spearman_monotone():
    xs = list(range(20))
    ys = [x**2 for x in xs]
    rho, p = spearman(xs, ys)
    assert rho == pytest.approx(1.0)
    assert p < 1e-6
    rho, _ = spearman([1, None, 3], [1, 2, None])
    assert math.isnan(rho)


def test_significance_stars():
    assert significance_stars(0.0001) == "***"
    assert significance_stars(0.004) == "**"
    assert significance_stars(0.04) == "*"
    assert significance_stars(0.5) == ""


def _synthetic_rows(n_per_cell=20):
    import random

    # Cell means stay within one global IQR fence of each other (the pipeline
    # filters per metric over the whole table, like notebook cell 11).
    rng = random.Random(7)
    rows = []
    i = 0
    for location, base in (("on_device", 100.0), ("remote", 50.0)):
        for length in (100, 200):
            for _ in range(n_per_cell):
                energy = base * (length / 100) * rng.uniform(0.9, 1.1)
                rows.append(
                    {
                        "__run_id": f"run_{i}_repetition_0",
                        "__done": RunProgress.DONE,
                        "model": "m",
                        "location": location,
                        "length": length,
                        "energy_J": round(energy, 3),
                        "execution_time_s": round(energy / 10, 3),
                        "cpu_usage": rng.uniform(1, 5),
                        "memory_usage": 50.0,
                        "tokens_per_s": 100.0,
                    }
                )
                i += 1
    return rows


def test_analyze_h1_recovers_energy_ratio():
    rows = _synthetic_rows()
    report = analyze(rows)
    h1 = report["h1_energy_by_length"]
    assert set(h1) == {"100", "200"}
    for h in h1.values():
        assert h["p"] < 1e-4
        assert h["magnitude"] == "large"
        assert h["mean_ratio"] == pytest.approx(2.0, rel=0.1)
    # energy correlates with exec time perfectly (it's energy/10)
    assert report["h2_spearman"]["on_device"]["execution_time_s"]["rho"] == pytest.approx(1.0)


def test_apply_iqr_filter_drops_rows():
    rows = _synthetic_rows(n_per_cell=10)
    rows[0]["energy_J"] = 1e9
    filtered = apply_iqr_filter(rows, ["energy_J"])
    assert len(filtered) == len(rows) - 1


def test_apply_iqr_filter_keeps_rows_with_missing_values():
    rows = _synthetic_rows(n_per_cell=10)
    rows[3]["energy_J"] = None  # missing ≠ outlier
    filtered = apply_iqr_filter(rows, ["energy_J"])
    assert len(filtered) == len(rows)


def test_analyze_experiment_writes_reports(tmp_path):
    rows = _synthetic_rows(n_per_cell=8)
    store = RunTableStore(tmp_path)
    store.write(rows)
    report = analyze_experiment(tmp_path)
    assert (tmp_path / "analysis_report.json").exists()
    md = (tmp_path / "analysis_report.md").read_text()
    assert "H1: energy" in md and "Spearman" in md
    assert report["n_rows"] == len(rows)


def test_render_markdown_handles_empty_subsets():
    report = analyze(_synthetic_rows(n_per_cell=5))
    md = render_markdown(report)
    assert md.startswith("# Experiment analysis")


def test_paper_reproduction_matches_survey_baseline():
    """Feed the reference's shipped 1,260-run table (pure input data)
    through our stats pipeline: the descriptives must match SURVEY.md §6's
    recomputed baseline to the decimal, and the hypothesis tests must
    reproduce the paper's findings."""
    from pathlib import Path

    ref_csv = Path("/root/reference/data-analysis/run_table.csv")
    if not ref_csv.exists():
        pytest.skip("reference data not mounted")
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "repro", Path(__file__).parent.parent / "examples" / "reproduce_paper_analysis.py"
    )
    repro = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repro)

    rows = repro.load(ref_csv)
    clean = repro.iqr_filter_per_group(rows)
    assert len(rows) == 1260  # data rows (header consumed by DictReader)

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.stats import (
        cliffs_delta,
        descriptives,
        wilcoxon_rank_sum,
    )

    def vals(method, length):
        return [
            r["energy_usage_J"]
            for r in clean
            if r["method"] == method and r["length"] == length
        ]

    # SURVEY.md §6 baseline table values (mean/median/sd, n)
    d = descriptives(vals("on_device", 100))
    assert (round(d.mean, 1), round(d.median, 1), d.n) == (52.8, 55.0, 167)
    d = descriptives(vals("on_device", 1000))
    assert (round(d.mean, 1), round(d.median, 1), d.n) == (432.0, 462.5, 191)

    # H1: strongly significant, large effect, on-device higher
    for length in (100, 500, 1000):
        _, p = wilcoxon_rank_sum(vals("on_device", length), vals("remote", length))
        delta, label = cliffs_delta(vals("on_device", length), vals("remote", length))
        assert p < 1e-40 and label == "large" and delta > 0.9

    # headline ratio envelope: ~3.5x short, ~9x long
    ratio_short = descriptives(vals("on_device", 100)).mean / descriptives(
        vals("remote", 100)
    ).mean
    ratio_long = descriptives(vals("on_device", 1000)).mean / descriptives(
        vals("remote", 1000)
    ).mean
    assert 3.0 < ratio_short < 4.0
    assert 8.0 < ratio_long < 10.0


def test_descriptives_cv():
    d = descriptives([10.0, 10.5, 9.5, 10.0])
    assert d.cv == pytest.approx(d.sd / d.mean)
    assert math.isnan(descriptives([]).cv)


def test_skewness_detects_asymmetry():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.stats import (
        skewness,
    )

    sym = [float(x) for x in range(-50, 51)]
    assert abs(skewness(sym)) < 1e-9
    skewed = [math.exp(x / 10.0) for x in range(100)]
    assert skewness(skewed) > 1.0


def test_variance_check_reports_cells_and_verdict():
    rows = _synthetic_rows(n_per_cell=12)
    report = analyze(rows)
    vc = report["variance_check"]
    # uniform(0.9, 1.1) noise → CV ≈ 5.8% > 5% target on at least some cells
    assert vc["target_cv"] == 0.05
    assert vc["n_cells"] == 4  # 1 model × 2 locations × 2 lengths
    assert vc["verdict"] in ("pass", "fail")
    assert vc["worst"]["cell"] in vc["cells"]
    md = render_markdown(report)
    assert "Run-to-run variance" in md
    # tight synthetic data: verdict should actually pass when noise is small
    tight = _synthetic_rows(n_per_cell=12)
    for r in tight:
        r["energy_J"] = 100.0 if r["location"] == "on_device" else 50.0
    vc2 = analyze(tight)["variance_check"]
    assert vc2["verdict"] == "pass"


def test_variance_check_judges_cells_against_their_own_distribution():
    """A slow model's rows can be outliers of the POOLED location×length
    subset while being perfectly tight within their own cell. The global
    IQR filter must not make such cells unassessable (round 2 lost 6 of
    42 cells this way) — per-cell CV is judged on the cell's own
    distribution."""
    rows = []
    i = 0
    # slow is a small minority → the whole-table IQR fence sits tight
    # around the fast rows and (globally) drops every slow row
    for model, base, reps in (("fast", 1.0, 40), ("slow", 400.0, 6)):
        for rep in range(reps):
            rows.append(
                {
                    "__run_id": f"run_{i}_repetition_{rep}",
                    "__done": RunProgress.DONE,
                    "model": model,
                    "location": "on_device",
                    "length": 100,
                    "energy_J": base * (1.0 + 0.002 * (rep % 3)),
                    "execution_time_s": base,
                }
            )
            i += 1
    # sanity: the rounds-1-3 POOLED filter really does drop the slow rows
    # (the bias this test exists to guard against, kept reproducible)
    pooled = analyze(
        rows, metrics=("energy_J", "execution_time_s"), filter_scope="pooled"
    )
    assert pooled["n_after_iqr"] < len(rows)
    # the default per-cell scope keeps every cell's rows
    report = analyze(rows, metrics=("energy_J", "execution_time_s"))
    assert report["n_after_iqr"] == len(rows)
    vc = report["variance_check"]
    assert set(vc["cells"]) == {"fast|on_device|100", "slow|on_device|100"}
    assert vc["cells"]["slow|on_device|100"]["n"] >= 4
    assert vc["n_cells"] == 2
    assert vc["verdict"] == "pass"  # both cells are tight within themselves


def test_variance_check_flags_nan_cv_cells():
    """A zero-mean cell has an undefined CV: it must be flagged
    unassessable, excluded from the worst-cell pick, and never silently
    counted as a failure (ADVICE round-2)."""
    rows = []
    i = 0
    for model, energy in (("ok", 10.0), ("zero", 0.0)):
        for rep in range(5):
            rows.append(
                {
                    "__run_id": f"run_{i}_repetition_{rep}",
                    "__done": RunProgress.DONE,
                    "model": model,
                    "location": "on_device",
                    "length": 100,
                    "energy_J": energy,
                }
            )
            i += 1
    report = analyze(rows, metrics=("energy_J",))
    vc = report["variance_check"]
    assert vc["cells"]["zero|on_device|100"]["cv"] is None
    assert vc["n_unassessable"] == 1
    assert vc["worst"]["cell"] == "ok|on_device|100"
    assert vc["verdict"] == "pass"  # the assessable cell passes
    md = render_markdown(report)
    assert "unassessable" in md

    # every cell NaN → the target was never failed, it was never judged
    for r in rows:
        r["energy_J"] = 0.0
    vc_all_nan = analyze(rows, metrics=("energy_J",))["variance_check"]
    assert vc_all_nan["verdict"] == "unassessable"
    assert vc_all_nan["n_cells"] == 0
    assert "worst" not in vc_all_nan


def test_variance_check_keeps_globally_filtered_treatments():
    """A treatment (location/length level) whose rows the pooled IQR
    filter drops wholesale must still appear in the variance check."""
    rows = []
    i = 0
    for loc, base, reps in (("on_device", 1.0, 40), ("remote", 400.0, 6)):
        for rep in range(reps):
            rows.append(
                {
                    "__run_id": f"run_{i}_repetition_{rep}",
                    "__done": RunProgress.DONE,
                    "model": "m",
                    "location": loc,
                    "length": 100,
                    "energy_J": base * (1.0 + 0.002 * (rep % 3)),
                }
            )
            i += 1
    report = analyze(rows, metrics=("energy_J",))
    vc = report["variance_check"]
    assert "m|remote|100" in vc["cells"]
    assert vc["cells"]["m|remote|100"]["n"] >= 4
    assert vc["verdict"] == "pass"


def test_skewness_transform_step_in_report():
    rows = _synthetic_rows(n_per_cell=15)
    # make one subset strongly right-skewed so the log-transform step fires
    for r in rows:
        if r["location"] == "on_device" and r["length"] == 100:
            r["energy_J"] = math.exp(r["cpu_usage"]) * 10
    report = analyze(rows, iqr_k=100.0)  # keep the skewed tail in
    skew = report["skewness"]["on_device|100"]
    assert skew["skew"] > 1
    assert "skew_log" in skew and abs(skew["skew_log"]) < abs(skew["skew"])
    assert "Skewness" in render_markdown(report)


def test_density_and_panel_plots_written(tmp_path):
    pytest.importorskip("matplotlib")
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.plots import (
        density_by,
        plot_experiment,
        violin_panel_by_model,
    )

    rows = _synthetic_rows(n_per_cell=10)
    assert density_by(rows, "energy_J", "location", tmp_path / "d.png")
    assert (tmp_path / "d.png").exists()
    assert violin_panel_by_model(rows, "energy_J", tmp_path / "p.png")
    assert (tmp_path / "p.png").exists()
    written = plot_experiment(rows, tmp_path / "all")
    names = {p.name for p in written}
    assert "density_energy_J_by_location.png" in names
    assert "violin_energy_J_per_model.png" in names
    assert "qq_energy_J.png" in names


def test_latex_descriptives_table(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.analysis.pipeline import (
        render_latex_descriptives,
    )

    rows = _synthetic_rows(n_per_cell=8)
    store = RunTableStore(tmp_path)
    store.write(rows)
    report = analyze_experiment(tmp_path)
    tex = (tmp_path / "descriptives.tex").read_text()
    assert tex.startswith("\\begin{tabular}")
    # underscores must be escaped or the pasted tabular won't compile
    assert "on\\_device / 100" in tex and "remote / 200" in tex
    assert "on_device" not in tex.replace("on\\_device", "")
    assert tex == render_latex_descriptives(report, "energy_J")


def test_subset_filter_scope_matches_notebook_order():
    """filter_scope='subset' reproduces the reference notebook's exact
    procedure (cells 11-13): subset by location×length FIRST, IQR within.
    A value that is an outlier of the pooled table but typical of its own
    subset must survive."""
    rows = []
    i = 0
    # remote is a small minority → the pooled fences sit tight around the
    # on_device rows and (pooled) drop every remote row
    for loc, base, reps in (("on_device", 1.0, 24), ("remote", 1000.0, 5)):
        for rep in range(reps):
            rows.append(
                {
                    "__run_id": f"run_{i}_repetition_{rep}",
                    "__done": RunProgress.DONE,
                    "model": "m",
                    "location": loc,
                    "length": 100,
                    "energy_J": base * (1.0 + 0.01 * (rep % 3)),
                }
            )
            i += 1
    # pooled: the remote rows straddle the pooled fences → rows vanish
    pooled = analyze(rows, metrics=("energy_J",), filter_scope="pooled")
    # per-subset: each location is its own stratum → everything survives
    subset = analyze(rows, metrics=("energy_J",), filter_scope="subset")
    assert subset["n_after_iqr"] == len(rows)
    assert pooled["n_after_iqr"] < len(rows)
    assert subset["filter_scope"] == "subset"
    # descriptives reflect the subset's own (unbiased) mean
    d = subset["descriptives"]["remote|100"]["energy_J"]
    assert 1000.0 <= d["mean"] <= 1015.0


def test_cell_filter_scope_preserves_every_cells_assessability():
    """The default per-cell scope (VERDICT round-3 directive 2): with 7
    models spanning ~500× in energy, every model×location×length cell
    must keep ≥ its non-outlier rows — no cell may be erased by another
    model's distribution, and the published mean must match the raw
    direction (remote|long ≈ its raw mean, not 3.8× low)."""
    rows = []
    i = 0
    scales = {"tiny": 26.0, "mid": 800.0, "big": 13035.0}
    for model, scale in scales.items():
        for loc in ("on_device", "remote"):
            for length in (100, 1000):
                for rep in range(8):
                    rows.append(
                        {
                            "__run_id": f"run_{i}_repetition_{rep}",
                            "__done": RunProgress.DONE,
                            "model": model,
                            "location": loc,
                            "length": length,
                            "energy_J": scale
                            * (10 if length == 1000 else 1)
                            * (2 if loc == "remote" else 1)
                            * (1.0 + 0.01 * (rep % 4)),
                        }
                    )
                    i += 1
    report = analyze(rows, metrics=("energy_J",), filter_scope="cell")
    assert report["n_after_iqr"] == len(rows)
    # every cell assessable in the variance check AND represented in
    # the filtered descriptives
    vc = report["variance_check"]
    assert vc["n_cells"] == len(scales) * 2 * 2
    raw_remote_long = [
        r["energy_J"]
        for r in rows
        if r["location"] == "remote" and r["length"] == 1000
    ]
    raw_mean = sum(raw_remote_long) / len(raw_remote_long)
    d = report["descriptives"]["remote|1000"]["energy_J"]
    assert d["mean"] == pytest.approx(raw_mean, rel=0.02)
    # and the means are monotone in length (the round-3 report was not)
    assert (
        report["descriptives"]["remote|1000"]["energy_J"]["mean"]
        > report["descriptives"]["remote|100"]["energy_J"]["mean"]
    )


def test_h2_definitional_metrics_annotated_under_modelled_energy():
    """When energy is MODEL-derived, ρ between the model and its own
    inputs (decode_s, execution_time_s, ...) is arithmetic. Those metrics
    must be flagged definitional, excluded from the rendered H2 table,
    and genuinely independent metrics (cpu_usage) left unrestricted. A
    measured energy metric gets no flags at all (VERDICT round-3 dir 5)."""
    import random

    rng = random.Random(7)
    rows = []
    for i in range(30):
        decode = 1.0 + 0.1 * i
        rows.append(
            {
                "__run_id": f"run_{i}_repetition_0",
                "__done": RunProgress.DONE,
                "model": "m",
                "location": "on_device",
                "length": 100,
                "energy_model_J": 55.0 * decode,  # deterministic in decode_s
                "decode_s": decode,
                "execution_time_s": decode + 0.2,
                "cpu_usage": rng.uniform(5, 95),
            }
        )
    metrics = ("energy_model_J", "decode_s", "execution_time_s", "cpu_usage")
    report = analyze(
        rows, metrics=metrics, energy_metric="energy_model_J"
    )
    assert report["h2_energy_is_modelled"] is True
    h2 = report["h2_spearman"]["on_device"]
    assert h2["decode_s"]["definitional"] is True
    assert h2["execution_time_s"]["definitional"] is True
    assert "definitional" not in h2["cpu_usage"]
    md = render_markdown(report)
    assert "Definitional (excluded from the table)" in md
    # the ρ=1.000 row must not appear as a table row
    assert "| decode_s | 1.000" not in md

    # measured energy: same table shape, no flags, no exclusion note
    for r in rows:
        r["energy_J"] = r.pop("energy_model_J") * 1.1
    measured = analyze(
        rows,
        metrics=("energy_J", "decode_s", "cpu_usage"),
        energy_metric="energy_J",
    )
    assert measured["h2_energy_is_modelled"] is False
    assert "definitional" not in measured["h2_spearman"]["on_device"]["decode_s"]
    assert "Definitional" not in render_markdown(measured)


def test_tpu_util_rendered_as_percent():
    """The utilisation column mirrors the reference's GPU-residency
    metric; a 61% duty must render as a percentage, not '0.61' (and
    never the round-3 report's flat '0.00')."""
    rows = [
        {
            "__run_id": f"run_{i}_repetition_0",
            "__done": RunProgress.DONE,
            "model": "m",
            "location": "on_device",
            "length": 100,
            "energy_model_J": 100.0 + i,
            "tpu_util_est": 0.61 + 0.001 * (i % 3),
        }
        for i in range(6)
    ]
    report = analyze(
        rows,
        metrics=("energy_model_J", "tpu_util_est"),
        energy_metric="energy_model_J",
    )
    md = render_markdown(report)
    assert "61%" in md


def test_measured_energy_channel_outranks_the_model(tmp_path):
    """docs/ARCHITECTURE.md measured-host runbook: a table carrying BOTH
    a measured device channel (tpu_energy_J) and the modelled column
    analyses the measured one — and host_energy_J (client CPU) must
    never outrank the model as the study metric."""
    rows = [
        {
            "__run_id": f"run_{i}_repetition_0",
            "__done": RunProgress.DONE,
            "model": "m",
            "location": "on_device",
            "length": 100,
            "tpu_energy_J": 90.0 + i,
            "energy_model_J": 50.0 + i,
            "host_energy_J": 10.0 + i,
            "decode_s": 1.0 + 0.01 * i,
        }
        for i in range(6)
    ]
    store = RunTableStore(tmp_path)
    store.write(rows)
    report = analyze_experiment(tmp_path)
    assert report["variance_check"]["metric"] == "tpu_energy_J"
    # measured channel: H2 runs unrestricted (no definitional flags)
    assert report["h2_energy_is_modelled"] is False

    # model-only table: energy_model_J is the metric, host stays below
    for r in rows:
        r["tpu_energy_J"] = None
    store.write(rows)
    report = analyze_experiment(tmp_path)
    assert report["variance_check"]["metric"] == "energy_model_J"
    assert report["h2_energy_is_modelled"] is True


def test_shipped_capstone_report_invariants():
    """The committed flagship deliverable (docs/sample_run): re-deriving
    the analysis from the shipped run table must reproduce the
    properties the round-3 verdict found broken and round 4 fixed —
    energy monotone in content length within each location, every
    model-cell assessable in the CV check, a real (non-zero) utilisation
    column, and the remote rows carrying a modelled mesh window that is
    FASTER than their measured single-chip window (VERDICT round-3
    missing #2/#3, weak #1/#2)."""
    from pathlib import Path

    sample = Path(__file__).parent.parent / "docs" / "sample_run"
    if not (sample / "run_table.csv").exists():
        pytest.skip("sample run not present")
    rows = RunTableStore(sample).read()
    assert len(rows) == 1260
    report = analyze(
        rows,
        metrics=("energy_model_J", "tpu_util_est", "decode_s"),
        energy_metric="energy_model_J",
    )
    for loc in ("on_device", "remote"):
        means = [
            report["descriptives"][f"{loc}|{length}"]["energy_model_J"][
                "mean"
            ]
            for length in (100, 500, 1000)
        ]
        assert means[0] < means[1] < means[2], (loc, means)
    vc = report["variance_check"]
    assert vc["n_cells"] == 42 and vc["n_unassessable"] == 0
    # utilisation is a real working fraction, not the round-3 flat zero
    utils = [r["tpu_util_est"] for r in rows if r["tpu_util_est"] is not None]
    assert min(utils) > 0.05 and max(utils) <= 1.0
    # remote rows: modelled mesh window present, faster than measured,
    # sublinear in the 8-chip mesh
    for r in rows:
        if r["location"] == "remote":
            assert r["remote_modeled_decode_s"] is not None
            speedup = r["decode_s"] / r["remote_modeled_decode_s"]
            assert 1.0 < speedup < 8.0, r["__run_id"]
        else:
            assert r["remote_modeled_decode_s"] is None


def test_shipped_capstone_recompute_is_deterministic(tmp_path):
    """recompute-energy on a copy of the shipped capstone reproduces the
    committed modelled columns bit-for-bit — the table is self-contained
    (chips + quantize persisted per row) and the model is a pure function
    of the raw measurements, so the deliverable can be regenerated by
    anyone from the raw columns alone."""
    import shutil
    from pathlib import Path

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        recompute_energy,
    )

    sample = Path(__file__).parent.parent / "docs" / "sample_run"
    if not (sample / "run_table.csv").exists():
        pytest.skip("sample run not present")
    exp = tmp_path / "capstone"
    exp.mkdir()
    shutil.copy(sample / "run_table.csv", exp / "run_table.csv")
    before = {
        r["__run_id"]: (
            r["energy_model_J"], r["joules_per_token"], r["tpu_util_est"],
            r["remote_modeled_decode_s"],
        )
        for r in RunTableStore(exp).read()
    }
    n = recompute_energy(exp, reanalyze=False)
    assert n == 1260
    after = {
        r["__run_id"]: (
            r["energy_model_J"], r["joules_per_token"], r["tpu_util_est"],
            r["remote_modeled_decode_s"],
        )
        for r in RunTableStore(exp).read()
    }
    assert before == after

def _speed_rows():
    """Synthetic 2-location table with modelled mesh windows on the
    remote rows (the aliased-capstone shape)."""
    rows = []
    for i in range(8):
        rows.append({
            "model": "m", "location": "on_device", "length": 100,
            "energy_model_J": 100.0 + i, "decode_s": 10.0 + 0.1 * i,
            "remote_modeled_decode_s": None,
        })
        rows.append({
            "model": "m", "location": "remote", "length": 100,
            "energy_model_J": 150.0 + i, "decode_s": 10.0 + 0.1 * i,
            "remote_modeled_decode_s": 2.5 + 0.05 * i,
        })
    return rows


def test_h1_speed_section_uses_modelled_remote_window_with_provenance():
    """VERDICT round-4 missing #2: the speed axis of the study's research
    question gets a tested, labelled home in the published analysis — the
    remote side rides remote_modeled_decode_s (never the aliased
    single-chip measurement) and the provenance label says so."""
    report = analyze(
        _speed_rows(),
        metrics=("energy_model_J", "decode_s", "remote_modeled_decode_s"),
        energy_metric="energy_model_J",
    )
    h = report["h1_speed_by_length"]["100"]
    # remote side ≈ 2.5 s modelled vs on-device ≈ 10 s measured → ~4×
    assert 3.5 < h["mean_ratio"] < 4.5
    assert h["remote_provenance"] == "modelled (TP roofline)"
    assert h["n_modelled"] == h["n_remote"] == 8
    assert h["stars"]  # significant at n=8 vs n=8 with disjoint ranges

    # the joint statement: remote faster AND more Joules, both axes
    # labelled with their provenance
    t = report["speed_energy_tradeoff"]
    lo, hi = t["speedup_range"]
    assert 3.5 < lo <= hi < 4.5
    e_lo, e_hi = t["energy_multiple_range"]
    assert 1.3 < e_lo <= e_hi < 1.7
    assert t["speed_provenance"] == ["modelled (TP roofline)"]
    assert t["energy_provenance"] == "modelled (energy_model_J)"

    md = render_markdown(report)
    assert "## H1-speed: serving decode time, on-device vs remote" in md
    assert "**modelled** mesh window" in md
    assert "## Speed–energy trade-off (the study's joint result)" in md
    assert "faster at" in md and "× the Joules" in md


def test_h1_speed_measured_remote_has_measured_label():
    """A genuinely distinct remote server (no modelled column) must NOT be
    labelled modelled."""
    rows = _speed_rows()
    for r in rows:
        if r["location"] == "remote":
            r["remote_modeled_decode_s"] = None
            r["decode_s"] = 3.0
    report = analyze(
        rows,
        metrics=("energy_model_J", "decode_s"),
        energy_metric="energy_model_J",
    )
    h = report["h1_speed_by_length"]["100"]
    assert h["remote_provenance"] == "measured"
    assert h["n_modelled"] == 0
    md = render_markdown(report)
    assert "Both sides of this comparison are **measured**" in md


def test_shipped_capstone_publishes_speed_energy_tradeoff():
    """The committed capstone report must carry the trade-off tables —
    the reference's research question (RunnerConfig.py:122-131) was in no
    published table through round 4 (VERDICT round-4 missing #2)."""
    import json
    from pathlib import Path

    sample = Path(__file__).parent.parent / "docs" / "sample_run"
    if not (sample / "analysis_report.md").exists():
        pytest.skip("sample run not present")
    md = (sample / "analysis_report.md").read_text()
    assert "## H1-speed: serving decode time, on-device vs remote" in md
    assert "## Speed–energy trade-off (the study's joint result)" in md
    # the provenance label: the capstone topology is aliased, so the
    # speed table must declare the remote side modelled
    assert "modelled (TP roofline)" in md
    report = json.loads((sample / "analysis_report.json").read_text())
    t = report["speed_energy_tradeoff"]
    s_lo, s_hi = t["speedup_range"]
    e_lo, e_hi = t["energy_multiple_range"]
    # remote: faster (sublinear on 8 chips) at a modest Joule premium
    assert 1.5 < s_lo <= s_hi < 8.0
    assert 1.0 < e_lo <= e_hi < 3.0


def test_shipped_capstone_power_states_are_per_engine():
    """Round-5 directive #1 'done' criterion on the deliverable: no
    decode row bills the flat 200 W matmul envelope (the round-4
    artifact for util-capped int4 rows), every row bills a working state
    above idle, and int4 rows are distinguishable from int8 rows in
    billed watts."""
    from pathlib import Path

    sample = Path(__file__).parent.parent / "docs" / "sample_run"
    if not (sample / "run_table.csv").exists():
        pytest.skip("sample run not present")
    rows = RunTableStore(sample).read()
    powers = []
    for r in rows:
        w = r.get("tpu_power_model_W")
        assert w is not None
        assert 55.0 < w < 150.0, r["__run_id"]  # working state, not envelope
        powers.append(w)
    # power is a per-row engine-mix outcome, not a constant: the table
    # must span a real range (the round-4 model pinned whole treatment
    # groups at identical peak watts)
    assert max(powers) - min(powers) > 20.0
    # and no util-capped row sits at the envelope: the rows with
    # tpu_util_est == 1.0 (saturated engine) still bill engine watts
    capped = [
        r["tpu_power_model_W"] for r in rows if r.get("tpu_util_est") == 1.0
    ]
    assert capped and all(w < 150.0 for w in capped)
    # (same-model int4-vs-int8 watt separation is pinned in
    # test_per_engine_power_int4_vs_int8_distinguishable — across the
    # capstone's per-model quantize assignment the pooled means are
    # confounded and deliberately not compared here)


def test_h1_speed_modelled_window_not_keyed_on_location_label():
    """A two-location table whose remote arm uses a custom label but
    carries remote_modeled_decode_s must still substitute the modelled
    window and declare it modelled — never publish the aliased
    single-chip measurement as 'measured' (round-5 review finding)."""
    rows = _speed_rows()
    for r in rows:
        if r["location"] == "remote":
            r["location"] = "cloud"
    report = analyze(
        rows,
        metrics=("energy_model_J", "decode_s", "remote_modeled_decode_s"),
        energy_metric="energy_model_J",
    )
    h = report["h1_speed_by_length"]["100"]
    assert h["remote_provenance"] == "modelled (TP roofline)"
    assert h["n_modelled"] == h["n_remote"] == 8
    # modelled ≈2.5 s vs measured ≈10 s → ~4× either way; with 'cloud'
    # sorting before 'on_device' the ratio inverts direction but the
    # magnitude must reflect the modelled window, not the aliased one
    assert 3.5 < max(h["mean_ratio"], 1.0 / h["mean_ratio"]) < 4.5
    # the remote-named trade-off block is gated on canonical labels
    assert report["speed_energy_tradeoff"] == {}
