"""Fleet-wide observability (ISSUE 13): wire trace propagation, the
Prometheus text parser + /metrics federation, cross-process timelines,
and the retry/preemption-proof wasted-energy ledger."""

import json
import threading
import time
import urllib.request

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs import (
    energy as obs_energy,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
    FLIGHT,
    trace_attrs,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    REGISTRY,
    histogram_mean,
    merge_expositions,
    parse_exposition,
    sample_value,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.trace import (
    TRACER,
    TraceContext,
    mint_trace_id,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.router import (
    LocalReplica,
    Router,
    RouterServer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
    ContinuousScheduler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


def _snapshot(name):
    fam = REGISTRY.snapshot().get(name) or {}
    return sum(v for v in fam.values() if isinstance(v, (int, float)))


def _req(prompt, n=8, **kw):
    return GenerationRequest("m", prompt, max_new_tokens=n, **kw)


def _post(base, body):
    req = urllib.request.Request(
        f"{base}/api/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def _get(base, path):
    with urllib.request.urlopen(f"{base}{path}", timeout=10) as resp:
        return resp.read().decode()


# -- wire trace context --------------------------------------------------------


def test_trace_wire_round_trip():
    trace = TraceContext(trace_id="cafe0123deadbeef", parent="42")
    request = _req("hello", trace=trace)
    wire = protocol.request_to_wire(request)
    assert wire["x_trace"] == {"id": "cafe0123deadbeef", "parent": "42"}
    back = protocol.request_from_wire(wire)
    assert back.trace == trace
    # parent omitted when the caller minted the trace itself
    wire2 = protocol.request_to_wire(_req("x", trace=TraceContext("abcd")))
    assert wire2["x_trace"] == {"id": "abcd"}
    # untraced requests put nothing on the wire
    assert "x_trace" not in protocol.request_to_wire(_req("y"))
    # bare-string form (curl-friendliness)
    bare = protocol.request_from_wire(
        {"model": "m", "prompt": "p", "x_trace": "feed0000"}
    )
    assert bare.trace == TraceContext(trace_id="feed0000")


def test_trace_wire_malformed_rejected():
    for bad in ({"id": ""}, {"parent": "7"}, 17, {"id": 12}):
        with pytest.raises(ValueError):
            protocol.request_from_wire(
                {"model": "m", "prompt": "p", "x_trace": bad}
            )


def test_ensure_trace_mints_once():
    request = _req("z")
    minted = protocol.ensure_trace(request)
    assert minted.trace is not None and len(minted.trace.trace_id) == 16
    assert protocol.ensure_trace(minted) is minted  # adopt, never re-mint
    a, b = mint_trace_id(), mint_trace_id()
    assert a != b


def test_span_trace_id_inherits_and_flight_links():
    tid = mint_trace_id()
    with TRACER.span("request", trace_id=tid) as root:
        assert root.trace_id == tid
        with TRACER.span("child") as child:
            assert child.trace_id == tid  # nested spans inherit
            attrs = trace_attrs(child)
            assert attrs == {"trace": child.span_id, "trace_id": tid}
        # timed-interval spans inherit through their parent too
        span = TRACER.add_span("decode", 0.0, 1.0)
        assert span.trace_id == tid
    event = FLIGHT.emit("test_fleet_obs", **trace_attrs(root))
    try:
        got = FLIGHT.events(trace=tid)
        assert any(e["seq"] == event.seq for e in got)
        assert all(e["trace_id"] == tid for e in got)
        # span-id (integer) filtering still works for old consumers
        by_span = FLIGHT.events(trace=str(root.span_id))
        assert any(e["seq"] == event.seq for e in by_span)
        assert FLIGHT.events(trace=mint_trace_id()) == []
    finally:
        pass


# -- federation: parser + bucket-wise merge ------------------------------------

_REPLICA_A = """\
# HELP llm_sched_requests_total Requests submitted
# TYPE llm_sched_requests_total counter
llm_sched_requests_total 5.0
# TYPE llm_request_ttft_seconds histogram
llm_request_ttft_seconds_bucket{le="0.1"} 2
llm_request_ttft_seconds_bucket{le="1.0"} 4
llm_request_ttft_seconds_bucket{le="+Inf"} 5
llm_request_ttft_seconds_sum 2.5
llm_request_ttft_seconds_count 5
# TYPE llm_sched_inflight_rows gauge
llm_sched_inflight_rows 3.0
# TYPE llm_sched_rows_retired_total counter
llm_sched_rows_retired_total{reason="eos"} 2.0
llm_sched_rows_retired_total{reason="bs\\\\q\\"o\\nte"} 1.0
# TYPE llm_router_dispatch_total counter
llm_router_dispatch_total{replica="x",policy="p"} 9.0
"""

_REPLICA_B = """\
# TYPE llm_sched_requests_total counter
llm_sched_requests_total 7.0
# TYPE llm_request_ttft_seconds histogram
llm_request_ttft_seconds_bucket{le="0.1"} 1
llm_request_ttft_seconds_bucket{le="1.0"} 1
llm_request_ttft_seconds_bucket{le="+Inf"} 3
llm_request_ttft_seconds_sum 9.5
llm_request_ttft_seconds_count 3
# TYPE llm_sched_inflight_rows gauge
llm_sched_inflight_rows 1.0
# TYPE llm_sched_rows_retired_total counter
llm_sched_rows_retired_total{reason="eos"} 4.0
"""

# Pinned golden output: counters summed per label set (escaped label
# values surviving the round trip byte-exact), histogram buckets merged
# CUMULATIVELY per le, gauges re-labelled {replica=...}, llm_router_*
# excluded, the empty replica contributing nothing, families sorted.
_GOLDEN_FLEET = """\
# TYPE llm_fleet_request_ttft_seconds histogram
llm_fleet_request_ttft_seconds_bucket{le="0.1"} 3
llm_fleet_request_ttft_seconds_bucket{le="1.0"} 5
llm_fleet_request_ttft_seconds_bucket{le="+Inf"} 8
llm_fleet_request_ttft_seconds_sum 12.0
llm_fleet_request_ttft_seconds_count 8
# TYPE llm_fleet_sched_inflight_rows gauge
llm_fleet_sched_inflight_rows{replica="a"} 3.0
llm_fleet_sched_inflight_rows{replica="b"} 1.0
# HELP llm_fleet_sched_requests_total Requests submitted
# TYPE llm_fleet_sched_requests_total counter
llm_fleet_sched_requests_total 12.0
# TYPE llm_fleet_sched_rows_retired_total counter
llm_fleet_sched_rows_retired_total{reason="bs\\\\q\\"o\\nte"} 1.0
llm_fleet_sched_rows_retired_total{reason="eos"} 6.0
"""


def test_federation_merge_golden():
    merged = merge_expositions(
        [("a", _REPLICA_A), ("b", _REPLICA_B), ("empty", "")]
    )
    assert merged == _GOLDEN_FLEET
    # deterministic: same scrapes, same bytes (the byte-consistency the
    # acceptance criterion pins between the router endpoint and a
    # by-hand merge of the replica scrapes)
    assert merged == merge_expositions(
        [("a", _REPLICA_A), ("b", _REPLICA_B), ("empty", "")]
    )


def test_federation_merge_drops_bucket_skew_whole():
    skewed = _REPLICA_B.replace('le="0.1"', 'le="0.2"')
    merged = merge_expositions([("a", _REPLICA_A), ("b", skewed)])
    # the skewed histogram family is dropped WHOLE (merging mismatched
    # bounds would be wrong); everything else still federates
    assert "llm_fleet_request_ttft_seconds" not in merged
    assert "llm_fleet_sched_requests_total 12.0" in merged


def test_parser_round_trips_own_exposition():
    fam = REGISTRY.counter(
        "llm_test_fleet_obs_total", "x", labels=("edge",)
    )
    fam.labels(edge='a"b\\c\nd').inc(2)
    families = parse_exposition(REGISTRY.exposition())
    parsed = families["llm_test_fleet_obs_total"]
    assert parsed.samples[(("edge", 'a"b\\c\nd'),)] == 2.0
    assert sample_value(families, "llm_test_fleet_obs_total") == 2.0
    assert histogram_mean(families, "definitely_absent") is None


# -- single-server trace propagation end-to-end --------------------------------


def test_server_flight_story_filters_by_wire_trace():
    server = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        tid = mint_trace_id()
        body = _post(
            base,
            {
                "model": "m",
                "prompt": "traced request",
                "options": {"num_predict": 8},
                "x_trace": {"id": tid, "parent": "777"},
            },
        )
        assert body.get("done")
        flight = json.loads(_get(base, f"/debug/flight?trace={tid}"))
        types = [e["type"] for e in flight["events"]]
        assert "request_admitted" in types and "row_retired" in types
        assert all(e["trace_id"] == tid for e in flight["events"])
        # lifecycle order: admitted strictly before retired
        assert types.index("request_admitted") < types.index("row_retired")
        admitted = [
            e for e in flight["events"] if e["type"] == "request_admitted"
        ][0]
        assert "queue_wait_s" in admitted
        # an untraced request gets a SERVER-minted trace — its story is
        # just as filterable
        _post(
            base,
            {"model": "m", "prompt": "untraced", "options": {"num_predict": 4}},
        )
        all_admits = json.loads(
            _get(base, "/debug/flight?type=request_admitted&n=500")
        )["events"]
        minted = [
            e
            for e in all_admits
            if e.get("trace_id") and e["trace_id"] != tid
        ]
        assert minted, all_admits
    finally:
        server.stop()


def test_streaming_rows_emit_stream_chunk_events():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
        RemoteHTTPBackend,
    )

    server = GenerationServer(
        FakeBackend(tokens_per_s=400.0, simulate_delay=True),
        host="127.0.0.1",
        port=0,
        quiet=True,
        scheduler="continuous",
    )
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        tid = mint_trace_id()
        client = RemoteHTTPBackend(base)
        chunks = list(
            client.generate_stream(
                _req("streamed", n=32, trace=TraceContext(trace_id=tid))
            )
        )
        assert chunks[-1].done
        flight = json.loads(_get(base, f"/debug/flight?trace={tid}&n=500"))
        stream_events = [
            e for e in flight["events"] if e["type"] == "stream_chunk"
        ]
        assert stream_events, flight["events"]
        assert sum(e["tokens"] for e in stream_events) == 32
    finally:
        server.stop()


# -- router: retry shares one trace, timeline, wasted retry Joules -------------


def test_router_retry_shares_trace_and_charges_wasted_joules():
    wasted0 = _snapshot("llm_request_wasted_joules_total")
    backend_dead = FakeBackend(tokens_per_s=500.0)
    backend_live = FakeBackend(tokens_per_s=500.0)
    backend_dead.fail_decode_open = True  # r0 is dead from the start
    router = Router(
        [
            LocalReplica("r0", backend_dead),
            LocalReplica("r1", backend_live),
        ],
        policy="round-robin",
        probe_interval_s=30.0,
    )
    server = RouterServer(router, host="127.0.0.1", port=0, quiet=True)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        tid = mint_trace_id()
        body = _post(
            base,
            {
                "model": "m",
                "prompt": "retried ticket",
                "options": {"num_predict": 8},
                "x_trace": {"id": tid},
            },
        )
        assert body.get("done")
        router_extras = body["x_extras"]["router"]
        assert router_extras["replica"] == "r1"
        assert router_extras["retried"] == "dead"
        assert router_extras["trace"] == tid
        # the wasted-energy ledger charged the dead first attempt and
        # stamped it on the wire next to the counter
        wasted_wire = body["x_extras"]["energy"]["wasted_J"]["retry"]
        assert wasted_wire > 0
        assert _snapshot("llm_request_wasted_joules_total") >= (
            wasted0 + wasted_wire * 0.99
        )
        # BOTH dispatch attempts carry ONE trace id, attempts in order
        flight = json.loads(
            _get(base, f"/debug/flight?trace={tid}&type=dispatched")
        )
        attempts = [(e["attempt"], e["replica"]) for e in flight["events"]]
        assert attempts == [(1, "r0"), (2, "r1")]
        assert {e["trace_id"] for e in flight["events"]} == {tid}
        # the timeline endpoint reassembles the full story in order:
        # dispatch(r0) -> retry dispatch(r1) -> admitted -> retired
        timeline = json.loads(_get(base, f"/debug/timeline?trace={tid}"))
        assert timeline["trace"] == tid
        assert timeline["attempts"] == 2
        types = [e["type"] for e in timeline["events"]]
        hops = [e["hop"] for e in timeline["events"]]
        d0 = types.index("dispatched")
        d1 = types.index("dispatched", d0 + 1)
        assert (
            d0
            < d1
            < types.index("request_admitted")
            < types.index("row_retired")
        )
        assert hops[d0] == "router" and hops[d1] == "router"
        assert hops[types.index("request_admitted")] == "local"
        # ?trace= without a match is empty, not everything
        empty = json.loads(
            _get(base, f"/debug/timeline?trace={mint_trace_id()}")
        )
        assert empty["events"] == [] and empty["attempts"] == 0
    finally:
        server.stop()


def test_least_joules_routes_to_cheapest_fake_replica():
    # the ROADMAP gap this closes: least-joules reads live figures the
    # FAKE fleet now exposes (FakeBackend(joules_per_token=...)), so the
    # policy is exercised hermetically end to end
    cheap = FakeBackend(tokens_per_s=500.0, joules_per_token=0.2)
    pricey = FakeBackend(tokens_per_s=500.0, joules_per_token=5.0)
    router = Router(
        [
            LocalReplica("cheap", cheap),
            LocalReplica("pricey", pricey),
        ],
        policy="least-joules",
        probe_interval_s=30.0,
    )
    try:
        router.probe_now()
        assert router.replicas()[0].last_stats.get("joules_per_token") == 0.2
        for i in range(4):
            result = router.dispatch(_req(f"jpt {i}", n=4))
            assert result.extras["router"]["replica"] == "cheap"
    finally:
        router.stop()


def test_router_metrics_federates_fleet_rollup():
    # two in-process replicas share THIS process's registry: the fleet
    # rollup federates it exactly once as the "local" source, so
    # llm_fleet_* values equal the process totals (the remote-replica
    # bucket math itself is pinned by the golden test above)
    requests0 = _snapshot("llm_sched_requests_total")
    router = Router(
        [
            LocalReplica("r0", FakeBackend()),
            LocalReplica("r1", FakeBackend()),
        ],
        policy="round-robin",
        probe_interval_s=30.0,
    )
    server = RouterServer(router, host="127.0.0.1", port=0, quiet=True)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        for i in range(4):
            assert _post(
                base,
                {
                    "model": "m",
                    "prompt": f"fleet {i}",
                    "options": {"num_predict": 4},
                },
            ).get("done")
        text = _get(base, "/metrics")
        families = parse_exposition(text)
        fleet_requests = sample_value(
            families, "llm_fleet_sched_requests_total"
        )
        assert fleet_requests == _snapshot("llm_sched_requests_total")
        assert fleet_requests >= requests0 + 4
        # byte-consistency with a by-hand merge of the same sources
        fleet_lines = [
            ln for ln in text.splitlines() if "llm_fleet_" in ln
        ]
        by_hand = merge_expositions(router.federation_sources())
        for ln in by_hand.splitlines():
            if ln.startswith("llm_fleet_sched_requests_total"):
                assert ln in fleet_lines
        # the router's own families are never rolled up into the fleet
        assert "llm_fleet_router_dispatch_total" not in text
    finally:
        server.stop()


# -- wasted-energy ledger: preemption causes -----------------------------------


def test_preempt_swap_and_recompute_charge_wasted_ledger():
    for policy, cause in (("swap", "swap"), ("recompute", "recompute")):
        before = (
            REGISTRY.snapshot()
            .get("llm_request_wasted_joules_total", {})
            .get(f"cause={cause}", 0.0)
        )
        sched = ContinuousScheduler(
            FakeBackend(tokens_per_s=200.0, simulate_delay=True, max_rows=2),
            preempt_policy=policy,
        )
        sched.start()
        results = {}

        def run(name, req):
            try:
                results[name] = sched.submit(req)
            except Exception as exc:  # noqa: BLE001
                results[name] = exc

        threads = [
            threading.Thread(
                target=run,
                args=("low_old", _req("older low", n=128, priority=0)),
            )
        ]
        threads[0].start()
        time.sleep(0.15)
        threads.append(
            threading.Thread(
                target=run,
                args=("low_young", _req("younger low", n=128, priority=0)),
            )
        )
        threads[1].start()
        time.sleep(0.25)
        threads.append(
            threading.Thread(
                target=run, args=("high", _req("high", n=16, priority=2))
            )
        )
        threads[2].start()
        for t in threads:
            t.join(timeout=30)
        try:
            for name in ("low_old", "low_young", "high"):
                assert not isinstance(results.get(name), Exception), results
            after = (
                REGISTRY.snapshot()
                .get("llm_request_wasted_joules_total", {})
                .get(f"cause={cause}", 0.0)
            )
            assert after > before, (policy, before, after)
            # the victim's wire extras carry the same cause
            victim = results["low_young"]
            assert victim.extras["sched"].get("preempted") == 1
            wasted = victim.extras["energy"]["wasted_J"]
            assert wasted.get(cause, 0) > 0, wasted
            # the other rows carry NO wasted block — attribution is
            # per-request, not smeared
            assert "energy" not in (results["high"].extras or {})
        finally:
            sched.stop()


def test_charge_wasted_prices_tokens_and_bytes():
    j_tokens = obs_energy.charge_wasted("retry", tokens=100, jpt=0.25)
    assert j_tokens == pytest.approx(25.0)
    j_bytes = obs_energy.charge_wasted("swap", nbytes=2 * 1024 * 1024)
    assert j_bytes == pytest.approx(
        2 * 1024 * 1024 * obs_energy.SWAP_J_PER_BYTE
    )
    assert obs_energy.charge_wasted("retry") == 0.0  # nothing to charge
    # fallback pricing exists even before any live attribution
    assert obs_energy.live_joules_per_token() > 0


# -- poisson_load: caller-minted traces in the summary -------------------------


def test_poisson_load_mints_traces_and_reports_them():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_poisson_load",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
            "poisson_load.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    workload = mod.build_workload(6, 0.001, seed=3)
    traces = [req.trace.trace_id for _, req in workload]
    assert len(set(traces)) == 6  # every request distinctly traced
    # the summary names failed / SLO-missed / retried requests by trace
    records = [
        {"trace": traces[0], "error": "RuntimeError: boom"},
        {"trace": traces[1], "error": "DeadlineExceeded: late"},
        {
            "trace": traces[2],
            "tokens": 8,
            "completion_s": 0.1,
            "t_submit": 0.0,
            "t_done": 0.1,
            "ttft_s": 0.05,
            "replica": "r1",
            "retried": "dead",
        },
        {
            "trace": traces[3],
            "tokens": 8,
            "completion_s": 0.1,
            "t_submit": 0.0,
            "t_done": 0.1,
        },
    ]
    summary = mod.summarize(records)
    assert summary["failed_traces"] == [traces[0]]
    assert summary["slo_missed_traces"] == [traces[1]]
    assert summary["retried_traces"] == [traces[2]]
