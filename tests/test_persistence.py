"""CSV/JSON persistence: typed round-trips and atomic row updates."""

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.errors import PersistenceError
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.persistence import (
    MetadataStore,
    RunTableStore,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.progress import RunProgress


def _rows():
    return [
        {
            "__run_id": "run_0_repetition_0",
            "__done": RunProgress.TODO,
            "model": "qwen2:1.5b",
            "length": 100,
            "energy_J": None,
            "ratio": None,
        },
        {
            "__run_id": "run_1_repetition_0",
            "__done": RunProgress.DONE,
            "model": "gemma:2b",
            "length": 500,
            "energy_J": 12.625,
            "ratio": 0.5,
        },
    ]


def test_round_trip_preserves_types(tmp_path):
    store = RunTableStore(tmp_path)
    store.write(_rows())
    back = store.read()
    assert back[0]["__done"] == RunProgress.TODO
    assert back[0]["length"] == 100 and isinstance(back[0]["length"], int)
    assert back[0]["energy_J"] is None
    # The reference leaves floats as strings (CSVOutputManager.py:21-22); we don't.
    assert back[1]["energy_J"] == 12.625 and isinstance(back[1]["energy_J"], float)
    assert back[1]["model"] == "gemma:2b"


def test_bool_round_trip(tmp_path):
    store = RunTableStore(tmp_path)
    store.write(
        [{"__run_id": "r", "__done": RunProgress.TODO, "flag": True, "off": False}]
    )
    back = store.read()[0]
    assert back["flag"] is True and back["off"] is False


def test_update_row_touches_only_target(tmp_path):
    store = RunTableStore(tmp_path)
    store.write(_rows())
    store.update_row(
        "run_0_repetition_0", {"__done": RunProgress.DONE, "energy_J": 3.5}
    )
    back = store.read()
    assert back[0]["__done"] == RunProgress.DONE and back[0]["energy_J"] == 3.5
    assert back[1]["energy_J"] == 12.625  # untouched


def test_update_row_unknown_id_or_column(tmp_path):
    store = RunTableStore(tmp_path)
    store.write(_rows())
    with pytest.raises(PersistenceError, match="not in run table"):
        store.update_row("missing", {"energy_J": 1.0})
    with pytest.raises(PersistenceError, match="unknown columns"):
        store.update_row("run_0_repetition_0", {"nope": 1.0})


def test_no_temp_files_left_behind(tmp_path):
    store = RunTableStore(tmp_path)
    store.write(_rows())
    store.update_row("run_0_repetition_0", {"energy_J": 1.0})
    leftovers = [p for p in tmp_path.iterdir() if p.suffix == ".tmp"]
    assert leftovers == []


def test_empty_write_rejected(tmp_path):
    with pytest.raises(PersistenceError, match="empty run table"):
        RunTableStore(tmp_path).write([])


def test_metadata_round_trip(tmp_path):
    meta = MetadataStore(tmp_path)
    assert meta.read() is None
    meta.write({"config_ast_hash": "abc", "framework_version": "0.1.0"})
    assert meta.read()["config_ast_hash"] == "abc"
