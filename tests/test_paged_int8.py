"""Paged KV pool × int8 KV quantization: the two capacity features
composed (VERDICT round-5 directives #3/#4).

The pool holds int8 pages — codes + per-position scales pooled together
(engine/paged_kv.py quantized mode) — and the stacked-hybrid decode
merges int8 prompt parts (both impls: the Pallas parts kernel and the
gather+fused-XLA variant) with quantized side caches. Token parity is
pinned against the CONTIGUOUS int8 path (solo, batch, TP virtual mesh),
and the fixed-budget admission regression pins the capacity payoff: at
equal BATCH_KV_BUDGET_BYTES on the mixed-length study fleet, paged
admits ≥ contiguous rows per decode window and paged+int8 admits ≥
paged-bf16.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
    quantize_kv_vector,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
    pallas_decode_attention,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_paged_attention import (
    pallas_paged_decode_attention_parts,
    pallas_paged_decode_attention_parts_int8,
    xla_paged_decode_attention_parts_int8,
)


# -- kernel parity ----------------------------------------------------------
def _quantized_pools(seed, l, p, hkv, page, d):
    rng = np.random.default_rng(seed)
    kf = jnp.asarray(rng.normal(size=(l, p, hkv, page, d)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(l, p, hkv, page, d)), jnp.float32)
    kq, ks = quantize_kv_vector(kf)
    vq, vs = quantize_kv_vector(vf)
    kd = kq.astype(jnp.float32) * ks[..., None]
    vd = vq.astype(jnp.float32) * vs[..., None]
    return (kq, ks, vq, vs), (kd, vd)


def test_int8_parts_kernel_matches_dequantized_bf16_parts():
    """The int8 parts kernel folds scales into the online softmax; its
    (acc, m, l) must equal the bf16 parts kernel on the dequantized pool
    — per-layer (xs-streamed) AND stacked-``layer`` modes, including
    page-edge and zero-length rows."""
    L, P, HKV, PAGE, D = 2, 8, 2, 128, 128
    B, HQ = 3, 4
    (kq, ks, vq, vs), (kd, vd) = _quantized_pools(0, L, P, HKV, PAGE, D)
    q = jnp.asarray(
        np.random.default_rng(1).normal(size=(B, HQ, D)), jnp.float32
    )
    table = jnp.asarray([[3, 5], [1, 6], [0, 2]], jnp.int32)
    lengths = jnp.asarray([200, 129, 0], jnp.int32)

    for layer in range(L):
        want = pallas_paged_decode_attention_parts(
            q, kd[layer], vd[layer], table, lengths, interpret=True
        )
        got = pallas_paged_decode_attention_parts_int8(
            q, kq[layer], ks[layer], vq[layer], vs[layer], table, lengths,
            interpret=True,
        )
        stacked = pallas_paged_decode_attention_parts_int8(
            q, kq, ks, vq, vs, table, lengths,
            layer=jnp.int32(layer), interpret=True,
        )
        for g, s, w in zip(got, stacked, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-5, atol=2e-5
            )
            np.testing.assert_allclose(
                np.asarray(s), np.asarray(w), rtol=2e-5, atol=2e-5
            )
    # the zero-length row exits with the merge's sentinel triplet
    acc, m, l = pallas_paged_decode_attention_parts_int8(
        q, kq[0], ks[0], vq[0], vs[0], table, jnp.zeros((B,), jnp.int32),
        interpret=True,
    )
    assert jnp.all(acc == 0.0) and jnp.all(l == 0.0)
    assert jnp.all(jnp.isneginf(m))


def test_xla_int8_parts_match_kernel_and_lane_padded_head_dim():
    """The gather+dequant XLA variant returns the kernel's exact
    contract — including a lane-padded pool head dim (d=96 → Dp=128)
    whose pad lanes carry zero codes."""
    L, P, HKV, PAGE, D, DP = 1, 6, 2, 128, 96, 128
    rng = np.random.default_rng(2)
    kf = jnp.asarray(rng.normal(size=(P, HKV, PAGE, DP)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(P, HKV, PAGE, DP)), jnp.float32)
    kf = kf.at[..., D:].set(0)  # engine pools zero the pad lanes
    vf = vf.at[..., D:].set(0)
    kq, ks = quantize_kv_vector(kf)
    vq, vs = quantize_kv_vector(vf)
    q = jnp.asarray(rng.normal(size=(2, 4, D)), jnp.float32)
    table = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    lengths = jnp.asarray([130, 0], jnp.int32)  # incl. an empty row

    acc_k, m_k, l_k = pallas_paged_decode_attention_parts_int8(
        q, kq, ks, vq, vs, table, lengths, interpret=True
    )
    acc_x, m_x, l_x = xla_paged_decode_attention_parts_int8(
        q, kq, ks, vq, vs, table, lengths
    )
    assert acc_x.shape == (2, HKV, 2, D)
    np.testing.assert_allclose(
        np.asarray(acc_x), np.asarray(acc_k), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(m_x), np.asarray(m_k), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(l_x), np.asarray(l_k), rtol=2e-5, atol=2e-5
    )
    assert not np.isfinite(np.asarray(m_x)[1]).any()


# -- pool plumbing ----------------------------------------------------------
def test_quantized_page_pool_round_trip():
    """write_prefill + write_token on a quantized pool hold the same
    values (after dequant) the bf16 pool holds, at the same slots."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
        PagePool,
        write_prefill,
        write_token,
    )

    hkv, d, page = 2, 64, 128
    pool = PagePool.create(
        n_layers=1, n_pages=3, n_kv_heads=hkv, d_head=d, page_size=page,
        quantized=True,
    )
    assert pool.quantized and pool.n_pages == 3 and pool.free_pages == 3
    pages = pool.alloc(2)
    row = jnp.asarray(pages, jnp.int32)
    rng = np.random.default_rng(3)
    n0 = 127
    k_seq = jnp.asarray(rng.normal(size=(1, hkv, n0, d)), jnp.float32)
    v_seq = jnp.asarray(rng.normal(size=(1, hkv, n0, d)), jnp.float32)
    pool.k, pool.v = write_prefill(pool.k, pool.v, row, k_seq, v_seq, n0)
    # the boundary-crossing append (slot 127 then page 2 slot 0)
    k_vec = jnp.asarray(rng.normal(size=(1, hkv, d)), jnp.float32)
    v_vec = jnp.asarray(rng.normal(size=(1, hkv, d)), jnp.float32)
    pool.k, pool.v = write_token(
        pool.k, pool.v, row, jnp.int32(n0), k_vec, v_vec
    )
    pool.k, pool.v = write_token(
        pool.k, pool.v, row, jnp.int32(n0 + 1), k_vec * 2, v_vec * 2
    )
    # dequant the first row's pages and compare against direct
    # quantization of the same vectors (single source of scale math)
    want_q, want_s = quantize_kv_vector(k_seq[0, :, 5])  # position 5
    got_q = pool.k["q"][0, pages[0], :, 5]
    got_s = pool.k["s"][0, pages[0], :, 5]
    np.testing.assert_array_equal(np.asarray(got_q), np.asarray(want_q))
    np.testing.assert_allclose(np.asarray(got_s), np.asarray(want_s))
    # the append landed on page 2, slot 0
    app_q, app_s = quantize_kv_vector(k_vec[0] * 2)
    np.testing.assert_array_equal(
        np.asarray(pool.k["q"][0, pages[1], :, 0]), np.asarray(app_q)
    )
    np.testing.assert_allclose(
        np.asarray(pool.k["s"][0, pages[1], :, 0]), np.asarray(app_s)
    )


# -- engine token parity ----------------------------------------------------
@pytest.fixture(scope="module")
def registry():
    return {"tiny": get_model_config("qwen2:1.5b").tiny()}


@pytest.fixture(scope="module")
def parity_reqs():
    return [
        GenerationRequest("tiny", "short row", max_new_tokens=6),
        GenerationRequest(
            "tiny",
            "a much longer prompt for the second row of this batch",
            max_new_tokens=20,
        ),
        GenerationRequest(
            "tiny", "sampled row", max_new_tokens=12,
            temperature=0.7, seed=3,
        ),
    ]


@pytest.fixture(scope="module")
def contiguous_int8_tokens(registry, parity_reqs):
    engine = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, kv_quantize="int8"
    )
    return [r.tokens for r in engine.generate_batch(parity_reqs)]


def test_engine_accepts_paged_with_kv_quantize(registry):
    """The round-5 guard is lifted: the composition constructs (the old
    ValueError said 'an int8 pool is future work')."""
    engine = JaxEngine(
        registry=dict(registry), paged_kv=True, kv_quantize="int8"
    )
    assert engine.paged_kv and engine.kv_quantize == "int8"


@pytest.mark.parametrize("parts_impl", ["kernel", "xla"])
def test_paged_int8_stacked_matches_contiguous_int8(
    parts_impl, monkeypatch, registry, parity_reqs, contiguous_int8_tokens
):
    """STACKED-HYBRID paged decode over an int8 pool (both prompt-parts
    impls) emits the contiguous int8 path's tokens, row for row —
    mixed lengths, sampled rows, per-row budgets."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    monkeypatch.setattr(
        je, "PAGED_XLA_PARTS_MIN_ROWS",
        1 if parts_impl == "xla" else 10**9,
    )
    paged8 = JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
        kv_quantize="int8",
        decode_attention=pallas_decode_attention,  # stacked mode on CPU
    )
    assert paged8._paged_decode_attention() is not None
    got = paged8.generate_batch(parity_reqs)
    for g, want in zip(got, contiguous_int8_tokens):
        assert g.tokens == want


def test_paged_int8_legacy_gather_matches_contiguous_int8(
    registry, parity_reqs, contiguous_int8_tokens
):
    """LEGACY mode (no kernel → per-step quantized pool writes + the
    dequantizing gather fallback — the multi-device no-head-shard path)
    matches the contiguous int8 tokens too."""
    paged8 = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=True, kv_quantize="int8",
    )
    assert paged8._paged_decode_attention() is None  # gather fallback
    got = paged8.generate_batch(parity_reqs)
    for g, want in zip(got, contiguous_int8_tokens):
        assert g.tokens == want


def test_paged_int8_batch_matches_solo(registry):
    """Each batch row is token-identical to its own solo generate() on
    the same paged+int8 engine (the solo path runs the contiguous int8
    decode — same quantized stream, different layout)."""
    paged8 = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=True, kv_quantize="int8",
        decode_attention=pallas_decode_attention,
    )
    reqs = [
        GenerationRequest("tiny", "row a", max_new_tokens=8),
        GenerationRequest("tiny", "row b is different", max_new_tokens=10),
    ]
    batch = paged8.generate_batch(reqs)
    for r, req in zip(batch, reqs):
        assert r.tokens == paged8.generate(req).tokens


def test_paged_int8_on_tensor_parallel_engine(registry):
    """TP × paged × int8: codes/scales shard over the mesh heads
    (pool/pool_scale placements) and the int8 parts kernel runs through
    its shard_map rule, token-identical to the single-device paged+int8
    engine. The dryrun's tp=8 virtual-mesh leg runs the same
    composition at mesh width 8 (__graft_entry__.py)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.tp import (
        TensorParallelEngine,
    )

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 (virtual) devices")
    tp = TensorParallelEngine(
        mesh=build_mesh(MeshSpec.tp_only(2), devices=jax.devices()[:2]),
        registry=dict(registry),
        dtype=jnp.float32,
        paged_kv=True,
        kv_quantize="int8",
        decode_attention=pallas_decode_attention,
    )
    assert tp._paged_decode_attention(registry["tiny"]) is not None
    single = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=True, kv_quantize="int8",
        decode_attention=pallas_decode_attention,
    )
    reqs = [
        GenerationRequest("tiny", "sharded paged quantized row",
                          max_new_tokens=8),
        GenerationRequest("tiny", "another longer sharded paged quantized "
                          "row here", max_new_tokens=14),
    ]
    got = [r.tokens for r in tp.generate_batch(reqs)]
    want = [r.tokens for r in single.generate_batch(reqs)]
    assert got == want


# -- admission --------------------------------------------------------------
MIXED_FLEET_LENS = (26, 235, 913, 3697)  # the docs/PERF.md study mix


def _admitted_rows(monkeypatch, budget, **engine_kw):
    """Rows per decode window the estimator admits for a 256-row mixed
    fleet at ``budget`` — flagship shapes, pure arithmetic (no weights)."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    monkeypatch.setattr(je, "BATCH_KV_BUDGET_BYTES", budget)
    cfg = get_model_config("qwen2:1.5b")
    rows = 256
    ids = [[1] * MIXED_FLEET_LENS[i % 4] for i in range(rows)]
    reqs = [
        GenerationRequest(cfg.name, "x", max_new_tokens=256)
        for _ in range(rows)
    ]
    engine = JaxEngine(
        registry={cfg.name: cfg}, dtype=jnp.bfloat16,
        decode_attention=pallas_decode_attention, **engine_kw
    )
    return engine._max_batch_rows(cfg, reqs, ids)


@pytest.mark.parametrize("budget", [2_500_000_000, 4_500_000_000])
def test_equal_budget_admission_is_monotone_in_cache_density(
    monkeypatch, budget
):
    """THE capacity regression (VERDICT round-5 directive #4): at equal
    BATCH_KV_BUDGET_BYTES on the mixed-length study fleet, paged admits
    ≥ contiguous rows per decode window and paged+int8 admits ≥
    paged-bf16 — with the composition strictly widest at the default
    budget (the docs/PERF.md admission table's ladder)."""
    contiguous = _admitted_rows(monkeypatch, budget)
    paged = _admitted_rows(monkeypatch, budget, paged_kv=True)
    paged8 = _admitted_rows(
        monkeypatch, budget, paged_kv=True, kv_quantize="int8"
    )
    assert paged >= contiguous
    assert paged8 >= paged
    assert paged8 > contiguous  # the composition must actually pay off


def test_max_admission_rows_tracks_cache_density(registry):
    """The scheduler-facing probe: denser layouts admit wider fleets for
    the same anchor request, without loading any weights."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine as je

    cfg = get_model_config("qwen2:1.5b")
    req = GenerationRequest(
        cfg.name, "m" * 1800, max_new_tokens=256
    )  # ~1.8k-token prompt

    def probe(**kw):
        e = JaxEngine(
            registry={cfg.name: cfg}, dtype=jnp.bfloat16,
            decode_attention=pallas_decode_attention, **kw
        )
        assert not e._models  # estimate only — nothing loads
        return e.max_admission_rows(req)

    contiguous = probe()
    paged8 = probe(paged_kv=True, kv_quantize="int8")
    assert paged8 >= contiguous
    assert paged8 >= je.BATCH_MIN_SPLIT_ROWS


def test_scheduler_budget_aware_admission_uses_backend_estimate():
    """BatchScheduler raises a batch's cap to the backend's
    max_admission_rows estimate (and ignores a failing probe)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
        GenerationResult,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
    )

    class Backend:
        def __init__(self, rows):
            self.rows = rows
            self.batches = []

        def generate(self, request):
            return self._result(request)

        def generate_batch(self, requests):
            self.batches.append(len(requests))
            return [self._result(r) for r in requests]

        @staticmethod
        def _result(request):
            return GenerationResult(
                request=request, tokens=[1], text="x",
                prompt_tokens=1, generated_tokens=1,
                prefill_s=0.0, decode_s=0.0, total_s=0.0,
            )

        def max_admission_rows(self, request):
            if self.rows is None:
                raise RuntimeError("probe down")
            return self.rows

    backend = Backend(rows=64)
    sched = BatchScheduler(backend, max_batch=2, window_s=0.2)
    assert sched.budget_aware
    sched.start()
    try:
        import threading

        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(
                    sched.submit(
                        GenerationRequest("m", "p", max_new_tokens=1)
                    )
                )
            )
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        sched.stop()
    assert len(results) == 6
    # without the estimate the cap of 2 forces ≥3 batches; the raised
    # cap of 64 admits everything the window catches into fewer calls
    assert backend.batches and max(backend.batches) > 2

    # a failing probe falls back to the static cap, never to an error
    flaky = Backend(rows=None)
    sched2 = BatchScheduler(flaky, max_batch=4, window_s=0.05)
    probe_req = GenerationRequest("m", "p", max_new_tokens=1)
    assert sched2._admission_cap(
        type("T", (), {"request": probe_req})()
    ) == 4

    # explicit opt-out pins the static cap
    sched3 = BatchScheduler(Backend(rows=64), max_batch=4, budget_aware=False)
    assert not sched3.budget_aware
