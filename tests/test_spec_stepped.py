"""Batched speculative decoding inside stepped decode sessions (ISSUE 9).

The acceptance mechanics under test: per slice, every live row drafts k
tokens then ONE target forward scores its k+1 candidate positions, and
rows advance by their own longest-accepted-prefix length m ∈ [1, k+1] —
so retirement, EOS clipping, budgets, joins and page accounting all move
at per-row variable stride. Parity discipline is the usual one: every
row's stream must be bit-identical to plain greedy decode on the same
engine configuration (float32 pins, per the numerics caveat in
engine/speculative.py), whatever the cache layout.
"""

import dataclasses

import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)


@pytest.fixture(scope="module")
def registry():
    tiny = get_model_config("qwen2:1.5b").tiny(max_seq_len=1024)
    return {
        "tiny": tiny,
        # a genuinely different (weaker) draft exercises the rejection
        # path; same vocab by construction
        "tiny-d": dataclasses.replace(tiny, n_layers=1),
        # an alias of the target config: identical seeded weights, so
        # every draft is accepted — the acceptance-friendly arm
        "tiny-same": tiny,
    }


@pytest.fixture(scope="module")
def plain(registry):
    return JaxEngine(registry=dict(registry), dtype=jnp.float32)


def _spec_engine(registry, draft="tiny-d", k=3, **kwargs):
    return JaxEngine(
        registry=dict(registry),
        dtype=jnp.float32,
        speculative={"tiny": (draft, k)},
        **kwargs,
    )


def _drain(session, max_steps=8, limit=300):
    out = []
    for _ in range(limit):
        if not session.active:
            break
        out.extend(session.step(max_steps))
    assert not session.active, "session did not drain"
    return out


LAYOUTS = [
    pytest.param(False, None, id="contig-bf16"),
    pytest.param(False, "int8", id="contig-int8"),
    pytest.param(True, None, id="paged-bf16"),
    pytest.param(True, "int8", id="paged-int8"),
]


@pytest.mark.parametrize("paged,kv", LAYOUTS)
def test_spec_session_parity_all_layouts_with_join(registry, paged, kv):
    """The tentpole invariant: a speculating session — mid-flight joiner
    included — emits exactly the plain greedy stream of the same engine
    configuration, on all four cache layouts (int8 target KV composes:
    the former kv_quantize × speculative exclusion is retired)."""
    eng = _spec_engine(registry, paged_kv=paged, kv_quantize=kv)
    exp = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=paged, kv_quantize=kv,
    )
    reqs = [
        GenerationRequest("tiny", "alpha prompt", max_new_tokens=12),
        GenerationRequest(
            "tiny", "the longer second row runs on", max_new_tokens=24,
            stop_at_eos=False, seed=2,
        ),
    ]
    sess = eng.decode_open(reqs, reserve_rows=4)
    assert sess.spec is not None, "session did not speculate"
    sess.step(4)
    joiner = GenerationRequest("tiny", "late joiner", max_new_tokens=10, seed=3)
    assert sess.can_join(joiner)
    sess.join(joiner)
    results = {id(r.request): r for r in _drain(sess)}
    for r in reqs + [joiner]:
        assert results[id(r)].tokens == exp._generate_plain(r).tokens, (
            f"diverged: paged={paged} kv={kv} prompt={r.prompt!r}"
        )
        spec = results[id(r)].extras["spec"]
        assert spec["k"] == 3 and spec["draft_model"] == "tiny-d"
        assert spec["rounds"] >= 1
        assert 0 <= spec["accepted"] <= spec["drafted"]


def test_spec_rows_advance_multiple_tokens_per_round(registry):
    """With an identical-weights draft every proposal is accepted: rows
    advance ~k+1 tokens per target forward — the amortization the mode
    exists for — and the stream still equals plain greedy decode."""
    eng = _spec_engine(registry, draft="tiny-same", k=4)
    plain_eng = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    req = GenerationRequest(
        "tiny", "perfect acceptance", max_new_tokens=33, stop_at_eos=False
    )
    sess = eng.decode_open([req])
    res = _drain(sess)[0]
    assert res.tokens == plain_eng._generate_plain(req).tokens
    spec = res.extras["spec"]
    # 32 decode tokens in ≤ ceil(32/5)+1 rounds; acceptance ≈ 1
    assert spec["rounds"] <= 8, spec
    assert spec["accepted"] >= spec["rounds"] * 3, spec


def test_spec_paged_bills_no_slack_and_restores_exactly(registry):
    """ISSUE 10: the 2k+2 slack page bill is GONE — a paged speculative
    row bills exactly the plain-decode page count (the verify keeps
    candidates in the scratch/side leaves, never in out-of-budget pool
    slots), and retire/cancel/close restore the pool free count EXACTLY
    — on bf16 and int8 pools."""
    for kv in (None, "int8"):
        eng = _spec_engine(registry, k=3, paged_kv=True, kv_quantize=kv)
        plain_eng = JaxEngine(
            registry=dict(registry), dtype=jnp.float32,
            paged_kv=True, kv_quantize=kv,
        )
        anchor = GenerationRequest(
            "tiny", "anchor decodes on", max_new_tokens=40, stop_at_eos=False
        )
        sess = eng.decode_open([anchor], reserve_rows=4)
        assert sess.spec is not None
        assert not hasattr(sess, "spec_slack")  # the attribute is retired
        plain_sess = plain_eng.decode_open([anchor], reserve_rows=4)
        # slack-free billing: spec row == plain row == ceil((s+mnt)/page)
        assert (
            sess._pages_needed(100, 40)
            == plain_sess._pages_needed(100, 40)
            == -(-(100 + 40) // 128)
        )
        # the kernel-less native mode carries its candidates in the
        # scratch leaves (head-layout mini cache), visible in debug
        st = sess.debug_state()
        assert st["spec"]["verify_mode"] == "native"
        assert st["spec"]["scratch_bytes"] > 0
        assert "scratch_k" in sess.carry and "scratch_v" in sess.carry
        plain_sess.close()
        free0 = sess.pool.free_pages
        sess.step(4)
        victim = GenerationRequest(
            "tiny", "victim row", max_new_tokens=30, stop_at_eos=False, seed=5
        )
        assert sess.can_join(victim)
        sess.join(victim)
        victim_pages = next(
            row.pages
            for row in sess.rows
            if row is not None and row.request is victim
        )
        assert sess.pool.free_pages == free0 - len(victim_pages)
        sess.step(4)
        # cancel restores the victim's slack-free pages exactly
        assert sess.cancel(victim)
        assert sess.pool.free_pages == free0
        results = _drain(sess)
        assert results[0].tokens == plain_eng._generate_plain(anchor).tokens
        sess.close()
        assert sess.pool.free_pages == sess.pool.n_pages - 1  # parking only


def test_spec_chunked_joiner_prefills_draft_too(registry):
    """A long-prompt joiner into a speculating session: its TARGET
    prefill chunks interleave as usual AND its DRAFT prefill rides the
    same chunk machinery (one chunk forward per join_step call) — the
    committed row then speculates and stays solo-identical."""
    eng = _spec_engine(registry, k=3)
    plain_eng = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    anchor = GenerationRequest(
        "tiny", "a" * 120, max_new_tokens=40, stop_at_eos=False, seed=1
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    assert sess.spec is not None
    sess.step(4)
    joiner = GenerationRequest("tiny", "j" * 100, max_new_tokens=12, seed=3)
    assert sess.can_join(joiner)
    pj = sess.join_begin(joiner, chunk_tokens=32)
    assert len(pj.chunks) >= 3  # 101 prompt ids at 32-token chunks
    assert len(pj.draft_chunks) >= 3  # the draft prefills the FULL prompt
    steps = 0
    done = False
    while not done:
        done = sess.join_step(pj)
        steps += 1
        if not done:
            sess.step(2)  # the anchor keeps speculating between chunks
    assert steps >= len(pj.chunks) + len(pj.draft_chunks)
    sess.join_commit(pj)
    results = {id(r.request): r for r in _drain(sess)}
    assert results[id(anchor)].tokens == plain_eng._generate_plain(anchor).tokens
    assert results[id(joiner)].tokens == plain_eng._generate_plain(joiner).tokens
    assert results[id(joiner)].extras["spec"]["rounds"] >= 1


STACKED_KV = [
    pytest.param(None, id="stacked-bf16"),
    pytest.param("int8", id="stacked-int8"),
]


def _stacked_spec_engine(registry, kv, **kwargs):
    """A paged spec engine in STACKED-HYBRID mode on CPU: injecting the
    contiguous decode kernel flips _specialised_kernels_enabled, so the
    paged wrapper (and its multi-query twins, interpret mode) engages —
    the test_paged_int8.py convention."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.ops.pallas_attention import (
        pallas_decode_attention,
    )

    return _spec_engine(
        registry, paged_kv=True, kv_quantize=kv,
        decode_attention=pallas_decode_attention, **kwargs,
    )


@pytest.mark.parametrize("kv", STACKED_KV)
def test_spec_stacked_hybrid_paged_parity_with_join_and_cancel(registry, kv):
    """The newly-un-excluded layout (ISSUE 10): a speculating session in
    STACKED-HYBRID paged mode — the multi-query parts kernel streams
    each row's prompt pages once for all k+1 candidate positions,
    candidates land in the side caches — stays bit-identical to plain
    greedy decode on the same engine configuration through mid-flight
    joins and cancellation, with EXACT pool free-count restoration."""
    eng = _stacked_spec_engine(registry, kv)
    exp = JaxEngine(
        registry=dict(registry), dtype=jnp.float32,
        paged_kv=True, kv_quantize=kv,
    )
    anchor = GenerationRequest(
        "tiny", "stacked anchor runs on", max_new_tokens=24,
        stop_at_eos=False,
    )
    short = GenerationRequest(
        "tiny", "short stacked row", max_new_tokens=8, seed=2
    )
    sess = eng.decode_open([anchor, short], reserve_rows=4)
    assert sess.spec is not None and sess.stacked, (
        "session did not take the stacked×spec path"
    )
    # stacked spec rows bill PROMPT-ONLY pages — same as plain stacked
    plain_sess = exp.decode_open([anchor], reserve_rows=2)
    del plain_sess  # plain CPU engine has no kernel: compare by rule
    assert sess._pages_needed(100, 40) == -(-100 // 128)
    assert sess.debug_state()["spec"]["verify_mode"] == "native"
    assert sess.debug_state()["spec"]["scratch_bytes"] > 0
    free0 = sess.pool.free_pages
    sess.step(2)
    joiner = GenerationRequest(
        "tiny", "stacked late joiner", max_new_tokens=10, seed=3
    )
    victim = GenerationRequest(
        "tiny", "stacked victim row", max_new_tokens=30,
        stop_at_eos=False, seed=5,
    )
    assert sess.can_join(joiner)
    sess.join(joiner)
    assert sess.can_join(victim)
    sess.join(victim)
    sess.step(2)
    # cancellation restores the victim's pages exactly, mid-flight
    victim_pages = next(
        row.pages
        for row in sess.rows
        if row is not None and row.request is victim
    )
    assert sess.cancel(victim)
    del victim_pages
    results = {id(r.request): r for r in _drain(sess)}
    for r in (anchor, short, joiner):
        assert results[id(r)].tokens == exp._generate_plain(r).tokens, (
            f"stacked spec diverged: kv={kv} prompt={r.prompt!r}"
        )
        assert results[id(r)].extras["spec"]["rounds"] >= 1
    sess.close()
    assert sess.pool.free_pages == sess.pool.n_pages - 1  # parking only
    del free0


def test_spec_stacked_vs_scratch_modes_agree(registry):
    """The two native verify modes — stacked (multi-query kernel) and
    kernel-less (scratch + table commit) — emit the same stream for the
    same request: the mode is an execution detail, not a numerics
    choice (float32 pins, per the module caveat)."""
    req = GenerationRequest(
        "tiny", "mode agreement probe", max_new_tokens=20,
        stop_at_eos=False,
    )
    stacked_eng = _stacked_spec_engine(registry, None)
    scratch_eng = _spec_engine(registry, paged_kv=True)
    s1 = stacked_eng.decode_open([req])
    assert s1.stacked
    s2 = scratch_eng.decode_open([req])
    assert not s2.stacked
    r1 = _drain(s1)[0]
    r2 = _drain(s2)[0]
    assert r1.tokens == r2.tokens


def test_spec_session_admits_sampled_rows_and_joiners(registry):
    """ISSUE 16 retires the greedy-only gate: sampled anchors SPECULATE
    (rejection resampling), a speculating session's can_join admits a
    sampled joiner, and only hotter-than-spec_temperature_max rows
    still defer to a plain session."""
    eng = _spec_engine(registry)
    sampled = GenerationRequest(
        "tiny", "sampled anchor", max_new_tokens=8, temperature=0.9, seed=5
    )
    sess = eng.decode_open([sampled])
    assert sess.spec is not None
    res = _drain(sess)[0]
    assert res.extras["spec"]["rounds"] >= 1
    assert res.extras["spec"]["source"] == "model"

    hot = GenerationRequest(
        "tiny", "too hot to draft", max_new_tokens=8, temperature=5.0, seed=6
    )
    hot_sess = eng.decode_open([hot])
    assert hot_sess.spec is None  # above the default 2.0 cap: plain
    _drain(hot_sess)

    greedy = GenerationRequest(
        "tiny", "greedy anchor", max_new_tokens=24, stop_at_eos=False
    )
    sess2 = eng.decode_open([greedy], reserve_rows=4)
    assert sess2.spec is not None
    sampled_joiner = GenerationRequest(
        "tiny", "sampled joiner", max_new_tokens=8, temperature=0.7, seed=7
    )
    assert sess2.can_join(sampled_joiner)
    sess2.join(sampled_joiner)
    hot_joiner = GenerationRequest(
        "tiny", "hot joiner", max_new_tokens=8, temperature=5.0
    )
    assert not sess2.can_join(hot_joiner)
    results = {id(r.request): r for r in _drain(sess2)}
    assert results[id(sampled_joiner)].extras["spec"]["rounds"] >= 1


def test_spec_adaptive_fallback_preserves_parity(registry):
    """The adaptive policy: a weak draft under a high floor first
    SHRINKS the draft length (llm_spec_k_adapt_total{direction=down},
    ISSUE 19) and only falls the session back to plain decode from
    k=1 — llm_spec_fallback_total moves, extras mark fallback, and the
    stream is STILL the plain greedy stream at every k along the way
    (both modes emit the target's argmax tokens)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )

    def snap(name):
        return sum(
            v
            for k, v in REGISTRY.snapshot().get(name, {}).items()
            if "source=model" in k
        )

    eng = _spec_engine(registry, spec_accept_floor=0.95)
    plain_eng = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    req = GenerationRequest(
        "tiny", "long fallback run", max_new_tokens=120, stop_at_eos=False
    )
    before = snap("llm_spec_fallback_total")
    down0 = snap("llm_spec_k_adapt_total")
    sess = eng.decode_open([req])
    assert sess.spec is not None and sess.spec["k"] == 3
    res = _drain(sess, max_steps=4)[0]
    assert sess.spec is None and sess.spec_fallback
    assert res.extras["spec"]["fallback"] is True
    assert res.tokens == plain_eng._generate_plain(req).tokens
    assert snap("llm_spec_fallback_total") == before + 1
    # the shrink stage ran before the fallback: k stepped 3 -> 1
    assert snap("llm_spec_k_adapt_total") >= down0 + 1


def test_spec_session_through_continuous_scheduler(registry):
    """End-to-end through the serving stack: the continuous scheduler
    opens a speculating session, a staggered arrival joins it, results
    carry the spec extras, and every stream is plain-greedy identical.
    The scheduler's decode_open floor override rides along."""
    import threading

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        ContinuousScheduler,
    )

    eng = _spec_engine(registry, draft="tiny-same", k=3)
    plain_eng = JaxEngine(registry=dict(registry), dtype=jnp.float32)
    anchor = GenerationRequest(
        "tiny", "scheduler anchor", max_new_tokens=48, stop_at_eos=False
    )
    late = GenerationRequest("tiny", "late arrival", max_new_tokens=8, seed=2)
    # warm compiled shapes outside the scheduler
    warm = eng.decode_open([anchor, late], reserve_rows=4)
    _drain(warm)
    sched = ContinuousScheduler(eng, slice_steps=4, spec_accept_floor=0.05)
    sched.start()
    results = {}
    try:
        def submit(req):
            results[id(req)] = sched.submit(req)

        t1 = threading.Thread(target=submit, args=(anchor,))
        t2 = threading.Thread(target=submit, args=(late,))
        t1.start()
        t2.start()
        t1.join(timeout=120)
        t2.join(timeout=120)
    finally:
        sched.stop()
    assert set(results) == {id(anchor), id(late)}
    for req in (anchor, late):
        assert results[id(req)].tokens == plain_eng._generate_plain(req).tokens
        assert results[id(req)].extras["spec"]["rounds"] >= 1


def test_spec_debug_state_reports_session_and_rows(registry):
    eng = _spec_engine(registry, k=3)
    req = GenerationRequest(
        "tiny", "debug probe", max_new_tokens=24, stop_at_eos=False
    )
    sess = eng.decode_open([req])
    sess.step(4)
    state = sess.debug_state()
    assert state["spec"]["active"] is True
    assert state["spec"]["draft_model"] == "tiny-d"
    assert state["spec"]["k"] == 3
    assert state["spec"]["rounds_total"] >= 1
    assert "spec_rounds" in state["rows"][0]
    _drain(sess)


def test_spec_disabled_when_draft_cache_cannot_fit(registry):
    """A budget whose draft cache would exceed the draft's max_seq_len
    serves the session PLAIN (never fails a request plain decode would
    serve) — the solo path's fallback rule, stepped."""
    small = {
        "tiny": get_model_config("qwen2:1.5b").tiny(),  # max_seq_len 256
        "tiny-d": dataclasses.replace(
            get_model_config("qwen2:1.5b").tiny(), n_layers=1
        ),
    }
    eng = JaxEngine(
        registry=small, dtype=jnp.float32, speculative={"tiny": ("tiny-d", 3)}
    )
    req = GenerationRequest(
        "tiny", "big budget", max_new_tokens=128, stop_at_eos=False
    )
    sess = eng.decode_open([req])
    assert sess.spec is None  # margin would blow max_seq_len: plain
    res = _drain(sess)
    assert res[0].generated_tokens == 128


def test_solo_spec_emits_obs_and_nested_extras(registry):
    """Satellite: the solo path no longer drops rounds/accepted on the
    floor — extras['spec'] plus the llm_spec_* families move."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )

    eng = _spec_engine(registry, draft="tiny-same", k=4)
    before = (
        REGISTRY.snapshot()
        .get("llm_spec_rounds_total", {})
        .get("source=model", 0)
    )
    res = eng.generate(
        GenerationRequest(
            "tiny", "solo obs", max_new_tokens=17, stop_at_eos=False
        )
    )
    spec = res.extras["spec"]
    assert spec["rounds"] == res.extras["spec_rounds"]
    assert spec["accepted"] == res.extras["spec_accepted"]
    assert spec["drafted"] == spec["rounds"] * 4
    after = (
        REGISTRY.snapshot()
        .get("llm_spec_rounds_total", {})
        .get("source=model", 0)
    )
    assert after >= before + spec["rounds"]
