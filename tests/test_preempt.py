"""SLO tiers + mid-flight preemption (ISSUE 11).

Engine half: ``PagePool.swap_out/swap_in`` round-trips pages through
host memory with exact free-count and payload restoration (bf16 AND
int8 — codes + per-position scales), refcounted CoW pages are refused
by swap, and a preempted-then-resumed row's token stream is
bit-identical to an uninterrupted solo ``generate()`` on every cache
layout and under both policies (swap / recompute).

Scheduler half: per-tier FIFO ordering, a higher-tier ticket preempting
the youngest lower-tier live row when the session is full, the victim
completing after resume, starvation aging, and the monotonic-clock
regression pin (a wall-clock step must neither mass-expire nor
immortalize in-flight rows).
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import (
    FakeBackend,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
    PagePool,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    REGISTRY,
    SWAP_HOST_BYTES_G,
    SWAP_HOST_ROWS_G,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve import protocol
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
    ContinuousScheduler,
    _TierQueue,
    _Ticket,
)


# -- PagePool swap -------------------------------------------------------------
def _fill_pool_pages(pool, pages):
    """Write a recognizable payload into ``pages`` and return the host
    expectation, [N, L, Hkv, page, D]-chunk-shaped like a swap blob."""
    import numpy as np

    idx = jnp.asarray(pages, jnp.int32)
    if pool.quantized:
        qshape = pool.k["q"][:, idx].shape  # [L, N, Hkv, page, D]
        sshape = pool.k["s"][:, idx].shape
        kq = jnp.arange(np.prod(qshape), dtype=jnp.int32).reshape(qshape)
        kq = (kq % 251 - 125).astype(jnp.int8)
        ks = (
            jnp.arange(np.prod(sshape), dtype=jnp.float32).reshape(sshape)
            / 7.0
            + 0.5
        )
        pool.k = {
            "q": pool.k["q"].at[:, idx].set(kq),
            "s": pool.k["s"].at[:, idx].set(ks),
        }
        pool.v = {
            "q": pool.v["q"].at[:, idx].set(-kq),
            "s": pool.v["s"].at[:, idx].set(ks * 2.0),
        }
    else:
        shape = pool.k[:, idx].shape
        payload = jnp.arange(
            np.prod(shape), dtype=jnp.float32
        ).reshape(shape) / 3.0
        pool.k = pool.k.at[:, idx].set(payload.astype(pool.k.dtype))
        pool.v = pool.v.at[:, idx].set((-payload).astype(pool.v.dtype))
    return jax.device_get(
        jax.tree.map(lambda a: a[:, idx], (pool.k, pool.v))
    )


@pytest.mark.parametrize("quantized", [False, True])
def test_pagepool_swap_roundtrip_exact(quantized):
    pool = PagePool.create(
        n_layers=2, n_pages=8, n_kv_heads=2, d_head=4,
        page_size=4, quantized=quantized,
    )
    pages = pool.alloc(3)
    expect_k, expect_v = _fill_pool_pages(pool, pages)
    assert pool.free_pages == 5
    blob = pool.swap_out(pages)
    # exact free-count restoration: every swapped page is free again
    assert pool.free_pages == 8
    assert blob.n_pages == 3 and blob.nbytes > 0
    back = pool.swap_in(blob)
    assert pool.free_pages == 5
    import numpy as np

    got_k, got_v = jax.device_get(
        jax.tree.map(
            lambda a: a[:, jnp.asarray(back, jnp.int32)], (pool.k, pool.v)
        )
    )
    for exp, got in ((expect_k, got_k), (expect_v, got_v)):
        if quantized:
            np.testing.assert_array_equal(exp["q"], got["q"])
            np.testing.assert_array_equal(exp["s"], got["s"])
        else:
            np.testing.assert_array_equal(exp, got)


def test_pagepool_swap_refuses_shared_and_free_pages():
    pool = PagePool.create(
        n_layers=1, n_pages=4, n_kv_heads=1, d_head=4, page_size=4
    )
    pages = pool.alloc(2)
    pool.share(pages[:1])  # a CoW prefix reader
    with pytest.raises(ValueError, match="shared"):
        pool.swap_out(pages)
    # releasing the extra reader makes it swappable again
    pool.free(pages[:1])
    blob = pool.swap_out(pages)
    assert blob.n_pages == 2
    with pytest.raises(ValueError, match="free"):
        pool.swap_out(pages)  # already free → bookkeeping bug


def test_pagepool_swap_in_rejects_layout_mismatch():
    pool = PagePool.create(
        n_layers=1, n_pages=4, n_kv_heads=1, d_head=4, page_size=4
    )
    other = PagePool.create(
        n_layers=1, n_pages=4, n_kv_heads=1, d_head=4,
        page_size=4, quantized=True,
    )
    blob = pool.swap_out(pool.alloc(1))
    with pytest.raises(ValueError, match="layout"):
        other.swap_in(blob)


# -- stepped-session preempt/resume parity -------------------------------------
@pytest.fixture(scope="module")
def engines():
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny()}
    cache = {}

    def get(paged, kvq):
        key = (paged, kvq)
        if key not in cache:
            cache[key] = JaxEngine(
                registry=dict(registry),
                dtype=jnp.float32,
                paged_kv=paged,
                kv_quantize=kvq,
            )
        return cache[key]

    return get


def _host_gauges():
    return (
        SWAP_HOST_BYTES_G._default.value,
        SWAP_HOST_ROWS_G._default.value,
    )


@pytest.mark.parametrize(
    "paged,kvq,policy",
    [
        (False, None, "swap"),
        (True, None, "swap"),
        (False, "int8", "swap"),
        (True, "int8", "swap"),
        (False, None, "recompute"),
        (True, "int8", "recompute"),
    ],
    ids=[
        "contig-bf16-swap", "paged-bf16-swap", "contig-int8-swap",
        "paged-int8-swap", "contig-bf16-recompute", "paged-int8-recompute",
    ],
)
def test_preempt_resume_token_parity(engines, paged, kvq, policy):
    """A preempted-then-resumed row's stream is identical to solo
    generate(); companions are unperturbed; the pool free count and the
    host-swap gauges return exactly to their idle values."""
    eng = engines(paged, kvq)
    anchor = GenerationRequest(
        "tiny", "anchor keeps decoding", max_new_tokens=32,
        stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "victim of the overload", max_new_tokens=24,
        stop_at_eos=False, seed=7, priority=0,
    )
    solo_v = eng.generate(victim).tokens
    solo_a = eng.generate(anchor).tokens
    idle_bytes, idle_rows = _host_gauges()
    sess = eng.decode_open([anchor, victim], reserve_rows=4)
    # idle pool = every page free except the session's parking page
    pool_idle = sess.pool.n_pages - 1 if paged else None
    sess.step(4)
    free_before = sess.pool.free_pages if paged else None
    pr = sess.preempt(victim, policy=policy)
    assert pr is not None
    if paged:
        # every page the victim held is back on the free list
        assert sess.pool.free_pages == free_before + pr.n_own_pages + len(
            pr.shared_pages
        )
    if policy == "swap":
        assert pr.host_bytes > 0
        assert _host_gauges() == (idle_bytes + pr.host_bytes, idle_rows + 1)
    else:
        assert pr.host_bytes == 0 and pr.blob is None
    sess.step(4)  # the anchor decodes on while the victim is parked
    assert sess.can_resume(pr)
    pend = sess.resume_begin(pr, 64)
    while not sess.join_step(pend):
        pass
    sess.join_commit(pend)
    assert _host_gauges() == (idle_bytes, idle_rows)
    results = {}
    while sess.active:
        for res in sess.step(8):
            results[id(res.request)] = res
    assert results[id(victim)].tokens == solo_v
    assert results[id(anchor)].tokens == solo_a
    assert results[id(victim)].prompt_tokens == len(sess.tok.encode(victim.prompt))
    sess.close()
    if paged:
        assert sess.pool.free_pages == pool_idle


def test_preempt_during_pending_join(engines):
    """Preempting a live row while a chunked joiner holds a pending
    reservation: the joiner commits, the victim resumes, every stream
    stays solo-identical and close() restores the pool exactly."""
    eng = engines(True, None)
    anchor = GenerationRequest(
        "tiny", "anchor holds the session open for everyone",
        max_new_tokens=40, stop_at_eos=False,
    )
    victim = GenerationRequest(
        "tiny", "victim row", max_new_tokens=24, stop_at_eos=False, seed=3
    )
    joiner = GenerationRequest(
        "tiny", "j" * 90, max_new_tokens=12, seed=5
    )
    solo = {r: eng.generate(r).tokens for r in (anchor, victim, joiner)}
    sess = eng.decode_open([anchor, victim], reserve_rows=4)
    pool_idle = sess.pool.n_pages - 1  # the parking page stays held
    sess.step(4)
    pend_join = sess.join_begin(joiner, 32)  # mid-prefill reservation
    pr = sess.preempt(victim, policy="swap")
    assert pr is not None
    while not sess.join_step(pend_join):
        pass
    sess.join_commit(pend_join)
    assert sess.can_resume(pr)
    pend = sess.resume_begin(pr)
    while not sess.join_step(pend):
        pass
    sess.join_commit(pend)
    results = {}
    while sess.active:
        for res in sess.step(8):
            results[id(res.request)] = res
    for req, tokens in solo.items():
        assert results[id(req)].tokens == tokens, req.prompt[:16]
    sess.close()
    assert sess.pool.free_pages == pool_idle


def test_preempt_refuses_unknown_and_retired_rows(engines):
    eng = engines(True, None)
    req = GenerationRequest("tiny", "only row", max_new_tokens=6)
    sess = eng.decode_open([req], reserve_rows=2)
    stranger = GenerationRequest("tiny", "never admitted", max_new_tokens=4)
    assert sess.preempt(stranger) is None
    while sess.active:
        sess.step(8)
    assert sess.preempt(req) is None  # already retired
    sess.close()


def test_preempted_streaming_row_resumes_delta_cursor(engines):
    """A streaming victim's egress cursor survives the round trip: no
    token is delivered twice and none is lost."""
    eng = engines(True, None)
    anchor = GenerationRequest(
        "tiny", "anchor", max_new_tokens=30, stop_at_eos=False
    )
    victim = GenerationRequest(
        "tiny", "streamed victim", max_new_tokens=20,
        stop_at_eos=False, seed=11,
    )
    sess = eng.decode_open([anchor, victim], reserve_rows=4)
    sess.stream_tokens = True
    delivered = []
    sess.step(4)
    for request, tokens, _text in sess.stream_deltas():
        if request is victim:
            delivered.extend(tokens)
    pr = sess.preempt(victim, policy="swap")
    assert pr is not None and pr.streamed == len(delivered)
    sess.step(2)
    pend = sess.resume_begin(pr)
    while not sess.join_step(pend):
        pass
    sess.join_commit(pend)
    final = None
    while sess.active:
        retired = sess.step(4)
        for request, tokens, _text in sess.stream_deltas():
            if request is victim:
                delivered.extend(tokens)
        for res in retired:
            if res.request is victim:
                final = res
    assert final is not None
    assert delivered == final.tokens
    sess.close()


# -- scheduler: tier queue, preemption end-to-end ------------------------------
def test_tier_queue_orders_by_tier_then_fifo():
    q = _TierQueue()
    mk = lambda prio, tag: _Ticket(
        GenerationRequest("m", tag, max_new_tokens=4, priority=prio)
    )
    low1, low2 = mk(0, "low1"), mk(0, "low2")
    high = mk(2, "high")
    norm = mk(1, "norm")
    for t in (low1, low2, norm, high):
        q.put(t)
    assert q.qsize() == 4
    assert q.depths() == {0: 2, 1: 1, 2: 1}
    order = [q.get_nowait().request.prompt for _ in range(4)]
    assert order == ["high", "norm", "low1", "low2"]
    import queue as _queue

    with pytest.raises(_queue.Empty):
        q.get_nowait()


def _snapshot(name):
    fam = REGISTRY.snapshot().get(name) or {}
    return sum(v for v in fam.values() if isinstance(v, (int, float)))


def test_scheduler_preempts_lowest_tier_victim_and_resumes():
    """Full session under a full fake pool: the high-tier ticket is
    admitted by preempting the YOUNGEST low-tier row; the victim parks,
    resumes when the high-tier row retires, and completes with its full
    stream; counters + extras tell the story."""
    pre0 = _snapshot("llm_sched_preempted_total")
    res0 = _snapshot("llm_sched_resumed_total")
    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=200.0, simulate_delay=True, max_rows=2),
        preempt_policy="swap",
    )
    sched.start()
    results = {}

    def run(name, req):
        try:
            results[name] = sched.submit(req)
        except Exception as exc:  # noqa: BLE001
            results[name] = exc

    low_old = GenerationRequest(
        "m", "older low row", max_new_tokens=128, priority=0
    )
    low_young = GenerationRequest(
        "m", "younger low row", max_new_tokens=128, priority=0
    )
    high = GenerationRequest("m", "high tier", max_new_tokens=16, priority=2)
    threads = [threading.Thread(target=run, args=("low_old", low_old))]
    threads[0].start()
    time.sleep(0.15)
    threads.append(threading.Thread(target=run, args=("low_young", low_young)))
    threads[1].start()
    time.sleep(0.25)
    t_high = time.monotonic()
    threads.append(threading.Thread(target=run, args=("high", high)))
    threads[2].start()
    for t in threads:
        t.join(timeout=30)
    try:
        for name in ("low_old", "low_young", "high"):
            assert not isinstance(results.get(name), Exception), results
        # the high-tier ticket did not wait for a 128-token row to drain
        high_sched = results["high"].extras["sched"]
        assert high_sched["completion_s"] < 0.45, high_sched
        assert results["high"].generated_tokens == 16
        # the YOUNGEST low row was the victim; it resumed and completed
        young_sched = results["low_young"].extras["sched"]
        assert young_sched.get("preempted") == 1
        assert young_sched.get("resumed") is True
        assert "preempted" not in results["low_old"].extras["sched"]
        assert results["low_young"].generated_tokens == 128
        assert _snapshot("llm_sched_preempted_total") == pre0 + 1
        assert _snapshot("llm_sched_resumed_total") == res0 + 1
        # swap ledger drained: nothing host-resident once all completed
        assert SWAP_HOST_BYTES_G._default.value == 0
        assert SWAP_HOST_ROWS_G._default.value == 0
        assert t_high  # silence lint on the admission clock
    finally:
        sched.stop()


def test_scheduler_preempt_off_keeps_shed_only_behavior():
    """policy="off": a high-tier arrival waits for capacity instead of
    preempting — the pre-ISSUE-11 behavior (and the bench baseline)."""
    pre0 = _snapshot("llm_sched_preempted_total")
    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=400.0, simulate_delay=True, max_rows=1),
        preempt_policy="off",
    )
    sched.start()
    results = {}

    def run(name, req):
        results[name] = sched.submit(req)

    low = GenerationRequest("m", "low", max_new_tokens=96, priority=0)
    high = GenerationRequest("m", "high", max_new_tokens=8, priority=2)
    t1 = threading.Thread(target=run, args=("low", low))
    t1.start()
    time.sleep(0.1)
    t2 = threading.Thread(target=run, args=("high", high))
    t2.start()
    t1.join(timeout=30)
    t2.join(timeout=30)
    sched.stop()
    assert _snapshot("llm_sched_preempted_total") == pre0
    assert "preempted" not in (results["low"].extras or {}).get("sched", {})


def test_parked_victim_ages_up_a_tier():
    """Starvation protection: a parked victim's EFFECTIVE tier rises by
    one per preempt_max_wait_s waited (victim selection and the resume
    gate read it)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        _Parked,
    )

    sched = ContinuousScheduler(
        FakeBackend(), preempt_policy="swap", preempt_max_wait_s=0.05
    )
    ticket = _Ticket(
        GenerationRequest("m", "victim", max_new_tokens=4, priority=0)
    )
    entry = _Parked(ticket, {"policy": "swap", "host_bytes": 0})
    entry.t_parked -= 0.12  # parked for > 2 aging periods
    sched._age_parked([entry])
    assert ticket.priority >= 2
    # aging never lowers an already-raised tier
    sched._age_parked([entry])
    assert ticket.priority >= 2


def test_reap_and_aging_survive_wall_clock_step(monkeypatch):
    """Monotonic-clock pin (ISSUE 11 satellite): deadline reaping and
    preemption age math run on time.monotonic(); a wall-clock step —
    time.time() jumping a year — must neither mass-expire nor
    immortalize in-flight rows."""
    import cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler as sched_mod

    monkeypatch.setattr(
        sched_mod.time, "time", lambda: 4e9, raising=False
    )
    sched = ContinuousScheduler(
        FakeBackend(tokens_per_s=400.0, simulate_delay=True)
    )
    sched.start()
    try:
        # a generous deadline: the wall-clock jump must not shed it
        res = sched.submit(
            GenerationRequest(
                "m", "steady", max_new_tokens=16, deadline_ms=30_000
            )
        )
        assert res.generated_tokens == 16
    finally:
        sched.stop()


# -- wire ----------------------------------------------------------------------
def test_priority_wire_roundtrip_and_names():
    req = GenerationRequest("m", "p", max_new_tokens=4, priority=2)
    wire = protocol.request_to_wire(req)
    assert wire["x_priority"] == 2
    back = protocol.request_from_wire(wire)
    assert back.priority == 2
    # default tier stays OFF the wire (older servers keep working)
    plain = protocol.request_to_wire(
        GenerationRequest("m", "p", max_new_tokens=4)
    )
    assert "x_priority" not in plain
    # names and integers both parse; the server default fills absence
    named = protocol.request_from_wire(
        {"model": "m", "prompt": "p", "x_priority": "high"}
    )
    assert named.priority == protocol.PRIORITY_TIERS["high"]
    defaulted = protocol.request_from_wire(
        {"model": "m", "prompt": "p"}, default_priority=0
    )
    assert defaulted.priority == 0
    with pytest.raises(ValueError):
        protocol.parse_priority("urgent-ish")
    with pytest.raises(ValueError):
        protocol.parse_priority(-1)
    with pytest.raises(ValueError):
        GenerationRequest("m", "p", max_new_tokens=4, priority=-2)
