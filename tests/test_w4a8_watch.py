"""Toolchain watch: Mosaic i8 elementwise support (VERDICT round-3 #8).

The int4 decode kernel is VPU-bound on its nibble unpack because Mosaic
does not legalize ``arith.shli``/``arith.muli`` on i8 vectors (it lays
i8 out 4-per-lane but lowers only a sparse op set) — reproduced by
``scripts/w4a8_probe.py`` and documented in docs/PERF.md. The day the
toolchain grows that support, a w4a8 kernel (~3 VPU ops/packed byte,
int8 MXU dots) becomes expressible and the projected int4 body drops to
~2.0–2.2 ms/step, putting int4 AHEAD of int8.

This test pins the watch into the suite: it attempts to COMPILE the
probe's w4a8 kernel for the TPU backend and is expected to fail with the
Mosaic legalization error. ``strict=True`` makes an XPASS a loud suite
failure — the signal to remeasure int4 and claim the projected win the
week it becomes possible. On CPU runs (the hermetic suite forces
``JAX_PLATFORMS=cpu``; Mosaic lowering needs a real TPU client) the test
skips.
"""

import importlib.util
from pathlib import Path

import pytest


@pytest.mark.xfail(
    reason="Mosaic does not legalize i8 elementwise shifts/muls yet "
    "(scripts/w4a8_probe.py; docs/PERF.md) - an XPASS means the "
    "toolchain grew support: remeasure int4 with the w4a8 kernel",
    strict=True,
)
def test_w4a8_kernel_compiles_on_tpu():
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend for Mosaic lowering")
    import jax.numpy as jnp

    spec = importlib.util.spec_from_file_location(
        "w4a8_probe",
        Path(__file__).parent.parent / "scripts" / "w4a8_probe.py",
    )
    probe = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(probe)

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.quantize import (
        quantize_tensor_int4,
    )

    in_dim, out_dim = 1536, 8960
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (in_dim, out_dim), jnp.float32) * 0.05
    leaf = quantize_tensor_int4(w)
    x = jax.random.normal(key, (probe.M, in_dim), jnp.bfloat16)
    # compile (not just trace): Mosaic legalization happens at lowering
    jax.jit(probe.w4a8_matmul).lower(x, leaf["q4"], leaf["s"]).compile()
