"""RadixPrefixStore (ISSUE 14): the radix tree over refcounted page
runs with host-RAM spill — data-structure behavior (longest match, node
splitting, LRU budgets) and the byte-/free-count-exact spill round-trip
on bf16 AND int8 pool layouts.

Session-level integration (joiner hits, parity, preemption interplay)
is pinned in tests/test_prefix.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
    JaxEngine,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
    PagePool,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.radix_store import (
    RadixPrefixStore,
    STORE_EVICTIONS_C,
    STORE_RESTORES_C,
    STORE_SPILLS_C,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)

PAGE = 128
L, HKV, D = 1, 1, 4


def _pool(n_pages=16, quantized=False):
    return PagePool.create(
        n_layers=L,
        n_pages=n_pages,
        n_kv_heads=HKV,
        d_head=D,
        page_size=PAGE,
        quantized=quantized,
    )


def _seed(n_tokens, base=0.0):
    k = np.arange(n_tokens, dtype=np.float32).reshape(1, 1, n_tokens, 1)
    k = np.broadcast_to(k + base, (L, HKV, n_tokens, D)).copy()
    return k, k + 0.5


def _publish(store, pool, ids, base=0.0, model="m"):
    """Publish like a session row would: alloc the prompt's full pages
    (the 'row's' references), publish (the store adds its own), then
    retire the row (free its refs) — the store's refs remain."""
    k, v = _seed(len(ids), base)
    full = len(ids) // PAGE
    pages = pool.alloc(full) if full else []
    store.publish(model, ids, k, v, pages, pool)
    if pages:
        pool.free(pages)
    return pages


# -- tree shape ----------------------------------------------------------------


def test_longest_match_and_partial_edge():
    store = RadixPrefixStore()
    store.attach_pool("m", None)
    k, v = _seed(4)
    store.publish("m", [1, 2, 3, 4], k, v)
    k, v = _seed(3)
    store.publish("m", [1, 2, 9], k, v)
    assert store.match_len("m", [1, 2, 3, 5, 6]) == 3
    assert store.match_len("m", [7, 8]) == 0
    assert store.match_len("other", [1, 2]) == 0
    # publishing [1,2,9] split the first path at depth 2
    state = store.debug_state()
    assert state["nodes"] == 3 and state["depth"] == 4


def test_publish_covered_refreshes_instead_of_inserting():
    store = RadixPrefixStore()
    store.attach_pool("m", None)
    k, v = _seed(4)
    assert store.publish("m", [1, 2, 3, 4], k, v) is True
    k, v = _seed(3)
    assert store.publish("m", [1, 2, 3], k, v) is False  # covered
    assert store.debug_state()["nodes"] == 1


def test_seed_concatenates_across_split_segments():
    store = RadixPrefixStore()
    store.attach_pool("m", None)
    ids_a = list(range(10))
    k, v = _seed(10)
    store.publish("m", ids_a, k, v)
    ids_b = list(range(6)) + [99, 98]
    kb, vb = _seed(8, base=100.0)
    store.publish("m", ids_b, kb, vb)  # splits at depth 6
    assert store.debug_state()["nodes"] == 3
    got_k, got_v = store.seed("m", ids_a, 10)
    want_k, want_v = _seed(10)
    np.testing.assert_array_equal(got_k, want_k)
    np.testing.assert_array_equal(got_v, want_v)
    # the diverged branch's tail positions come from ITS slab
    got_k, _ = store.seed("m", ids_b, 8)
    np.testing.assert_array_equal(got_k[:, :, 6:], kb[:, :, 6:])


def test_node_capacity_evicts_lru_leaves():
    store = RadixPrefixStore(capacity=2)
    store.attach_pool("m", None)
    ev0 = STORE_EVICTIONS_C.labels().value
    for i, base in ((1, 0.0), (2, 10.0), (3, 20.0)):
        k, v = _seed(2, base)
        store.publish("m", [i, i], k, v)
        store.touch("m", [1, 1])  # keep the first entry hot
    assert store.debug_state()["nodes"] == 2
    assert STORE_EVICTIONS_C.labels().value > ev0
    assert store.match_len("m", [1, 1]) == 2  # the hot path survived


# -- page runs, splitting, spill/restore ---------------------------------------


@pytest.mark.parametrize("quantized", [False, True], ids=["bf16", "int8"])
def test_publish_spill_restore_evict_is_pool_exact(quantized):
    """The ISSUE-14 acceptance invariant at the store level: publish →
    spill (host gauge rises, HBM pages freed) → restore (fresh pages)
    → evict returns the pool and the store's byte ledgers exactly to
    their idle values, with the restored payload BIT-IDENTICAL."""
    pool = _pool(quantized=quantized)
    store = RadixPrefixStore()
    store.attach_pool("m", pool)
    free_idle = pool.free_pages
    host_idle = store.host_bytes_held
    ids = list(range(260))  # 2 full pages + a partial
    pages = pool.alloc(2)
    k, v = _seed(260)
    # write real payload into the publisher's pages so the spill blob
    # round-trip is checkable bit-for-bit
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.paged_kv import (
        _paginate,
        quantize_chunks,
        scatter_pages,
    )

    ck = _paginate(jnp.asarray(k), 256, PAGE)
    cv = _paginate(jnp.asarray(v), 256, PAGE)
    if quantized:
        ck, cv = quantize_chunks(ck, cv)
    pool.k, pool.v = scatter_pages(
        pool.k, pool.v, jnp.asarray(pages, jnp.int32), ck, cv
    )
    want_k = np.asarray(
        pool.k["q"][:, pages] if quantized else pool.k[:, pages]
    ).copy()
    store.publish("m", ids, k, v, pages, pool)
    pool.free(pages)  # the publisher row retires
    assert store.hbm_pages_held == 2
    assert pool.free_pages == free_idle - 2  # store holds them
    # SPILL (cold): pages leave the device, host bytes rise
    spills0 = STORE_SPILLS_C.labels().value
    store.detach_pool("m", pool)
    assert STORE_SPILLS_C.labels().value == spills0 + 1
    assert store.hbm_pages_held == 0
    assert pool.free_pages == free_idle  # swap freed them
    assert store.host_bytes_held > host_idle
    # RESTORE on hit (fresh pool attach, fresh pages)
    restores0 = STORE_RESTORES_C.labels().value
    store.attach_pool("m", pool)
    assert store.restore("m", ids, 260)
    assert STORE_RESTORES_C.labels().value == restores0 + 1
    run = store.hbm_run("m", ids)
    assert len(run) == 2
    assert pool.free_pages == free_idle - 2
    got_k = np.asarray(
        pool.k["q"][:, run] if quantized else pool.k[:, run]
    )
    np.testing.assert_array_equal(got_k, want_k)  # bit-exact round trip
    # EVICT: everything returns to idle
    store.release_all()
    assert pool.free_pages == free_idle
    assert store.host_bytes_held == 0
    assert store.hbm_pages_held == 0


def test_split_divides_page_run_between_top_and_bottom():
    pool = _pool()
    store = RadixPrefixStore()
    store.attach_pool("m", pool)
    ids = list(range(300))  # 2 full pages
    pages = _publish(store, pool, ids)
    # diverge at token 200 (inside page 1): top keeps page 0, the old
    # node keeps page 1, the new leaf owns nothing (tail < 1 page)
    ids_b = list(range(200)) + [999] * 30
    _publish(store, pool, ids_b, base=50.0)
    assert store.debug_state()["nodes"] == 3
    run_a = store.hbm_run("m", ids)
    assert run_a == pages  # full original run reassembled across nodes
    run_b = store.hbm_run("m", ids_b)
    assert run_b == pages[:1]  # the shared page only
    store.release_all()
    assert pool.free_pages == pool.n_pages


def test_hbm_budget_spills_cold_nodes():
    pool = _pool(n_pages=32)
    page_bytes = pool.payload_nbytes() // pool.n_pages
    store = RadixPrefixStore(hbm_bytes=2 * page_bytes)
    store.attach_pool("m", pool)
    _publish(store, pool, list(range(256)), base=0.0)  # 2 pages, cold
    spills0 = STORE_SPILLS_C.labels().value
    _publish(store, pool, [7] + list(range(300, 555)), base=9.0)  # 2 more
    # over budget → the LRU-cold first node spilled to host
    assert STORE_SPILLS_C.labels().value > spills0
    assert store.hbm_pages_held <= 2
    assert store.host_bytes_held > 0
    state = store.debug_state()
    assert state["tiers"]["host"] >= 1
    store.release_all()
    assert pool.free_pages == pool.n_pages


def test_host_budget_evicts_lru_leaves():
    store = RadixPrefixStore(host_bytes=1)  # practically nothing fits
    store.attach_pool("m", None)
    k, v = _seed(8)
    store.publish("m", list(range(8)), k, v)
    # seed bytes alone blow the budget → the leaf is evicted outright
    assert store.debug_state()["nodes"] == 0
    assert store.host_bytes_held == 0


def test_session_scope_drops_tree_at_detach():
    pool = _pool()
    store = RadixPrefixStore(scope="session")
    store.attach_pool("m", pool)
    _publish(store, pool, list(range(256)))
    assert store.debug_state()["nodes"] == 1
    store.detach_pool("m", pool)
    assert store.debug_state()["nodes"] == 0
    assert pool.free_pages == pool.n_pages  # refs released, not spilled
    assert store.host_bytes_held == 0


def test_shared_pages_are_released_not_spilled_at_detach():
    """A reader still mapping a store page at detach (abnormal close
    order) blocks the swap — the store demotes to seed tier and drops
    its reference; the reader's mapping stays valid."""
    pool = _pool()
    store = RadixPrefixStore()
    store.attach_pool("m", pool)
    _publish(store, pool, list(range(256)))
    run = store.hbm_run("m", list(range(256)))
    pool.share(run)  # a live row still reads the pages
    spills0 = STORE_SPILLS_C.labels().value
    store.detach_pool("m", pool)
    assert STORE_SPILLS_C.labels().value == spills0  # swap refused
    assert all(pool.refcount(p) == 1 for p in run)  # reader keeps its ref
    pool.free(run)
    assert pool.free_pages == pool.n_pages


# -- engine-session restore path (page rebuild without a blob) -----------------


def test_paged_hit_rebuilds_pages_from_seed_when_blob_gone():
    """A node demoted to seed tier (no blob) still backs a paged hit:
    the pages rebuild from the seed slab through the same
    paginate→quantize path that wrote the originals — joiner parity
    holds (the real-session end-to-end check)."""
    registry = {"tiny": get_model_config("qwen2:1.5b").tiny(max_seq_len=512)}
    eng = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True,
        prefix_share=True,
    )
    plain = JaxEngine(
        registry=dict(registry), dtype=jnp.float32, paged_kv=True
    )
    shared = "s" * 140
    anchor = GenerationRequest(
        "tiny", shared + " anchor", max_new_tokens=16,
        stop_at_eos=False, seed=1,
    )
    sess = eng.decode_open([anchor], reserve_rows=4)
    while sess.active:
        sess.step(8)
    sess.close()
    # strip the blobs: every host node degrades to seed tier
    for model in list(eng.prefix_store._trees):
        for node in eng.prefix_store._nodes_of(model):
            if node.blob is not None:
                eng.prefix_store._host_bytes_used -= int(node.blob.nbytes)
                node.blob = None
    a2 = GenerationRequest(
        "tiny", "x" * 170 + " fresh", max_new_tokens=16,
        stop_at_eos=False, seed=2,
    )
    sess2 = eng.decode_open([a2], reserve_rows=4)
    sess2.step(2)
    joiner = GenerationRequest(
        "tiny", shared + " rebuilt tail", max_new_tokens=10, seed=7
    )
    pj = sess2.join_begin(joiner, chunk_tokens=32)
    assert pj.hit_tokens > 0 and pj.shared_pages >= 1
    while not sess2.join_step(pj):
        pass
    sess2.join_commit(pj)
    results = {}
    while sess2.active:
        for res in sess2.step(8):
            results[id(res.request)] = res
    assert results[id(joiner)].tokens == plain.generate(joiner).tokens
    sess2.close()


# -- routing digest (ISSUE 19) -------------------------------------------------


def test_digest_bounded_under_large_store():
    """The /healthz digest must stay a bounded summary no matter how
    big the store grows: ≤ DIGEST_MAX_PREFIXES entries of
    ≤ DIGEST_MAX_HASHES chunk hashes each, freshest prefixes first —
    a 10k-node store and a 16-node store publish the same shape."""
    import json as _json

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.radix_store import (
        DIGEST_MAX_HASHES,
        DIGEST_MAX_PREFIXES,
        prefix_chunk_hashes,
    )

    store = RadixPrefixStore(capacity=20_000)
    k2, v2 = _seed(2)
    for i in range(10_000):
        store.publish("m", [i * 7 + 1, i * 7 + 2], k2, v2)
    # one deep spine: 40 full pages — the hash list must cap at 16
    deep = list(range(1, PAGE * 40 + 1))
    kd, vd = _seed(len(deep))
    store.publish("m", deep, kd, vd)
    assert len(store._nodes_of("m")) > 10_000

    d = store.digest()
    assert d["v"] == 1
    assert 0 < len(d["entries"]) <= DIGEST_MAX_PREFIXES
    for e in d["entries"]:
        assert len(e["h"]) <= DIGEST_MAX_HASHES
        assert e["model"] == "m" and e["page"] >= 1
    # the deep spine was published LAST → freshest → ranked first,
    # its claim capped at the hash budget's coverage
    first = d["entries"][0]
    assert first["tokens"] == len(deep)
    assert first["h"] == prefix_chunk_hashes(deep, first["page"], DIGEST_MAX_HASHES)
    # the serialized digest must ride a /healthz body comfortably
    assert len(_json.dumps(d)) < 16_384
