"""Profiler plugins: sampling thread, power integration, host/RAPL/synthetic."""

import time

import pytest
from pathlib import Path

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.base import (
    integrate_power_to_joules,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.host import (
    HostResourceProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.rapl import (
    RaplEnergyProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.synthetic import (
    SyntheticPowerProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import RunContext


def _ctx(tmp_path) -> RunContext:
    run_dir = tmp_path / "run_0"
    run_dir.mkdir(parents=True, exist_ok=True)
    return RunContext(
        run_id="run_0",
        run_nr=1,
        total_runs=1,
        variation={},
        run_dir=run_dir,
        experiment_dir=tmp_path,
    )


def test_integrate_constant_power():
    samples = [{"t_s": float(t), "power_W": 10.0} for t in range(5)]
    assert integrate_power_to_joules(samples, "power_W") == 40.0  # 10 W × 4 s


def test_integrate_handles_missing_and_short():
    assert integrate_power_to_joules([], "p") == 0.0
    assert integrate_power_to_joules([{"t_s": 0, "p": 5}], "p") == 0.0
    samples = [
        {"t_s": 0.0, "p": 10.0},
        {"t_s": 1.0, "p": None},
        {"t_s": 2.0, "p": 10.0},
    ]
    assert integrate_power_to_joules(samples, "p") == 20.0


def test_synthetic_profiler_energy_close_to_expected(tmp_path):
    prof = SyntheticPowerProfiler(period_s=0.005, base_w=100.0)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.12)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    # constant 100 W over ~0.12 s → ~12 J (loose tolerance: thread scheduling)
    assert 5.0 < data["energy_J"] < 25.0
    assert abs(data["avg_power_W"] - 100.0) < 1.0
    # artifact written (reference convention: raw trace in run_dir)
    assert (ctx.run_dir / "synthetic_power.csv").exists()


def test_sampling_profiler_final_sample_even_for_short_window(tmp_path):
    prof = SyntheticPowerProfiler(period_s=10.0, base_w=50.0)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)  # window far shorter than the period
    data = prof.collect(ctx)
    assert data["avg_power_W"] == 50.0  # falls back to base on single sample


def test_host_profiler_reports_cpu_and_memory(tmp_path):
    prof = HostResourceProfiler(period_s=0.02)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.08)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert set(data) == {"cpu_usage", "memory_usage", "host_sample_rate_hz"}
    assert 0.0 <= data["memory_usage"] <= 100.0
    assert data["host_sample_rate_hz"] is None or data["host_sample_rate_hz"] > 0
    assert (ctx.run_dir / "cpu_mem_usage.csv").exists()


def test_rapl_profiler_graceful_without_counters(tmp_path):
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "no-such-rapl:*"))
    assert not prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx) == {"host_energy_J": None, "host_avg_power_W": None}


def test_rapl_profiler_reads_fake_counters(tmp_path):
    dom = tmp_path / "intel-rapl:0"
    dom.mkdir()
    (dom / "energy_uj").write_text("1000000")
    (dom / "max_energy_range_uj").write_text("262143328850")
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "intel-rapl:*"))
    assert prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    (dom / "energy_uj").write_text("3500000")  # +2.5 J
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert data["host_energy_J"] == 2.5


def test_rapl_wraparound_corrected(tmp_path):
    dom = tmp_path / "intel-rapl:0"
    dom.mkdir()
    (dom / "energy_uj").write_text("9000000")
    (dom / "max_energy_range_uj").write_text("10000000")
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "intel-rapl:*"))
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    (dom / "energy_uj").write_text("1000000")  # wrapped: +2 J given 10 J range
    prof.on_stop(ctx)
    assert prof.collect(ctx)["host_energy_J"] == 2.0


# -- energy model validation against known power states ----------------------
# VERDICT.md round-1 item 1: with no measured channel on this host, pin the
# model's coefficients and its integration against the chip's known draw
# states so modelled Joules are at least *calibrated*, not arbitrary.


def test_energy_model_pinned_to_v5e_power_envelope(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        V5E_HBM_ACTIVE_W,
        V5E_IDLE_W,
        V5E_MXU_ACTIVE_W,
        V5E_PEAK_BF16_TFLOPS,
        V5E_PEAK_W,
        V5E_SPEC_HBM_GBPS,
        V5E_VPU_ACTIVE_W,
        V5E_VPU_OPS_PER_S,
        TpuEnergyModelProfiler,
    )

    # public v5e figures + the per-engine coefficients the model is built
    # on (derivations/bounds in profilers/tpu.py); changing any silently
    # would re-scale every shipped energy number
    assert V5E_PEAK_BF16_TFLOPS == 394.0
    assert V5E_SPEC_HBM_GBPS == 819.0
    assert V5E_IDLE_W == 55.0
    assert V5E_PEAK_W == 200.0
    assert V5E_MXU_ACTIVE_W == 145.0
    assert V5E_HBM_ACTIVE_W == 55.0
    assert V5E_VPU_ACTIVE_W == 40.0

    prof = TpuEnergyModelProfiler()
    ctx = _ctx(tmp_path)

    # idle state: zero achieved FLOPs and bytes → exactly idle power × t
    ctx.scratch["generation_stats"] = {
        "flops": 0.0, "duration_s": 2.0, "generated_tokens": 10,
    }
    out = prof.collect(ctx)
    assert out["energy_model_J"] == V5E_IDLE_W * 2.0
    assert out["tpu_util_est"] == 0.0
    assert out["tpu_power_model_W"] == V5E_IDLE_W

    # MXU-saturated state: achieved == peak FLOP/s → exactly the chip
    # envelope (idle + full MXU coefficient = 200 W by construction)
    ctx.scratch["generation_stats"] = {
        "flops": V5E_PEAK_BF16_TFLOPS * 1e12 * 2.0,
        "duration_s": 2.0,
        "generated_tokens": 10,
    }
    out = prof.collect(ctx)
    assert out["energy_model_J"] == V5E_PEAK_W * 2.0
    assert out["tpu_util_est"] == 1.0
    assert out["tpu_power_model_W"] == V5E_PEAK_W

    # HBM-saturated state: a working power state well above idle, but NOT
    # matmul heat — the per-engine split (VERDICT round-4 weak #1): a
    # streaming chip bills the HBM coefficient, not the chip envelope
    ctx.scratch["generation_stats"] = {
        "flops": 0.0,
        "bytes": V5E_SPEC_HBM_GBPS * 1e9 * 2.0,
        "duration_s": 2.0,
        "generated_tokens": 10,
    }
    out = prof.collect(ctx)
    assert out["tpu_util_est"] == 1.0
    assert out["tpu_power_model_W"] == V5E_IDLE_W + V5E_HBM_ACTIVE_W
    assert out["energy_model_J"] == (V5E_IDLE_W + V5E_HBM_ACTIVE_W) * 2.0

    # VPU-saturated state (int4's engine): distinct from both — nibble
    # unpacking at full vector duty is not HBM streaming and not matmul
    ctx.scratch["generation_stats"] = {
        "flops": 0.0,
        "vpu_ops": V5E_VPU_OPS_PER_S * 2.0,
        "duration_s": 2.0,
        "generated_tokens": 10,
    }
    out = prof.collect(ctx)
    assert out["tpu_power_model_W"] == V5E_IDLE_W + V5E_VPU_ACTIVE_W

    # the engines ADD: saturated VPU + half-spec HBM bills both engines —
    # and a workload change (more bytes) still moves the energy column
    # even though the MAX-duty utilisation is already capped at 1.0
    # (round-4's single-envelope model was insensitive exactly here)
    ctx.scratch["generation_stats"] = {
        "flops": 0.0,
        "bytes": V5E_SPEC_HBM_GBPS * 1e9 * 0.5 * 2.0,
        "vpu_ops": V5E_VPU_OPS_PER_S * 2.0,
        "duration_s": 2.0,
        "generated_tokens": 10,
    }
    out_half = prof.collect(ctx)
    assert out_half["tpu_util_est"] == 1.0
    assert out_half["tpu_power_model_W"] == (
        V5E_IDLE_W + V5E_VPU_ACTIVE_W + 0.5 * V5E_HBM_ACTIVE_W
    )
    ctx.scratch["generation_stats"]["bytes"] *= 1.5
    out_more = prof.collect(ctx)
    assert out_more["tpu_util_est"] == 1.0  # max-duty unchanged…
    assert out_more["energy_model_J"] > out_half["energy_model_J"]  # …energy moves

    # utilisation stays the MAX of the duties (the residency-style
    # column), even though power is now their weighted sum
    ctx.scratch["generation_stats"] = {
        "flops": V5E_PEAK_BF16_TFLOPS * 1e12 * 0.25 * 2.0,
        "bytes": V5E_SPEC_HBM_GBPS * 1e9 * 0.5 * 2.0,
        "duration_s": 2.0,
        "generated_tokens": 10,
    }
    assert prof.collect(ctx)["tpu_util_est"] == 0.5

    # any workload, however compound: average power stays inside
    # [idle, peak] — the additive form clamps at the chip envelope and
    # can never emit a physically impossible draw
    for flops, hbm_bytes, vpu in (
        (1e9, 0.0, 0.0),
        (1e12, 1e12, 1e12),
        (1e15, 1e13, 1e13),
        (1e18, 1e15, 1e13),
    ):
        ctx.scratch["generation_stats"] = {
            "flops": flops, "bytes": hbm_bytes, "vpu_ops": vpu,
            "duration_s": 0.5, "generated_tokens": 64,
        }
        out = prof.collect(ctx)
        power = out["energy_model_J"] / 0.5
        assert V5E_IDLE_W <= power <= V5E_PEAK_W
        assert abs(out["tpu_power_model_W"] - power) < 0.01


def test_energy_model_on_bench_workload_is_plausible(tmp_path):
    """The shipped BENCH decode (qwen2:1.5b int8, 256 tokens, ~0.79 s)
    through the real stats builder: decode streams ~60% of spec HBM
    bandwidth (docs/PERF.md:28-31: ~490 of 819 GB/s), so the modelled
    utilisation must land there — NOT at the ~5·10⁻⁴ MXU duty the
    FLOPs-only model reported (VERDICT round-3 missing #1/weak #2)."""
    import types

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        generation_stats_from,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        V5E_HBM_ACTIVE_W,
        V5E_IDLE_W,
        V5E_PEAK_W,
        TpuEnergyModelProfiler,
    )

    cfg = get_model_config("qwen2:1.5b")
    tokens, duration = 256, 0.79
    result = types.SimpleNamespace(
        prompt_tokens=64, generated_tokens=tokens,
        decode_s=duration, total_s=duration + 0.1,
    )
    ctx = _ctx(tmp_path)
    ctx.scratch["generation_stats"] = generation_stats_from(
        cfg, result, quantize="int8"
    )
    out = TpuEnergyModelProfiler().collect(ctx)
    assert V5E_IDLE_W * duration <= out["energy_model_J"] <= V5E_PEAK_W * duration
    jpt = out["joules_per_token"]
    assert V5E_IDLE_W * duration / tokens <= jpt <= V5E_PEAK_W * duration / tokens
    # the headline fix: int8 decode duty ≈ 0.6 (±0.1), mirroring the
    # reference's 78-93% GPU-residency metric (RunnerConfig.py:207-226)
    assert 0.5 <= out["tpu_util_est"] <= 0.75
    # and the modelled draw is a working HBM-streaming power state,
    # clearly above idle but billed at the HBM coefficient, not matmul's
    assert out["tpu_power_model_W"] > V5E_IDLE_W + 0.4 * V5E_HBM_ACTIVE_W
    assert out["tpu_power_model_W"] < V5E_IDLE_W + 1.2 * V5E_HBM_ACTIVE_W


def test_per_engine_power_int4_vs_int8_distinguishable(tmp_path):
    """VERDICT round-5 directive #1 'done' criterion: int4 and int8 decode
    bill distinguishable, documented power STATES. The per-engine model's
    verdict (docs/PERF.md round-5 section): the two modes draw similar
    total watts (~108 vs ~111) through DIFFERENT engine mixes — int8 is
    HBM-dominated (duty ≈0.60 bytes, ≈0.49 VPU dequant), int4 is
    VPU-dominated (duty ≈0.97 unpack, ≈0.30 bytes) — and neither is the
    flat 200 W the single-envelope model charged int4's capped util. The
    J/token ordering now comes from step time and engine physics, not
    from which duty won a max()."""
    import types

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        generation_stats_from,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        V5E_SPEC_HBM_GBPS,
        V5E_VPU_OPS_PER_S,
        V5E_PEAK_W,
        TpuEnergyModelProfiler,
    )

    cfg = get_model_config("qwen2:1.5b")
    # measured steady-state step times (docs/PERF.md component ablation)
    outs, duties = {}, {}
    for quant, step_s in (("int4", 0.00363), ("int8", 0.00314)):
        res = types.SimpleNamespace(
            prompt_tokens=64, generated_tokens=256,
            decode_s=256 * step_s, total_s=1.0,
        )
        stats = generation_stats_from(cfg, res, quantize=quant)
        outs[quant] = TpuEnergyModelProfiler().collect(
            types.SimpleNamespace(scratch={"generation_stats": stats})
        )
        dur = stats["duration_s"]
        duties[quant] = {
            "hbm": stats["bytes"] / (V5E_SPEC_HBM_GBPS * 1e9 * dur),
            "vpu": stats["vpu_ops"] / (V5E_VPU_OPS_PER_S * dur),
        }
    w4 = outs["int4"]["tpu_power_model_W"]
    w8 = outs["int8"]["tpu_power_model_W"]
    # the engine mixes are opposite: int4 VPU-dominated, int8 HBM-dominated
    assert duties["int4"]["vpu"] > 2.5 * duties["int4"]["hbm"]
    assert duties["int8"]["hbm"] > duties["int8"]["vpu"]
    # int4 bills (slightly) hotter — more work per streamed byte — and
    # BOTH are working states far below the matmul envelope: the util
    # cap no longer saturates the energy column
    assert w4 > w8
    assert outs["int4"]["tpu_util_est"] >= 0.85
    assert w4 < 0.65 * V5E_PEAK_W
    assert w8 < 0.65 * V5E_PEAK_W
    # per token int4 still costs more (slower step × hotter state)
    assert outs["int4"]["joules_per_token"] > outs["int8"]["joules_per_token"]


# -- energy channel probe -----------------------------------------------------


def test_probe_energy_channels_covers_all_sources():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.energy_probe import (
        probe_energy_channels,
    )

    statuses = probe_energy_channels()
    assert {s.name for s in statuses} == {
        "rapl", "hwmon", "battery", "tpu_info", "libtpu_monitoring",
    }
    for s in statuses:
        assert s.kind in ("energy", "power", "utilization")
        assert s.scope in ("host", "device")
        assert s.detail  # every unavailable channel says WHY


def test_write_probe_report(tmp_path):
    import json as _json

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.energy_probe import (
        write_probe_report,
    )

    path = tmp_path / "energy_channels.json"
    statuses = write_probe_report(path)
    payload = _json.loads(path.read_text())
    assert len(payload["channels"]) == len(statuses)
    assert isinstance(payload["any_measured_energy"], bool)
    assert "modelled" in payload["note"]


def test_power_counter_profiler_integrates_real_readings(tmp_path, monkeypatch):
    """The libtpu power-counter path (VERDICT round-2 weak 1: the code
    most load-bearing for the north star was the least exercised): with a
    counter source injected, the profiler samples, integrates W→J over
    the window, and reports the average power."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import tpu

    monkeypatch.setattr(tpu, "_try_read_power_w", lambda: 120.0)
    prof = tpu.TpuPowerCounterProfiler(period_s=0.01)
    assert prof.available
    assert prof.measured_channel
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.1)
    prof.on_stop(ctx)
    out = prof.collect(ctx)
    # exact W×span using the trace's own span (constant 120 W source)
    assert out["tpu_avg_power_W"] == pytest.approx(120.0, rel=1e-6)
    import csv as _csv

    rows = list(_csv.DictReader((ctx.run_dir / "tpu_power.csv").open()))
    span = float(rows[-1]["t_s"]) - float(rows[0]["t_s"])
    assert out["tpu_energy_J"] == pytest.approx(120.0 * span, abs=1e-3)


def test_power_counter_profiler_none_source_degrades_cleanly(tmp_path, monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import tpu

    monkeypatch.setattr(tpu, "_try_read_power_w", lambda: None)
    prof = tpu.TpuPowerCounterProfiler(period_s=0.01)
    assert not prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.03)
    prof.on_stop(ctx)
    out = prof.collect(ctx)
    assert out == {"tpu_energy_J": None, "tpu_avg_power_W": None}


def test_study_wires_power_counter_when_available(monkeypatch):
    """End-to-end policy: a live counter source puts the counter profiler
    in the study's profiler list AND re-grows the 90 s thermal cooldown."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import tpu

    monkeypatch.setattr(tpu, "_try_read_power_w", lambda: 95.0)
    config = LlmEnergyConfig()
    assert any(
        isinstance(p, tpu.TpuPowerCounterProfiler) for p in config.profilers
    )
    assert (
        config.time_between_runs_in_ms
        == LlmEnergyConfig.MEASURED_CHANNEL_COOLDOWN_MS
    )


def test_duty_cycle_profiler_summarises_trace(tmp_path, monkeypatch):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import (
        energy_probe,
    )

    prof = energy_probe.TpuDutyCycleProfiler(
        period_s=0.01, peak_w=200.0, idle_w=50.0
    )
    monkeypatch.setattr(
        energy_probe.TpuDutyCycleProfiler,
        "_read_duty",
        staticmethod(lambda: (50.0, 1)),
    )
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.12)
    prof.on_stop(ctx)
    out = prof.collect(ctx)
    assert out["tpu_duty_cycle_pct"] == 50.0
    # P = 50 + 0.5·(200−50) = 125 W over exactly the sampled span — read
    # the span back from the written trace so the assertion pins the
    # integration formula, not the sleep's jitter.
    import csv as _csv

    trace_path = ctx.run_dir / "tpu_duty_cycle.csv"
    assert trace_path.exists()
    with trace_path.open() as f:
        ts = [float(row["t_s"]) for row in _csv.DictReader(f)]
    span = ts[-1] - ts[0]
    assert span > 0
    # summarise() rounds to 4 decimals — allow exactly that quantisation
    assert out["energy_duty_J"] == pytest.approx(125.0 * span, abs=1e-3)


def test_energy_model_vpu_duty_bills_int4_as_saturated(tmp_path):
    """int4 decode is VPU-bound (docs/PERF.md: ~5 unpack ops per packed
    byte set its 3.6 ms step, not HBM) — the model must bill the
    saturated vector unit, not the ~30% bytes-duty lower bound. int8
    stays HBM-dominated (its VPU duty ~0.5 is below its 0.6 HBM duty)."""
    import types

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        generation_stats_from,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        TpuEnergyModelProfiler,
    )

    cfg = get_model_config("qwen2:1.5b")
    # measured steady-state step times (docs/PERF.md component ablation)
    res4 = types.SimpleNamespace(
        prompt_tokens=64, generated_tokens=256,
        decode_s=256 * 0.00363, total_s=1.0,
    )
    s4 = generation_stats_from(cfg, res4, quantize="int4")
    out4 = TpuEnergyModelProfiler().collect(
        types.SimpleNamespace(scratch={"generation_stats": s4})
    )
    assert 0.85 <= out4["tpu_util_est"] <= 1.0

    res8 = types.SimpleNamespace(
        prompt_tokens=64, generated_tokens=256,
        decode_s=256 * 0.00314, total_s=1.0,
    )
    s8 = generation_stats_from(cfg, res8, quantize="int8")
    out8 = TpuEnergyModelProfiler().collect(
        types.SimpleNamespace(scratch={"generation_stats": s8})
    )
    assert 0.5 <= out8["tpu_util_est"] <= 0.75
    # per token, int4 must now cost MORE than int8 (slower AND a
    # saturated engine) — the capstone's int4 rows stop reading as the
    # low-power mode
    assert out4["joules_per_token"] > out8["joules_per_token"]


def test_vpu_unpack_ops_accounting():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.utils.memory import (
        decode_vpu_unpack_ops_per_step,
    )

    cfg = get_model_config("qwen2:1.5b")
    # bf16: no quantized stream, no unpack
    assert decode_vpu_unpack_ops_per_step(cfg, None) == 0.0
    ops8 = decode_vpu_unpack_ops_per_step(cfg, "int8")
    ops4 = decode_vpu_unpack_ops_per_step(cfg, "int4")
    ops4_i32 = decode_vpu_unpack_ops_per_step(cfg, "int4-i32")
    # int4 halves: 5 ops per packed byte on half the bytes → 2.5x the
    # int8 body cost; i32 layout cheaper than halves, dearer than int8
    assert ops4 > ops4_i32 > ops8 > 0
    # docs/PERF.md arithmetic: qwen2 int4 body ≈ 0.66 GB × 5 ≈ 3.3e9
    # ops (+0.23e9 for the int8 logits head)
    assert 3.0e9 < ops4 < 4.0e9


# -- generic sysfs host power (hwmon / battery) -------------------------------


def test_sysfs_profiler_reads_hwmon_rails(tmp_path):
    """hwmon power rails (microwatts) integrated W→J, ONE rail per hwmon
    device: power2_input in the same device as power1_input is a
    hierarchical sub-rail of the same chip and summing both would
    double-count (ADVICE round-4); separate hwmon devices (separate
    chips) DO sum."""
    hm = tmp_path / "hwmon0"
    hm.mkdir()
    (hm / "power1_input").write_text("15000000")  # 15 W package rail
    (hm / "power2_input").write_text("5000000")  # 5 W sub-rail: ignored
    hm2 = tmp_path / "hwmon1"
    hm2.mkdir()
    (hm2 / "power1_input").write_text("5000000")  # 5 W, separate chip
    prof = __import__(
        "cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.sysfs_power",
        fromlist=["SysfsPowerProfiler"],
    ).SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "hwmon*/power*_input"),
        battery_glob=str(tmp_path / "nope/*/power_now"),
    )
    assert prof.available
    assert prof.measured_channel
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.08)
    prof.on_stop(ctx)
    out = prof.collect(ctx)
    assert out["sysfs_avg_power_W"] == pytest.approx(20.0, rel=1e-6)
    assert (ctx.run_dir / "sysfs_power.csv").exists()


def test_sysfs_battery_on_ac_is_not_a_measured_channel(tmp_path):
    """ADVICE round-4 (medium): on AC power the battery reading is
    charger flow, not system load — a non-Discharging supply must not
    count as an available measured channel (it would flip the study to
    the 90 s measured cooldown) and must not be sampled."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.sysfs_power import (
        SysfsPowerProfiler,
    )

    bat = tmp_path / "supply" / "BAT0"
    bat.mkdir(parents=True)
    (bat / "power_now").write_text("30000000")  # 30 W of CHARGE flow
    (bat / "status").write_text("Charging\n")
    prof = SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "supply/*/power_now"),
    )
    assert not prof.available
    assert prof._power_w() is None  # skipped at sample time too

    # ... and plugging in MID-RUN stops the channel: flip the status file
    # while sampling and the later samples must be None, not 30 W
    (bat / "status").write_text("Discharging\n")
    prof2 = SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "supply/*/power_now"),
    )
    assert prof2.available
    assert prof2._power_w() == pytest.approx(30.0)
    (bat / "status").write_text("Charging\n")
    assert prof2._power_w() is None

    # IV-fallback supplies obey the same status gate
    bat2 = tmp_path / "supply2" / "BAT0"
    bat2.mkdir(parents=True)
    (bat2 / "current_now").write_text("2000000")
    (bat2 / "voltage_now").write_text("11000000")
    (bat2 / "status").write_text("Full\n")
    prof3 = SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "supply2/*/power_now"),
    )
    assert not prof3.available
    assert prof3._power_w() is None


def test_sysfs_profiler_battery_fallbacks(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.sysfs_power import (
        SysfsPowerProfiler,
    )

    bat = tmp_path / "supply" / "BAT0"
    bat.mkdir(parents=True)
    (bat / "power_now").write_text("12000000")  # 12 W discharge
    prof = SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "supply/*/power_now"),
    )
    assert prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.05)
    prof.on_stop(ctx)
    assert prof.collect(ctx)["sysfs_avg_power_W"] == pytest.approx(
        12.0, rel=1e-6
    )

    # current_now × voltage_now fallback when power_now is absent
    bat2 = tmp_path / "supply2" / "BAT0"
    bat2.mkdir(parents=True)
    (bat2 / "current_now").write_text("2000000")  # 2 A
    (bat2 / "voltage_now").write_text("11000000")  # 11 V
    prof2 = SysfsPowerProfiler(
        period_s=0.01,
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "supply2/*/power_now"),
    )
    assert prof2.available
    ctx2 = _ctx(tmp_path)
    prof2.on_start(ctx2)
    time.sleep(0.05)
    prof2.on_stop(ctx2)
    assert prof2.collect(ctx2)["sysfs_avg_power_W"] == pytest.approx(
        22.0, rel=1e-6
    )


def test_sysfs_profiler_unavailable_degrades(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.sysfs_power import (
        SysfsPowerProfiler,
    )

    prof = SysfsPowerProfiler(
        hwmon_glob=str(tmp_path / "none*/power*_input"),
        battery_glob=str(tmp_path / "none/*/power_now"),
    )
    assert not prof.available


def test_study_wires_sysfs_profiler_when_available(monkeypatch, tmp_path):
    """A live hwmon/battery channel puts the sysfs profiler in the study
    AND re-grows the 90 s thermal cooldown — the prepare promise and the
    study's behavior agree."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.experiments.llm_energy import (
        LlmEnergyConfig,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import (
        sysfs_power,
    )

    hm = tmp_path / "hwmon0"
    hm.mkdir()
    (hm / "power1_input").write_text("10000000")
    monkeypatch.setattr(
        sysfs_power, "HWMON_GLOB", str(tmp_path / "hwmon*/power*_input")
    )
    config = LlmEnergyConfig()
    assert any(
        isinstance(p, sysfs_power.SysfsPowerProfiler)
        for p in config.profilers
    )
    assert (
        config.time_between_runs_in_ms
        == LlmEnergyConfig.MEASURED_CHANNEL_COOLDOWN_MS
    )


def test_hwmon_package_rail_selected_by_numeric_index(tmp_path):
    """power10_input must not shadow power1_input (lexicographic sort
    places it first): the package rail is the lowest NUMERIC index."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.sysfs_power import (
        select_hwmon_sensors,
    )

    hm = tmp_path / "hwmon0"
    hm.mkdir()
    for i in range(1, 12):
        (hm / f"power{i}_input").write_text(str(i * 1000000))
    sel = select_hwmon_sensors(str(tmp_path / "hwmon*/power*_input"))
    assert sel == [str(hm / "power1_input")]


# -- TPU power counter: injectable source + CLI fallback ----------------------


def test_tpu_counter_injectable_source_both_directions(tmp_path):
    """VERDICT round-5 directive #6: the counter profiler takes an
    injectable source like the sysfs/serial profilers, with availability
    mirroring the source in BOTH directions."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        TpuPowerCounterProfiler,
    )

    live = TpuPowerCounterProfiler(period_s=0.01, source=lambda: 123.0)
    assert live.available
    assert live.measured_channel
    ctx = _ctx(tmp_path)
    live.on_start(ctx)
    time.sleep(0.06)
    live.on_stop(ctx)
    out = live.collect(ctx)
    assert out["tpu_avg_power_W"] == pytest.approx(123.0, rel=1e-6)
    assert out["tpu_energy_J"] > 0

    dead = TpuPowerCounterProfiler(period_s=0.01, source=lambda: None)
    assert not dead.available
    ctx2 = _ctx(tmp_path)
    dead.on_start(ctx2)
    dead.on_stop(ctx2)
    assert dead.collect(ctx2) == {
        "tpu_energy_J": None,
        "tpu_avg_power_W": None,
    }


def test_tpu_info_cli_output_parsing():
    """The CLI fallback's parser: usage/limit pairs sum the USAGE side
    only; bare watts sum when no pairs exist; no watts → None."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.tpu import (
        parse_tpu_info_cli_watts,
    )

    table = (
        "Chip  Power\n"
        "/dev/accel0  12.50 W / 200.00 W\n"
        "/dev/accel1  13.25 W / 200.00 W\n"
    )
    assert parse_tpu_info_cli_watts(table) == pytest.approx(25.75)
    assert parse_tpu_info_cli_watts("chip0: 55 W\nchip1: 45 W\n") == 100.0
    assert parse_tpu_info_cli_watts("no power figures here") is None


def test_tpu_counter_default_chain_falls_back_to_cli(monkeypatch):
    """Library absent → the `tpu-info` CLI subprocess is the source; both
    absent → no reading."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import tpu

    monkeypatch.setattr(tpu, "_read_power_from_library", lambda: None)
    monkeypatch.setattr(tpu, "_read_power_from_cli", lambda: 42.0)
    assert tpu._try_read_power_w() == 42.0
    monkeypatch.setattr(tpu, "_read_power_from_cli", lambda: None)
    assert tpu._try_read_power_w() is None


def test_tpu_info_probe_mirrors_consumer_cli_fallback(monkeypatch):
    """A broken tpu_info library with a working CLI is a LIVE channel —
    the probe must agree with the profiler's source chain in both
    directions (round-5 review finding)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers import (
        energy_probe, tpu,
    )

    monkeypatch.setattr(tpu, "_read_power_from_cli", lambda: 87.5)
    status = energy_probe._probe_tpu_info()
    # whatever the library's state on this host, a working CLI makes the
    # channel available and the detail names the subprocess source
    assert status.available
    assert "tpu-info CLI subprocess" in status.detail

    monkeypatch.setattr(tpu, "_read_power_from_cli", lambda: None)
    status = energy_probe._probe_tpu_info()
    assert not status.available
