"""Profiler plugins: sampling thread, power integration, host/RAPL/synthetic."""

import time
from pathlib import Path

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.base import (
    integrate_power_to_joules,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.host import (
    HostResourceProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.rapl import (
    RaplEnergyProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.synthetic import (
    SyntheticPowerProfiler,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import RunContext


def _ctx(tmp_path) -> RunContext:
    run_dir = tmp_path / "run_0"
    run_dir.mkdir(parents=True, exist_ok=True)
    return RunContext(
        run_id="run_0",
        run_nr=1,
        total_runs=1,
        variation={},
        run_dir=run_dir,
        experiment_dir=tmp_path,
    )


def test_integrate_constant_power():
    samples = [{"t_s": float(t), "power_W": 10.0} for t in range(5)]
    assert integrate_power_to_joules(samples, "power_W") == 40.0  # 10 W × 4 s


def test_integrate_handles_missing_and_short():
    assert integrate_power_to_joules([], "p") == 0.0
    assert integrate_power_to_joules([{"t_s": 0, "p": 5}], "p") == 0.0
    samples = [
        {"t_s": 0.0, "p": 10.0},
        {"t_s": 1.0, "p": None},
        {"t_s": 2.0, "p": 10.0},
    ]
    assert integrate_power_to_joules(samples, "p") == 20.0


def test_synthetic_profiler_energy_close_to_expected(tmp_path):
    prof = SyntheticPowerProfiler(period_s=0.005, base_w=100.0)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.12)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    # constant 100 W over ~0.12 s → ~12 J (loose tolerance: thread scheduling)
    assert 5.0 < data["energy_J"] < 25.0
    assert abs(data["avg_power_W"] - 100.0) < 1.0
    # artifact written (reference convention: raw trace in run_dir)
    assert (ctx.run_dir / "synthetic_power.csv").exists()


def test_sampling_profiler_final_sample_even_for_short_window(tmp_path):
    prof = SyntheticPowerProfiler(period_s=10.0, base_w=50.0)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)  # window far shorter than the period
    data = prof.collect(ctx)
    assert data["avg_power_W"] == 50.0  # falls back to base on single sample


def test_host_profiler_reports_cpu_and_memory(tmp_path):
    prof = HostResourceProfiler(period_s=0.02)
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    time.sleep(0.08)
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert set(data) == {"cpu_usage", "memory_usage", "host_sample_rate_hz"}
    assert 0.0 <= data["memory_usage"] <= 100.0
    assert data["host_sample_rate_hz"] is None or data["host_sample_rate_hz"] > 0
    assert (ctx.run_dir / "cpu_mem_usage.csv").exists()


def test_rapl_profiler_graceful_without_counters(tmp_path):
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "no-such-rapl:*"))
    assert not prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx) == {"host_energy_J": None, "host_avg_power_W": None}


def test_rapl_profiler_reads_fake_counters(tmp_path):
    dom = tmp_path / "intel-rapl:0"
    dom.mkdir()
    (dom / "energy_uj").write_text("1000000")
    (dom / "max_energy_range_uj").write_text("262143328850")
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "intel-rapl:*"))
    assert prof.available
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    (dom / "energy_uj").write_text("3500000")  # +2.5 J
    prof.on_stop(ctx)
    data = prof.collect(ctx)
    assert data["host_energy_J"] == 2.5


def test_rapl_wraparound_corrected(tmp_path):
    dom = tmp_path / "intel-rapl:0"
    dom.mkdir()
    (dom / "energy_uj").write_text("9000000")
    (dom / "max_energy_range_uj").write_text("10000000")
    prof = RaplEnergyProfiler(rapl_glob=str(tmp_path / "intel-rapl:*"))
    ctx = _ctx(tmp_path)
    prof.on_start(ctx)
    (dom / "energy_uj").write_text("1000000")  # wrapped: +2 J given 10 J range
    prof.on_stop(ctx)
    assert prof.collect(ctx)["host_energy_J"] == 2.0
