"""Pipeline parallelism: the GPipe schedule must match single-device math.

SURVEY.md §4 prescribes virtual-device testing for every multi-chip path;
the strongest check for a pipeline schedule is exact numerical parity of
loss AND gradients against the unpipelined step (same params, same tokens).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
    get_model_config,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.transformer import (
    Transformer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.pp import (
    make_pp_grad,
    make_pp_loss,
    make_pp_train_step,
    pp_param_specs,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.parallel.train import (
    next_token_loss,
)


def _setup(model="mistral:7b", n_layers=4, seed=0, batch=4, seq=12):
    import dataclasses

    cfg = dataclasses.replace(
        get_model_config(model).tiny(), n_layers=n_layers
    )
    tf = Transformer.initialise(cfg, seed=seed, dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (batch, seq), 0, cfg.vocab_size
    )
    return cfg, tf.params, tokens


def _reference_loss_and_grads(cfg, params, tokens):
    def loss_fn(p):
        b, s = tokens.shape
        shape = (cfg.n_layers, b, cfg.n_kv_heads, s - 1, cfg.d_head)
        k0 = jnp.zeros(shape, dtype=jnp.float32)
        v0 = jnp.zeros(shape, dtype=jnp.float32)
        return next_token_loss(p, cfg, tokens, k0, v0)

    return jax.value_and_grad(loss_fn)(params)


@pytest.mark.parametrize("pp,m", [(2, 2), (4, 4), (4, 2)])
def test_pp_loss_matches_single_device(pp, m):
    cfg, params, tokens = _setup(n_layers=4, batch=4)
    mesh = build_mesh(MeshSpec(axes=(("pp", pp),)), jax.devices()[:pp])
    pp_loss = jax.jit(make_pp_loss(cfg, mesh, n_microbatches=m))
    ref_loss, _ = _reference_loss_and_grads(cfg, params, tokens)
    np.testing.assert_allclose(
        float(pp_loss(params, tokens)), float(ref_loss), rtol=2e-5
    )


# gemma:2b exercises every architecture quirk the pipelined path must share
# with the single-device path: tied embeddings, sqrt(d) embed scaling,
# (1+w) gemma norms, and the gelu MLP.
def test_pp_loss_matches_single_device_gemma():
    pp, m = 2, 2
    cfg, params, tokens = _setup(model="gemma:2b", n_layers=4, batch=4)
    mesh = build_mesh(MeshSpec(axes=(("pp", pp),)), jax.devices()[:pp])
    pp_loss = jax.jit(make_pp_loss(cfg, mesh, n_microbatches=m))
    ref_loss, _ = _reference_loss_and_grads(cfg, params, tokens)
    np.testing.assert_allclose(
        float(pp_loss(params, tokens)), float(ref_loss), rtol=2e-5
    )


def test_pp_grads_match_single_device():
    cfg, params, tokens = _setup(n_layers=4, batch=4)
    mesh = build_mesh(MeshSpec(axes=(("pp", 4),)), jax.devices()[:4])
    loss, grads = jax.jit(make_pp_grad(cfg, mesh, n_microbatches=2))(
        params, tokens
    )
    ref_loss, ref_grads = _reference_loss_and_grads(cfg, params, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for name in params:
        np.testing.assert_allclose(
            np.asarray(grads[name]),
            np.asarray(ref_grads[name]),
            atol=1e-5,
            rtol=1e-3,
            err_msg=f"grad mismatch for {name}",
        )


def test_pp_train_step_decreases_loss_and_keeps_sharding():
    cfg, params, tokens = _setup(model="qwen2:1.5b", n_layers=4, batch=4)
    mesh = build_mesh(MeshSpec(axes=(("pp", 4),)), jax.devices()[:4])
    init_fn, step = make_pp_train_step(
        cfg, mesh, n_microbatches=2, learning_rate=1e-2
    )
    params, opt_state = init_fn(params)
    from jax.sharding import NamedSharding

    def _is_stage_sharded(arr):
        return arr.sharding.is_equivalent_to(
            NamedSharding(mesh, pp_param_specs(cfg)["wq"]), arr.ndim
        )

    assert _is_stage_sharded(params["wq"])

    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert _is_stage_sharded(params["wq"])


def test_pp_rejects_indivisible_layers():
    cfg, params, tokens = _setup(n_layers=4)
    mesh = build_mesh(MeshSpec(axes=(("pp", 3),)), jax.devices()[:3])
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_loss(cfg, mesh, n_microbatches=2)
