"""Observability subsystem: metrics registry, span tracer, energy bridge,
and the instrumented serving path.

ISSUE 2 acceptance surface: ``/metrics`` exposes scheduler/engine/KV/
energy families after a served request; a request through
``BatchScheduler`` yields a queue→prefill→decode span tree under the
HTTP request's root with a finite J/token estimate; the kill switch
yields zero spans and a 404 ``/metrics``.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from cain_2025_device_remote_llm_energy_rep_pkg_tpu import obs
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
    MetricsRegistry,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.trace import (
    TRACER,
    SpanTracer,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.backend import (
    GenerationRequest,
)
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.fake import FakeBackend
from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.server import (
    GenerationServer,
)


@pytest.fixture
def obs_on():
    """Guarantee telemetry is on for the test and restored after."""
    was = obs.enabled()
    obs.enable()
    yield
    (obs.enable if was else obs.disable)()


@pytest.fixture
def obs_off():
    was = obs.enabled()
    obs.disable()
    yield
    (obs.enable if was else obs.disable)()


def _tiny_registry():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.models.config import (
        get_model_config,
    )

    return {"tiny": get_model_config("qwen2:1.5b").tiny()}


# -- metrics registry ---------------------------------------------------------


def test_counter_gauge_and_exposition(obs_on):
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "help text", labels=("path",))
    c.labels(path="/x").inc()
    c.labels(path="/x").inc(2)
    g = reg.gauge("t_gauge", "g")
    g.set(3.5)
    text = reg.exposition()
    assert "# TYPE t_requests_total counter" in text
    assert 't_requests_total{path="/x"} 3.0' in text
    assert "# HELP t_requests_total help text" in text
    assert "t_gauge 3.5" in text


def test_histogram_buckets_are_cumulative(obs_on):
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "h", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    text = reg.exposition()
    assert 't_lat_seconds_bucket{le="0.1"} 1' in text
    assert 't_lat_seconds_bucket{le="1.0"} 3' in text
    assert 't_lat_seconds_bucket{le="+Inf"} 4' in text
    assert "t_lat_seconds_count 4" in text
    assert "t_lat_seconds_sum 6.05" in text


def test_registry_families_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    a = reg.counter("t_same", "x")
    assert reg.counter("t_same", "x") is a
    with pytest.raises(ValueError):
        reg.gauge("t_same")


def test_snapshot_shape(obs_on):
    reg = MetricsRegistry()
    reg.counter("t_c").inc(2)
    reg.histogram("t_h", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["t_c"]["_"] == 2
    assert snap["t_h"]["_"]["count"] == 1
    assert snap["t_h"]["_"]["sum"] == 0.5


def test_exposition_golden_output(obs_on):
    """Pin the FULL text exposition against the v0.0.4 format spec:
    family sort, stable (sorted) child label order independent of
    first-touch order, label-value escaping (backslash, quote, newline),
    HELP escaping, cumulative buckets ending in +Inf == _count, and
    _count/_sum consistency. Any formatting drift breaks this test."""
    reg = MetricsRegistry()
    c = reg.counter("g_req_total", "requests", labels=("path", "code"))
    # touch children OUT of sorted order: exposition must sort them
    c.labels(path="/z", code="500").inc(2)
    c.labels(path="/a", code="200").inc(1)
    c.labels(path='/esc"\\x\n', code="200").inc(3)
    g = reg.gauge("g_rows", "live rows")
    g.set(4)
    h = reg.histogram("g_lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    golden = (
        "# HELP g_lat_seconds latency\n"
        "# TYPE g_lat_seconds histogram\n"
        'g_lat_seconds_bucket{le="0.1"} 1\n'
        'g_lat_seconds_bucket{le="1.0"} 2\n'
        'g_lat_seconds_bucket{le="+Inf"} 3\n'
        "g_lat_seconds_sum 2.55\n"
        "g_lat_seconds_count 3\n"
        "# HELP g_req_total requests\n"
        "# TYPE g_req_total counter\n"
        'g_req_total{path="/a",code="200"} 1.0\n'
        'g_req_total{path="/esc\\"\\\\x\\n",code="200"} 3.0\n'
        'g_req_total{path="/z",code="500"} 2.0\n'
        "# HELP g_rows live rows\n"
        "# TYPE g_rows gauge\n"
        "g_rows 4.0\n"
    )
    assert reg.exposition() == golden


def test_exposition_help_escaping(obs_on):
    reg = MetricsRegistry()
    reg.counter("g_c", "line one\nline two \\ slash").inc()
    text = reg.exposition()
    assert "# HELP g_c line one\\nline two \\\\ slash\n" in text


def test_kill_switch_silences_metrics_and_spans(obs_off):
    reg = MetricsRegistry()
    reg.counter("t_dead").inc(5)
    assert reg.exposition() == ""
    tracer = SpanTracer()
    with tracer.span("nothing"):
        tracer.add_span("inner", 0.0, 1.0)
    assert tracer.spans() == []


# -- tracer -------------------------------------------------------------------


def test_span_parent_links_and_chrome_export(obs_on, tmp_path):
    tracer = SpanTracer()
    with tracer.span("root", kind="test") as root:
        with tracer.span("child"):
            pass
        tracer.add_span("timed", 1.0, 2.0)
    spans = {s.name: s for s in tracer.spans()}
    assert spans["child"].parent_id == spans["root"].span_id
    assert spans["timed"].parent_id == spans["root"].span_id
    assert spans["root"].parent_id is None
    assert spans["timed"].dur_s == pytest.approx(1.0)
    out = tmp_path / "trace.json"
    tracer.export(out)
    events = json.loads(out.read_text())["traceEvents"]
    assert {e["name"] for e in events} == {"root", "child", "timed"}
    timed = next(e for e in events if e["name"] == "timed")
    assert timed["ph"] == "X" and timed["dur"] == pytest.approx(1e6)
    assert timed["args"]["parent_id"] == spans["root"].span_id


def test_attach_carries_parent_across_threads(obs_on):
    tracer = SpanTracer()
    with tracer.span("root") as root:
        def worker():
            with tracer.attach(root):
                tracer.add_span("hop", 0.0, 0.5)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans = {s.name: s for s in tracer.spans()}
    assert spans["hop"].parent_id == spans["root"].span_id


# -- energy bridge ------------------------------------------------------------


def test_energy_estimate_bounds_bracket_nominal(obs_on):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.energy import (
        estimate_from_stats,
    )

    est = estimate_from_stats(
        {
            "flops": 1e12,
            "bytes": 5e10,
            "vpu_ops": 1e9,
            "duration_s": 1.0,
            "generated_tokens": 100,
        }
    )
    assert est is not None
    assert est["J_low"] < est["J"] < est["J_high"]
    assert (
        est["J_per_token_low"]
        < est["J_per_token"]
        < est["J_per_token_high"]
    )
    assert est["J_per_token"] == pytest.approx(est["J"] / 100, rel=1e-3)


def test_energy_estimate_none_without_window(obs_on):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.energy import (
        estimate_from_stats,
    )

    assert estimate_from_stats({}) is None
    assert estimate_from_stats({"duration_s": 0.0}) is None


# -- served path (the acceptance criteria) ------------------------------------


def test_metrics_endpoint_after_served_request(obs_on):
    """/metrics exposition parses and contains the HTTP + scheduler
    families after one request through continuous batching."""
    srv = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True,
        batch_window_ms=20,
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(
            f"{base}/api/generate",
            data=json.dumps(
                {"model": "m", "prompt": "p", "options": {"num_predict": 4}}
            ).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["done"]
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
    finally:
        srv.stop()
    for family in (
        "llm_http_requests_total",
        "llm_http_request_seconds",
        "llm_sched_queue_wait_seconds",
        "llm_sched_window_collect_seconds",
        "llm_sched_admission_cap_rows",
        "llm_sched_batch_rows",
    ):
        assert family in text, family
    # the exposition parses: every sample line is "name{...} value"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value)
        assert name_part.startswith("llm_")


def test_metrics_endpoint_404_when_disabled(obs_off):
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10
            )
        assert exc_info.value.code == 404
    finally:
        srv.stop()


def test_kill_switch_covers_flight_and_debug_surface(obs_off):
    """Kill-switch completeness (ISSUE 5): with telemetry off the NEW
    surface is off too — flight emits are no-ops, the detectors stay
    silent, and both debug endpoints 404 (deep coverage incl. the
    concurrency/ordering cases lives in tests/test_flight.py)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.detect import (
        SpikeDetector,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.flight import (
        FlightRecorder,
    )

    rec = FlightRecorder(capacity=4)
    assert rec.emit("dead") is None and rec.events() == []
    det = SpikeDetector("s", min_samples=1)
    det.observe(0.001)
    assert det.observe(999.0) is False
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        for path in ("/debug/state", "/debug/flight", "/debug/timeseries"):
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=10
                )
            assert exc_info.value.code == 404, path
    finally:
        srv.stop()


def test_request_through_scheduler_yields_span_tree_and_energy(obs_on):
    """The tentpole's end-to-end: one HTTP request through BatchScheduler
    produces a request-rooted queue→prefill→decode span tree and a
    finite J/token estimate on the result."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.client import (
        RemoteHTTPBackend,
    )

    TRACER.clear()
    backend = JaxEngine(registry=_tiny_registry(), dtype=jnp.float32)
    # scheduler="window" pinned: per-request energy attribution (token
    # share of ONE shared decode window) is a window/solo-path feature;
    # continuous sessions retire rows across many slices with varying
    # companions and attach sched latency extras instead.
    srv = GenerationServer(
        backend, host="127.0.0.1", port=0, quiet=True, batch_window_ms=20,
        scheduler="window",
    )
    srv.start()
    try:
        client = RemoteHTTPBackend(f"http://127.0.0.1:{srv.port}")
        result = client.generate(
            GenerationRequest("tiny", "observe me", max_new_tokens=6)
        )
    finally:
        srv.stop()

    # finite per-request energy attribution rode the wire (x_extras)
    energy = (result.extras or {}).get("energy_model")
    assert energy is not None
    assert energy["J_per_token"] > 0
    assert energy["J_low"] < energy["J"] < energy["J_high"]

    spans = TRACER.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    assert "request" in by_name and "queue" in by_name
    root = by_name["request"][0]
    queue = by_name["queue"][0]
    assert queue.parent_id == root.span_id
    # prefill and decode parent under the SAME request root (the
    # scheduler re-attached it on its own thread)
    assert any(s.parent_id == root.span_id for s in by_name["prefill"])
    assert any(s.parent_id == root.span_id for s in by_name["decode"])


def test_paged_pool_and_engine_families_in_exposition(obs_on):
    """Engine + paged-KV gauge families land in the shared registry after
    a paged batched decode (the /metrics surface serves this registry)."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )

    engine = JaxEngine(
        registry=_tiny_registry(), dtype=jnp.float32, paged_kv=True
    )
    reqs = [
        GenerationRequest("tiny", p, max_new_tokens=5)
        for p in ("one", "two longer prompt", "three")
    ]
    results = engine.generate_batch(reqs)
    assert all(r.generated_tokens for r in results)
    text = REGISTRY.exposition()
    for family in (
        "llm_engine_prefill_seconds",
        "llm_engine_decode_seconds",
        "llm_engine_generated_tokens_total",
        "llm_paged_pool_pages",
        "llm_paged_pool_free_pages",
        "llm_paged_pool_occupancy",
        "llm_request_joules_per_token",
    ):
        assert family in text, family
    # attention-path labels name the paged bf16 path
    assert 'path="paged"' in text and 'kv="bf16"' in text
    # shared-window attribution: every row carries its token share
    for r in results:
        e = r.extras["energy_model"]
        assert e["window"] == "shared" and e["J"] > 0


def test_scheduler_budget_admission_counter(obs_on):
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        REGISTRY,
    )
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.serve.scheduler import (
        BatchScheduler,
        _Ticket,
    )

    engine = JaxEngine(registry=_tiny_registry(), dtype=jnp.float32)
    sched = BatchScheduler(engine, max_batch=2, budget_aware=True)
    fam = REGISTRY.counter(
        "llm_sched_budget_admission_total", labels=("outcome",)
    )
    before = fam.labels(outcome="raised").value
    cap = sched._admission_cap(
        _Ticket(GenerationRequest("tiny", "budget", max_new_tokens=4))
    )
    assert cap > 2  # tiny config: the KV estimate clears the static cap
    assert fam.labels(outcome="raised").value == before + 1


def test_kill_switch_keeps_serving_but_drops_telemetry(obs_off):
    """Disabled telemetry: requests still serve, zero spans, empty
    registry deltas — the measurement-run guarantee."""
    import jax.numpy as jnp

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.engine.jax_engine import (
        JaxEngine,
    )

    TRACER.clear()
    engine = JaxEngine(registry=_tiny_registry(), dtype=jnp.float32)
    result = engine.generate(
        GenerationRequest("tiny", "quiet", max_new_tokens=4)
    )
    assert result.generated_tokens == 4
    assert TRACER.spans() == []
    assert (result.extras or {}).get("energy_model") is None


# -- profiler satellites ------------------------------------------------------


def _run_context(tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.runner.context import (
        RunContext,
    )

    run_dir = tmp_path / "run_0"
    run_dir.mkdir()
    return RunContext(
        run_id="run_0",
        run_nr=1,
        total_runs=1,
        variation={},
        run_dir=run_dir,
        experiment_dir=tmp_path,
    )


def test_jax_trace_reports_none_when_start_failed(tmp_path, monkeypatch):
    """Satellite: a failed start_trace must not claim a trace_dir the
    run table would then point at."""
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.jax_trace import (
        JaxTraceProfiler,
    )

    def boom(path):
        raise RuntimeError("no profiler backend")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    prof = JaxTraceProfiler()
    ctx = _run_context(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx) == {"trace_dir": None}


def test_jax_trace_reports_dir_when_trace_written(tmp_path, monkeypatch):
    import jax

    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.jax_trace import (
        JaxTraceProfiler,
    )

    monkeypatch.setattr(jax.profiler, "start_trace", lambda path: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    prof = JaxTraceProfiler()
    ctx = _run_context(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx)["trace_dir"].endswith("jax_trace")


def test_span_trace_profiler_writes_artifact(obs_on, tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.span_trace import (
        SpanTraceProfiler,
    )

    prof = SpanTraceProfiler()
    ctx = _run_context(tmp_path)
    prof.on_start(ctx)
    with TRACER.span("measured-activity"):
        pass
    prof.on_stop(ctx)
    path = prof.collect(ctx)["span_trace"]
    assert path is not None
    events = json.loads((ctx.run_dir / "span_trace.json").read_text())[
        "traceEvents"
    ]
    assert any(e["name"] == "measured-activity" for e in events)


def test_span_trace_profiler_none_without_spans(obs_on, tmp_path):
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.profilers.span_trace import (
        SpanTraceProfiler,
    )

    prof = SpanTraceProfiler()
    ctx = _run_context(tmp_path)
    prof.on_start(ctx)
    prof.on_stop(ctx)
    assert prof.collect(ctx) == {"span_trace": None}


# -- access log ---------------------------------------------------------------


def test_access_log_opt_in(obs_on, capsys):
    srv = GenerationServer(
        FakeBackend(), host="127.0.0.1", port=0, quiet=True, access_log=True
    )
    srv.start()
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ).read()
    finally:
        srv.stop()
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines() if '"/healthz"' in l)
    record = json.loads(line.split("serve: ", 1)[1])
    assert record["method"] == "GET" and record["status"] == 200
    assert record["duration_ms"] >= 0


def test_access_log_default_off(obs_on, capsys):
    srv = GenerationServer(FakeBackend(), host="127.0.0.1", port=0, quiet=True)
    srv.start()
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ).read()
    finally:
        srv.stop()
    assert "/healthz" not in capsys.readouterr().out


# -- bucket quantile estimators (ISSUE 17) ------------------------------------


def test_quantile_from_buckets_monotone_in_q():
    """Property: the estimate is non-decreasing in q for any bucket
    mass (swept over several shapes including +Inf-heavy ones)."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        quantile_from_buckets,
    )

    bounds = (0.01, 0.1, 0.5, 2.0)
    shapes = [
        (5, 0, 0, 0, 0),
        (1, 2, 3, 4, 5),
        (0, 0, 0, 0, 7),  # everything overflowed
        (10, 0, 0, 0, 3),
        (1, 1, 1, 1, 1),
    ]
    qs = [i / 20 for i in range(21)]
    for counts in shapes:
        estimates = [quantile_from_buckets(bounds, counts, q) for q in qs]
        assert all(e is not None for e in estimates), counts
        for lo, hi in zip(estimates, estimates[1:]):
            assert lo <= hi, (counts, estimates)


def test_quantile_from_buckets_exact_on_single_bucket_mass():
    """Property: with ALL mass in one finite bucket, every quantile
    interpolates inside that bucket's bounds — and q=1.0 hits its upper
    bound exactly."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        quantile_from_buckets,
    )

    bounds = (0.01, 0.1, 0.5, 2.0)
    for i, (lo, hi) in enumerate(zip((0.0,) + bounds, bounds)):
        counts = [0] * (len(bounds) + 1)
        counts[i] = 9
        for q in (0.01, 0.5, 0.99):
            est = quantile_from_buckets(bounds, counts, q)
            assert lo < est <= hi, (i, q, est)
        assert quantile_from_buckets(bounds, counts, 1.0) == hi
        # linear inside the bucket: q=0.5 is the bucket's midpoint
        assert quantile_from_buckets(bounds, counts, 0.5) == pytest.approx(
            lo + (hi - lo) / 2
        )


def test_quantile_from_buckets_inf_and_edge_handling():
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        quantile_from_buckets,
    )

    bounds = (0.1, 1.0)
    # mass only in +Inf clamps to the last finite bound
    assert quantile_from_buckets(bounds, (0, 0, 5), 0.99) == 1.0
    # empty histogram -> None
    assert quantile_from_buckets(bounds, (0, 0, 0), 0.5) is None
    # q outside [0,1] clamps rather than raising
    assert quantile_from_buckets(bounds, (4, 0, 0), -1.0) is not None
    assert quantile_from_buckets(bounds, (4, 0, 0), 2.0) == 0.1
    # counts/bounds length mismatch is a caller bug -> ValueError
    with pytest.raises(ValueError):
        quantile_from_buckets(bounds, (1, 2), 0.5)


def test_bucket_fraction_below_additive_across_merged_histograms():
    """Property: the fraction computed on bucket-wise SUMMED counts
    equals the count-weighted mean of per-histogram fractions — the
    algebra that makes fleet attainment equal the per-replica merge."""
    from cain_2025_device_remote_llm_energy_rep_pkg_tpu.obs.metrics import (
        bucket_fraction_below,
    )

    bounds = (0.01, 0.1, 0.5, 2.0)
    a = (30, 5, 0, 0, 0)
    b = (2, 1, 4, 10, 3)
    merged = tuple(x + y for x, y in zip(a, b))
    for threshold in (0.005, 0.01, 0.07, 0.1, 0.3, 2.0, 99.0):
        fa = bucket_fraction_below(bounds, a, threshold)
        fb = bucket_fraction_below(bounds, b, threshold)
        fm = bucket_fraction_below(bounds, merged, threshold)
        weighted = (fa * sum(a) + fb * sum(b)) / (sum(a) + sum(b))
        assert fm == pytest.approx(weighted, abs=1e-12), threshold
    with pytest.raises(ValueError):
        bucket_fraction_below(bounds, a[:-1], 0.1)
    assert bucket_fraction_below(bounds, (0,) * 5, 0.1) is None


# -- windowed telemetry on the served path (ISSUE 17) -------------------------


def test_debug_timeseries_endpoint_after_served_request(obs_on):
    """/debug/timeseries serves windowed rollups (and the SLO snapshot
    when --slo is set) after one request through the scheduler."""
    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        batch_window_ms=20,
        slo="ttft_p99_ms<=250",
        ts_interval_s=0.05,
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(
            f"{base}/api/generate",
            data=json.dumps(
                {"model": "m", "prompt": "p", "options": {"num_predict": 4}}
            ).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["done"]
        # let the sampler take a post-traffic snapshot
        deadline = threading.Event()
        for _ in range(100):
            with urllib.request.urlopen(
                f"{base}/debug/timeseries?family=llm_sched_requests_total",
                timeout=10,
            ) as resp:
                body = json.loads(resp.read())
            rollup = body.get("rollup")
            if rollup and rollup["children"].get("_", {}).get("delta"):
                break
            deadline.wait(0.05)
        assert rollup is not None
        assert rollup["children"]["_"]["delta"] >= 1.0
        assert body["slo"]["objectives"][0]["name"] == "ttft_p99_ms"
        # bad ?window= is a 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"{base}/debug/timeseries?window=bogus", timeout=10
            )
        assert exc_info.value.code == 400
        # the sampled queue-depth gauge exists on the served path
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert "llm_sched_queue_depth" in resp.read().decode()
    finally:
        srv.stop()
    assert not srv._sampler.running


def test_kill_switch_keeps_sampler_and_slo_engine_off(obs_off):
    """ISSUE 17 kill-switch completeness: with telemetry off the
    sampler thread never starts, SLO evaluation is a no-op, and the
    ring stays empty — even when --slo was configured."""
    srv = GenerationServer(
        FakeBackend(),
        host="127.0.0.1",
        port=0,
        quiet=True,
        slo="ttft_p99_ms<=250",
        ts_interval_s=0.05,
    )
    srv.start()
    try:
        assert not srv._sampler.running
        assert len(srv.ts_ring) == 0
        assert srv.slo_engine is not None
        assert srv.slo_engine.evaluate() is None
    finally:
        srv.stop()
