"""Study configs — the equivalent of the reference's ``experiment/`` dir."""

from .llm_energy import LlmEnergyConfig

__all__ = ["LlmEnergyConfig"]
