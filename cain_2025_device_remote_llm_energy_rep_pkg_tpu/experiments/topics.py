"""Prompt-topic pool.

Reference: ``experiment/topics.csv`` — 100 popular-encyclopedia-page subjects,
one drawn uniformly per run (experiment/RunnerConfig.py:115-118). This is an
original general-knowledge list with the same role and size; topic choice
only varies the prompt bytes, the run table records which one was used.
"""

from __future__ import annotations

import random
from typing import List, Optional

TOPICS: List[str] = [
    "the water cycle", "photosynthesis", "plate tectonics", "the solar system",
    "black holes", "the speed of light", "electricity", "magnetism",
    "the periodic table", "chemical bonds", "DNA replication", "evolution",
    "the immune system", "the human brain", "vaccines", "antibiotics",
    "climate change", "renewable energy", "nuclear fission", "semiconductors",
    "the internet", "machine learning", "cryptography", "quantum computing",
    "the printing press", "the industrial revolution", "the silk road",
    "ancient rome", "ancient egypt", "the renaissance", "the enlightenment",
    "the french revolution", "the space race", "the cold war",
    "the united nations", "world trade", "supply and demand", "inflation",
    "central banks", "stock markets", "game theory", "probability",
    "prime numbers", "calculus", "geometry", "statistics", "logic",
    "linguistics", "the origin of writing", "the history of mathematics",
    "volcanoes", "earthquakes", "hurricanes", "ocean currents", "glaciers",
    "coral reefs", "rainforests", "deserts", "migration of birds",
    "honeybees", "whales", "dinosaurs", "fossils", "the carbon cycle",
    "soil formation", "agriculture", "irrigation", "fermentation",
    "the history of medicine", "anatomy", "genetics", "proteins",
    "photography", "cinema", "classical music", "jazz", "the violin",
    "oil painting", "sculpture", "architecture", "bridges", "skyscrapers",
    "railways", "aviation", "submarines", "satellites", "telescopes",
    "microscopes", "clocks and timekeeping", "calendars", "maps",
    "navigation", "olympic games", "chess", "football", "marathon running",
    "tea", "coffee", "chocolate", "bread", "cheese",
]


def pick_topic(seed: Optional[int] = None) -> str:
    """Uniform draw; seedable so a run's topic is reproducible from its id."""
    rng = random.Random(seed)
    return rng.choice(TOPICS)
