"""The study config: on-device vs remote LLM generation energy on TPU.

Rebuilds ``experiment/RunnerConfig.py`` (the reference's L7 workload, 269
LoC) on the TPU-native stack:

  reference                              → this config
  ------------------------------------------------------------------
  7 Ollama models (RunnerConfig.py:80)   → same 7 families, JAX engine
  location ∈ {on_device, remote} (:81)   → 1-device engine vs TP-mesh engine
  length ∈ {100,500,1000} words (:82)    → max_new_tokens = ceil(words·4/3)
  curl POST /api/generate (:128-131)     → in-process GenerationRequest
  CodeCarbon kWh→J (:250-259)            → TPU power/energy profilers
  powermetrics GPU sampling (:140)       → modelled TPU utilisation column
  psutil cpu/mem loop (:153-178)         → HostResourceProfiler thread
  random topic from topics.csv (:115)    → seeded topic per run (reproducible)
  30 reps, shuffle, 90 s cooldown (:87)  → constructor-configurable

The reference's quirks are deliberately fixed (SURVEY.md §7):
execution_time here is the request wall-time, not hook-to-hook time; the
measurement runs on profiler threads so ``interact`` genuinely waits on the
generation rather than being dead code.
"""

from __future__ import annotations

import math
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..engine.backend import GenerationBackend, GenerationRequest
from ..serve.client import RemoteHTTPBackend
from ..profilers.tpu import TpuEnergyModelProfiler, TpuPowerCounterProfiler
from ..runner.config import ExperimentConfig
from ..runner.context import RunContext
from ..runner.factors import Factor, RunTableModel
from .topics import pick_topic

MODELS = [
    "qwen2:1.5b",
    "gemma:2b",
    "phi3:3.8b",
    "gemma:7b",
    "qwen2:7b",
    "mistral:7b",
    "llama3.1:8b",
]
LOCATIONS = ["on_device", "remote"]
LENGTHS = [100, 500, 1000]
TOKENS_PER_WORD = 4 / 3  # common English tokens-per-word rule of thumb
# The study's serving topology: on_device = one chip, remote = the 8-chip
# TP mesh (BASELINE.json). Single definition — the constructor default AND
# recompute_energy's legacy-table fallback both read this.
DEFAULT_N_CHIPS_BY_LOCATION = {"on_device": 1, "remote": 8}


def _canonical_url(url: str) -> str:
    """Canonical form for same-server comparison: lowercase scheme+host,
    loopback spellings unified, default port explicit, trailing slash
    stripped — ``http://localhost:11434/`` and ``http://127.0.0.1:11434``
    are one server (and one chip), and missing that reintroduces the
    unmarked-aliasing bug this detection exists for."""
    from urllib.parse import urlsplit

    parts = urlsplit(url.strip().rstrip("/"))
    host = (parts.hostname or "").lower()
    if host in ("localhost", "::1", "0.0.0.0"):
        host = "127.0.0.1"
    port = parts.port or (443 if parts.scheme == "https" else 80)
    return f"{parts.scheme.lower()}://{host}:{port}"


def generation_stats_from(
    cfg,
    result,
    quantize: Optional[str] = "int8",
    kv_quantize: Optional[str] = None,
    n_chips: int = 1,
    aliased: bool = False,
) -> Dict[str, Any]:
    """The energy model's inputs for one generation, from the engine's
    raw measurements (a pure function of persisted columns, so modelled
    energy is recomputable post-hoc — the reference likewise derives its
    J column from raw data after the fact, RunnerConfig.py:250-259).

    Window choice (round-3 CV analysis): the idle-power window is the
    fence-timed DECODE loop only. ``prefill_s`` on tunneled devices is
    dominated by host→device dispatch latency (80–400 ms for a sub-ms
    32-token prefill) — transport jitter, not chip work, exactly what the
    ≤5% variance target requires keeping out of Joules. Prefill's compute
    is charged through the FLOPs term instead (all processed tokens,
    prompt + generated); its true device occupancy beyond that is
    bounded by the prefill execution itself (≪ the idle-power resolution
    of the model for bucketed prompts). total_s remains the recorded
    ``execution_time_s`` — the reference's client-observed metric.

    ``bytes`` is the decode loop's HBM traffic (weights + KV streamed
    every step, utils/memory.estimate_decode_read_bytes_per_step under
    the serving ``quantize`` mode) — the memory-bound half of the power
    model's duty cycle.

    ``aliased`` marks a remote-treatment row actually measured on the
    single on-device chip (single-chip dev hosts; the run table's
    ``backend`` column records this per row). For those rows the serving
    mesh's decode DURATION is modelled by the TP roofline
    (parallel/roofline.py) — an 8-chip mesh decodes materially faster
    than one chip, and billing 8 chips for the single chip's wall time
    would invert the reference's speed-vs-energy trade-off (VERDICT
    round-3 missing #3). The modelled window is returned as
    ``modeled_decode_s`` and used as ``duration_s``; the measured
    single-chip timing stays in the raw ``decode_s`` column untouched.
    """
    total_tokens = result.prompt_tokens + result.generated_tokens
    flops = (
        cfg.flops_per_token(total_tokens) * total_tokens
        if cfg is not None
        else 0.0
    )
    duration = result.decode_s if result.decode_s > 0 else result.total_s
    stats: Dict[str, Any] = {
        "flops": flops,
        "duration_s": duration,
        "generated_tokens": result.generated_tokens,
    }
    if cfg is None:
        if aliased and n_chips > 1:
            from ..runner import term

            model = getattr(getattr(result, "request", None), "model", "?")
            term.log_warn(
                f"model {model!r} not in the "
                f"registry: the aliased remote row keeps the single-chip "
                f"measured window and a FLOPs-free energy model (idle "
                f"watts) — pass the study's registry for honest mesh "
                f"columns"
            )
    else:
        from ..utils.memory import (
            decode_kv_stream_bytes,
            decode_weight_stream_bytes,
        )

        mid_context = int(result.prompt_tokens + result.generated_tokens / 2)
        # Mesh KV replication (parallel/sharding.py): when n_kv_heads does
        # not divide the mesh, EVERY chip streams the full cache — total
        # mesh traffic is W + n·KV, and the duty denominator already
        # scales by n_chips, so bytes must too (the roofline duration
        # model applies the same rule per chip).
        kv_mult = (
            n_chips
            if n_chips > 1 and cfg.n_kv_heads % n_chips != 0
            else 1
        )
        stats["bytes"] = (
            decode_weight_stream_bytes(cfg, quantize)
            + kv_mult
            * decode_kv_stream_bytes(
                cfg, mid_context, kv_quantize=kv_quantize
            )
        ) * result.generated_tokens
        # VPU unpack work (int4 decode is VPU-bound, not HBM-bound —
        # docs/PERF.md); on a TP mesh each chip unpacks its own weight
        # shard, so total ops don't scale with chips
        from ..utils.memory import decode_vpu_unpack_ops_per_step

        stats["vpu_ops"] = (
            decode_vpu_unpack_ops_per_step(cfg, quantize)
            * result.generated_tokens
        )
        if aliased and n_chips > 1:
            from ..parallel.roofline import modeled_tp_decode_s

            # The roofline supplies the 1-chip → n-chip RATIO only; the
            # absolute window is anchored on the row's own measured
            # single-chip decode. Reason: a KV-heavy access pattern
            # (phi3 at long context) sustains well under the calibrated
            # ~490 GB/s, so raw roofline seconds would understate the
            # mesh time and overstate the speedup past n_chips×; scaling
            # the measurement by the modelled ratio keeps the workload's
            # real efficiency and bounds the speedup by the model's own
            # sublinear ICI accounting.
            t1 = modeled_tp_decode_s(
                cfg,
                quantize,
                1,
                result.prompt_tokens,
                result.generated_tokens,
                kv_quantize=kv_quantize,
            )
            tn = modeled_tp_decode_s(
                cfg,
                quantize,
                n_chips,
                result.prompt_tokens,
                result.generated_tokens,
                kv_quantize=kv_quantize,
            )
            if t1 > 0 and tn > 0 and duration > 0:
                if tn >= t1:
                    # Physically honest — per-layer psums sit on the ICI
                    # latency floor, so toy/tiny models DO decode slower
                    # on a mesh — but a study billing mesh windows slower
                    # than one chip is almost certainly misconfigured
                    # (e.g. tiny test models with the real 8-chip
                    # topology; see examples/llm_energy_smoke.py).
                    from ..runner import term

                    term.log_warn(
                        f"TP-{n_chips} roofline predicts a SLOWDOWN "
                        f"({t1 / tn:.2f}× speedup) for this workload - "
                        f"the mesh window is being billed honestly, but "
                        f"check the topology fits the model scale "
                        f"(n_chips_by_location)"
                    )
                modeled = duration * (tn / t1)
                stats["modeled_decode_s"] = round(modeled, 4)
                stats["duration_s"] = modeled
    return stats


def recompute_energy(
    experiment_dir: Path,
    n_chips_by_location: Optional[Dict[str, int]] = None,
    registry: Optional[Dict[str, Any]] = None,
    reanalyze: bool = True,
    quantize_by_model: Optional[Dict[str, str]] = None,
    assume_aliased_without_backend: bool = True,
) -> int:
    """Recompute the modelled energy columns of an existing run table from
    its persisted RAW measurements (timings + token counts) under the
    current energy model — the post-hoc derived-column pattern the
    reference itself uses (``energy_usage_J``, RunnerConfig.py:250-259).
    Raw measurements are never touched. Returns the number of rows
    updated; re-runs the analysis pipeline by default.

    The serving-chip count comes from each row's persisted ``chips``
    column; tables from before that column existed fall back to
    ``n_chips_by_location`` (default: the study's standard topology,
    ``DEFAULT_N_CHIPS_BY_LOCATION``) — pass the map the study actually
    ran with if it was customised. The quantization mode comes from the
    row's ``quantize`` column; for older tables without it,
    ``quantize_by_model`` supplies the serving modes (the serve CLI's
    per-model spec shape: ``{"qwen2:1.5b": "int8", "default": "int4"}``),
    falling back to the study default ``"int8"`` — and the resolved mode
    is BACKFILLED into the ``quantize`` column so the table becomes
    self-contained for future recomputes. A row whose ``backend`` column carries
    the ``[aliased-on_device]`` marker (or, for pre-backend-column
    tables, any remote row served by >1 chip — aliasing was the only way
    such a row could exist then, and how many rows took that ASSUMPTION
    is warned about, since a genuinely multi-chip remote measurement fed
    through it would have its window silently rewritten; pass
    ``assume_aliased_without_backend=False`` for tables known to carry
    real remote measurements, ADVICE round-4) gets the TP-roofline
    modelled duration as its energy window and a
    ``remote_modeled_decode_s`` column (see ``generation_stats_from``). ``registry`` maps model name →
    ModelConfig for the FLOPs term (default: the full-size
    ``MODEL_REGISTRY``; pass the study's own registry for tables produced
    with custom/miniature configs)."""
    import types

    from ..models.config import MODEL_REGISTRY
    from ..runner.persistence import RunTableStore

    fallback_chips = dict(n_chips_by_location or DEFAULT_N_CHIPS_BY_LOCATION)
    configs = registry if registry is not None else MODEL_REGISTRY
    store = RunTableStore(Path(experiment_dir))
    rows = store.read()
    # Aliasing detection needs cross-row context: a remote row whose
    # backend ALSO serves on_device rows came from a shared single-chip
    # process (the loopback-server capstone records the same URL for
    # both treatments), even without the [aliased-on_device] marker the
    # in-process alias appends. HTTP backend strings are canonicalized
    # before comparison — localhost vs 127.0.0.1 is one server.
    def _canonical_backend(desc: str) -> str:
        if desc.startswith("http:"):
            try:
                return "http:" + _canonical_url(desc[len("http:"):])
            except ValueError:
                return desc
        return desc

    on_device_backends = {
        _canonical_backend(str(r.get("backend")))
        for r in rows
        if str(r.get("location")) == "on_device" and r.get("backend")
    }
    updated = 0
    assumed_aliased = 0
    for row in rows:
        # uniform keys: RunTableStore.write derives the header from the
        # first row, so every row must carry the new columns
        row.setdefault("remote_modeled_decode_s", None)
        row.setdefault("chips", None)
        for col in TpuEnergyModelProfiler.data_columns:
            row.setdefault(col, None)
        if quantize_by_model:
            row.setdefault("quantize", None)
        # every raw input the model consumes must be present — a legacy
        # table missing any one of them skips the row, never aborts the
        # whole recompute
        if any(
            row.get(k) is None
            for k in (
                "decode_s",
                "generated_tokens",
                "prompt_tokens",
                "execution_time_s",
            )
        ):
            continue
        cfg = configs.get(str(row.get("model")))
        result = types.SimpleNamespace(
            prompt_tokens=int(row["prompt_tokens"]),
            generated_tokens=int(row["generated_tokens"]),
            decode_s=float(row["decode_s"]),
            total_s=float(row["execution_time_s"]),
            # the unknown-model warning names the row's model through the
            # same attribute path interact's real result provides
            request=types.SimpleNamespace(model=str(row.get("model"))),
        )
        chips = row.get("chips")
        n_chips = (
            int(chips)
            if chips is not None
            else fallback_chips.get(str(row.get("location")), 1)
        )
        # Backfill the chips column ONLY from an operator-asserted map:
        # baking the built-in default into the table would make a later
        # `--chips remote=4` recompute a silent no-op (rows carrying the
        # column always win), turning a recoverable omission into a
        # frozen wrong topology.
        if chips is None and n_chips_by_location is not None:
            row["chips"] = n_chips
        backend = row.get("backend")
        is_remote = str(row.get("location")) == "remote"
        if backend is not None:
            aliased = str(backend).endswith("[aliased-on_device]") or (
                is_remote
                and _canonical_backend(str(backend)) in on_device_backends
            )
        else:
            # pre-backend-column table: aliasing was the only way a
            # multi-chip remote row could exist then — but it is an
            # ASSUMPTION here, counted and warned about below
            aliased = (
                assume_aliased_without_backend and is_remote and n_chips > 1
            )
            if aliased:
                assumed_aliased += 1
        # persisted as "bf16" for unquantized serving (CSV cannot
        # distinguish None from a missing pre-column cell); missing →
        # the caller's per-model map, then the study default int8
        q = row.get("quantize")
        if not q and quantize_by_model:
            q = quantize_by_model.get(
                str(row.get("model")), quantize_by_model.get("default")
            )
            row["quantize"] = q or "int8"
        stats = generation_stats_from(
            cfg,
            result,
            quantize=None if q == "bf16" else (q or "int8"),
            n_chips=n_chips,
            aliased=aliased,
        )
        profiler = TpuEnergyModelProfiler(n_chips=n_chips)
        ctx = types.SimpleNamespace(scratch={"generation_stats": stats})
        row.update(profiler.collect(ctx))
        row["remote_modeled_decode_s"] = stats.get("modeled_decode_s")
        updated += 1
    if assumed_aliased:
        from ..runner import term

        term.log_warn(
            f"{assumed_aliased} remote row(s) predate the backend column "
            f"and were ASSUMED aliased (single-chip measurement of a "
            f"multi-chip treatment): their energy window is the "
            f"TP-roofline modelled mesh duration, not their measured "
            f"decode_s. If this table came from a genuinely multi-chip "
            f"remote server, re-run with "
            f"assume_aliased_without_backend=False"
        )
    if updated:
        # one atomic whole-table rewrite, not one per row (update_row
        # re-reads and rewrites the full CSV each call — O(n²) here)
        store.write(rows)
    if reanalyze and updated:
        from ..analysis.pipeline import analyze_experiment

        analyze_experiment(Path(experiment_dir), make_plots=True)
    return updated


class LlmEnergyConfig(ExperimentConfig):
    """7 models × 2 locations × 3 content lengths × repetitions."""

    name = "llm_energy_tpu"
    results_output_path = Path("experiments_output")
    # Cooldown policy (reference: fixed 90 s, RunnerConfig.py:55): thermal
    # discipline only matters when a MEASURED energy/power channel is
    # active — a hot chip throttles and skews real Joules. Modelled energy
    # is thermal-state-free, so measured-channel hosts keep the reference's
    # 90 s and modelled-only hosts drop to 2 s. ``cooldown_ms`` overrides.
    MEASURED_CHANNEL_COOLDOWN_MS = 90_000
    MODELLED_ONLY_COOLDOWN_MS = 2_000
    time_between_runs_in_ms = MEASURED_CHANNEL_COOLDOWN_MS
    # Generation happens in-process; fork isolation would re-trace jit on
    # every run, so the engine lives in the parent by default.
    isolate_runs = False

    def __init__(
        self,
        models: Optional[List[str]] = None,
        locations: Optional[List[str]] = None,
        lengths: Optional[List[int]] = None,
        repetitions: int = 30,
        results_output_path: Optional[Path] = None,
        cooldown_ms: Optional[int] = None,
        backends: Optional[Dict[str, GenerationBackend]] = None,
        remote_url: Optional[str] = None,
        on_device_url: Optional[str] = None,
        remote_tp: int = -1,
        shuffle: bool = True,
        seed: int = 0,
        n_chips_by_location: Optional[Dict[str, int]] = None,
        quantize: Optional[str] = "int8",
    ) -> None:
        self.models = models or MODELS
        self.locations = locations or LOCATIONS
        self.lengths = lengths or LENGTHS
        self.repetitions = repetitions
        self.shuffle = shuffle
        self.seed = seed
        # int8 by default: the reference's baseline models are Ollama 4-bit
        # GGUF quants, so quantized serving is the matching configuration —
        # and llama3.1:8b at bf16 (~16 GB) cannot share a 16 GB chip with
        # its KV cache at all. None = full bf16 (smaller models only).
        self.quantize = quantize
        if results_output_path is not None:
            self.results_output_path = Path(results_output_path)
        self._cooldown_ms = cooldown_ms  # None → decided by channel type below
        self._backends = backends  # None → built lazily in before_experiment
        self._remote_url = remote_url
        # The reference's on-device treatment ALSO crosses a process+HTTP
        # boundary — curl to the local Ollama on localhost:11434
        # (experiment/RunnerConfig.py:122-131). With on_device_url set, this
        # study does the faithful equivalent: a separate serving process
        # owns the chip and the experiment process is a pure HTTP client
        # for both treatments (mandatory on single-chip relays, where two
        # JAX runtimes cannot share the chip).
        self._on_device_url = on_device_url
        self._remote_tp = remote_tp
        # Plain data, deliberately NOT read back from the profiler object:
        # the shared profiler's n_chips is mutated per run in before_run, and
        # reading the target count from any aliased profiler instance would
        # let one remote run permanently poison every later on_device run.
        self._n_chips_by_location = dict(
            n_chips_by_location or DEFAULT_N_CHIPS_BY_LOCATION
        )
        from ..profilers.native_host import NativeHostProfiler
        from ..profilers.sysfs_power import SysfsPowerProfiler

        self.profilers = [
            # one model-energy profiler; per-run chip count set in before_run
            TpuEnergyModelProfiler(
                n_chips=self._n_chips_by_location.get(self.locations[0], 1)
            ),
            # C++ kHz sampler for host energy/cpu/memory; it transparently
            # falls back to the psutil+RAPL Python pair (same columns) when
            # the native library can't build or load at runtime
            NativeHostProfiler(period_us=1000),
        ]
        # Generic sysfs host power (hwmon rails / battery discharge):
        # host-scoped, so it wires in EVERY mode — a laptop whose only
        # measured channel is hwmon records real Watts instead of
        # modelled-only (and re-grows the thermal cooldown below).
        sysfs = SysfsPowerProfiler()
        if sysfs.available:
            self.profilers.insert(1, sysfs)
        # Device-touching profilers only when this process owns (or will
        # own) the accelerator — in HTTP-client mode a libtpu query could
        # block on the device grant held by the serving process.
        if on_device_url is None:
            from ..profilers.energy_probe import TpuDutyCycleProfiler

            counter = TpuPowerCounterProfiler()
            if counter.available:  # real counters, when the platform has them
                self.profilers.insert(0, counter)
            duty = TpuDutyCycleProfiler()
            if duty.available:  # measured duty cycle (standard TPU VMs)
                self.profilers.insert(0, duty)
        # Cooldown by channel type (see the class attributes): explicit
        # cooldown_ms always wins; otherwise a measured energy/power
        # channel re-grows the reference's 90 s thermal discipline.
        if self._cooldown_ms is not None:
            self.time_between_runs_in_ms = self._cooldown_ms
        else:
            self.time_between_runs_in_ms = (
                self.MEASURED_CHANNEL_COOLDOWN_MS
                if any(
                    getattr(p, "measured_channel", False)
                    for p in self.profilers
                )
                else self.MODELLED_ONLY_COOLDOWN_MS
            )

    # -- run table ------------------------------------------------------------
    def create_run_table_model(self) -> RunTableModel:
        return RunTableModel(
            factors=[
                Factor("model", self.models),
                Factor("location", self.locations),
                Factor("length", self.lengths),
            ],
            repetitions=self.repetitions,
            data_columns=[
                "topic",
                "backend",  # which backend/transport really served this row
                "chips",  # serving-chip count the energy model used — the
                # modelled columns stay recomputable from the row alone
                "quantize",  # serving quantization mode ("bf16" = none) —
                # the bytes term of the energy model depends on it
                "prompt_tokens",
                "generated_tokens",
                "execution_time_s",
                "prefill_s",
                "decode_s",
                "tokens_per_s",
                # TP-roofline modelled mesh decode window for remote rows
                # measured on an aliased single chip (None otherwise) —
                # the energy window those rows were billed on
                "remote_modeled_decode_s",
            ],
            shuffle=self.shuffle,
            shuffle_seed=self.seed,
        )

    # -- lifecycle ------------------------------------------------------------
    def before_experiment(self) -> None:
        # Persistent XLA compilation cache: a sweep's per-(model, bucket)
        # warm-up compiles (~20-45 s each) hit disk after the first run, so
        # resume/re-runs warm in seconds (VERDICT.md round-1 item 7). In
        # HTTP-client mode the server compiles, not this process — keep the
        # client JAX-free.
        if self._on_device_url is None:
            from ..utils.compile_cache import enable_compilation_cache

            enable_compilation_cache()
        # Audit trail for the energy columns: which measured channels this
        # host offers and why the unavailable ones are unavailable
        # (VERDICT.md round-1 item 1 — a modelled-only table must say so).
        if self.experiment_path is not None:
            from ..profilers.energy_probe import write_probe_report
            from ..runner import term

            statuses = write_probe_report(
                Path(self.experiment_path) / "energy_channels.json",
                include_device=self._on_device_url is None,
            )
            measured = [s.name for s in statuses if s.available]
            term.log(
                "energy channels: "
                + (
                    f"measured sources available: {', '.join(measured)}"
                    if measured
                    else "no measured source on this host - energy columns "
                    "are modelled (see energy_channels.json)"
                )
            )
        if self._backends is None:
            if self._on_device_url:
                on_device: GenerationBackend = RemoteHTTPBackend(
                    self._on_device_url
                )
                if not on_device.health():
                    from ..runner.errors import ExperimentError

                    raise ExperimentError(
                        f"on-device generation server unreachable at "
                        f"{self._on_device_url}; start one with the 'serve' "
                        f"command (it must own the chip before this client "
                        f"process starts)"
                    )
                self._backends = {"on_device": on_device}
                self._wire_remote_backend()
                return
            from ..engine.jax_engine import JaxEngine

            self._backends = {
                "on_device": JaxEngine(
                    decode_attention="auto", quantize=self.quantize
                )
            }
            self._wire_remote_backend(allow_local_mesh=True)

    def _wire_remote_backend(self, allow_local_mesh: bool = False) -> None:
        """Choose the remote treatment's backend: an HTTP server named by
        ``remote_url`` / ``.env SERVER_IP`` (the reference's machine
        boundary, experiment/RunnerConfig.py:122-131), else a local TP mesh
        (multi-chip hosts, in-process mode only — a second JAX runtime must
        not start when a serving process already owns the chip), else the
        on-device backend aliased and *recorded as aliased* in the run
        table's backend column."""
        if "remote" not in self.locations:
            return
        from ..serve.client import backend_from_env

        http_backend = (
            RemoteHTTPBackend(self._remote_url)
            if self._remote_url
            else backend_from_env()
        )
        if http_backend is not None:
            # Fail fast on an unreachable server rather than hours into
            # the sweep.
            if not http_backend.health():
                from ..runner.errors import ExperimentError

                raise ExperimentError(
                    f"remote generation server unreachable at "
                    f"{http_backend.base_url} (from remote_url / "
                    f"SERVER_IP); start one with the 'serve' command "
                    f"or unset the variable to use the local TP mesh"
                )
            self._backends["remote"] = http_backend
            return
        if allow_local_mesh:
            import jax

            if len(jax.devices()) > 1:
                from ..parallel.mesh import MeshSpec, build_mesh
                from ..parallel.tp import TensorParallelEngine

                mesh = build_mesh(MeshSpec.tp_only(self._remote_tp))
                self._backends["remote"] = TensorParallelEngine(
                    mesh=mesh,
                    decode_attention="auto",
                    quantize=self.quantize,
                )
                return
        # single-chip dev box: the remote treatment still runs against the
        # on-device backend, distinguished by the energy model's chip count
        # — and the aliasing is recorded per row (describe_backend), so no
        # reader can mistake these rows for a real machine boundary.
        self._backends["remote"] = self._backends["on_device"]

    def _remote_is_aliased(self) -> bool:
        """True when the remote treatment is served by the SAME backing
        process/chip as on_device: either the backend object is literally
        shared, or both are HTTP clients of one URL (the single-chip
        capstone topology: one loopback server, two treatments). Aliased
        rows get the TP-roofline mesh duration; a genuinely distinct
        remote server keeps its own measured timing."""
        remote = self._backends.get("remote")
        on_device = self._backends.get("on_device")
        if remote is None or on_device is None:
            return False
        if remote is on_device:
            return True
        return (
            isinstance(remote, RemoteHTTPBackend)
            and isinstance(on_device, RemoteHTTPBackend)
            and _canonical_url(remote.base_url)
            == _canonical_url(on_device.base_url)
        )

    def describe_backend(self, location: str) -> str:
        """Human/machine-readable identity of the backend that serves
        ``location``'s rows — recorded per run in the ``backend`` column
        (VERDICT.md round-1 weakness 3: fallback rows must be
        distinguishable)."""
        be = self._backends[location]
        if isinstance(be, RemoteHTTPBackend):
            desc = f"http:{be.base_url}"
        else:
            n = getattr(be, "n_devices", 1)
            desc = f"{type(be).__name__}[{n}chip]"
        if location == "remote" and self._remote_is_aliased():
            desc += "[aliased-on_device]"
        return desc

    def before_run(self, context: RunContext) -> None:
        location = context.factor("location")
        self.profilers[self._model_profiler_index()].n_chips = (
            self._n_chips_by_location.get(location, 1)
        )

    def _model_profiler_index(self) -> int:
        for i, p in enumerate(self.profilers):
            if isinstance(p, TpuEnergyModelProfiler):
                return i
        raise RuntimeError("TpuEnergyModelProfiler missing from profilers")

    def start_run(self, context: RunContext) -> None:
        # Seed the topic from the run id so resume re-issues the same prompt
        # (the reference draws an unseeded random topic, RunnerConfig.py:118).
        # crc32, not hash(): str hashing is salted per interpreter, which
        # would break cross-process reproducibility.
        import zlib

        topic_seed = zlib.crc32(f"{self.seed}|{context.run_id}".encode())
        topic = pick_topic(seed=topic_seed)
        words = context.factor("length")
        context.scratch["request"] = GenerationRequest(
            model=context.factor("model"),
            prompt=f"In {words} words, please give me information about {topic}",
            max_new_tokens=math.ceil(words * TOKENS_PER_WORD),
            temperature=0.0,
            seed=self.seed,
        )
        context.scratch["topic"] = topic
        backend = self._backends[context.factor("location")]
        backend.load_model(context.factor("model"))  # HBM load outside window
        # Compile outside the window too: the reference's server is warm when
        # curl fires; jit compile inside the measured region would dominate
        # the first run of every (model, length) cell and blow the ≤5%
        # run-to-run variance target.
        backend.warmup(context.scratch["request"])

    def interact(self, context: RunContext) -> None:
        """The measured activity: one generation request (the measurement
        window is already open — profilers started in START_MEASUREMENT)."""
        backend = self._backends[context.factor("location")]
        request: GenerationRequest = context.scratch["request"]
        result = backend.generate(request)
        context.scratch["result"] = result
        # Architecture comes from the local registry, not the backend: an
        # HTTP backend has no registry, but the FLOPs estimate (→ modelled
        # utilisation/energy of the serving chips) must not degrade to idle.
        registry = getattr(backend, "registry", None)
        cfg = registry.get(request.model) if registry else None
        if cfg is None:
            from ..models.config import MODEL_REGISTRY

            cfg = MODEL_REGISTRY.get(request.model)
        location = context.factor("location")
        stats = generation_stats_from(
            cfg,
            result,
            quantize=self.quantize,
            n_chips=self._n_chips_by_location.get(location, 1),
            aliased=location == "remote" and self._remote_is_aliased(),
        )
        context.scratch["generation_stats"] = stats

    def populate_run_data(self, context: RunContext) -> Optional[Dict[str, Any]]:
        result = context.scratch.get("result")
        if result is None:
            return None
        # Per-run artifact: the generated text itself (the reference keeps
        # raw measurement artifacts per run dir; the generation is this
        # study's raw output, and with trained weights it is readable).
        try:
            (context.run_dir / "generation.txt").write_text(
                f"prompt: {result.request.prompt}\n---\n{result.text}\n"
            )
        except OSError:
            pass
        # Streaming per-cell CV (obs/detect.py): fold this run's modelled
        # J and wall time into the (model, length, location) cell's
        # Welford tracker, so ROADMAP #1's <=5% CV target is observable
        # MID-STUDY (llm_run_cell_cv gauges; a breaching cell fires an
        # anomaly flight event) instead of post-hoc. Telemetry only —
        # must never fail a run.
        try:
            from ..obs.detect import CELL_CV
            from ..obs.energy import estimate_from_stats

            location = context.factor("location")
            est = estimate_from_stats(
                context.scratch.get("generation_stats") or {},
                n_chips=self._n_chips_by_location.get(location, 1),
            )
            CELL_CV.observe_run(
                model=context.factor("model"),
                length=context.factor("length"),
                location=location,
                energy_J=est["J"] if est else None,
                wall_s=result.total_s,
            )
        except Exception:  # noqa: BLE001
            pass
        return {
            "topic": context.scratch["topic"],
            "backend": self.describe_backend(context.factor("location")),
            "chips": self._n_chips_by_location.get(
                context.factor("location"), 1
            ),
            "quantize": self.quantize or "bf16",
            "prompt_tokens": result.prompt_tokens,
            "generated_tokens": result.generated_tokens,
            "execution_time_s": round(result.total_s, 4),
            "prefill_s": round(result.prefill_s, 4),
            "decode_s": round(result.decode_s, 4),
            "tokens_per_s": round(result.tokens_per_s, 2),
            "remote_modeled_decode_s": context.scratch[
                "generation_stats"
            ].get("modeled_decode_s"),
        }

    def after_experiment(self) -> None:
        # The reference appends a derived J column post-hoc
        # (RunnerConfig.py:250-259); here the analysis pipeline computes
        # everything from the persisted table.
        if self.experiment_path and (self.experiment_path / "run_table.csv").exists():
            from ..analysis.pipeline import analyze_experiment

            try:
                analyze_experiment(
                    self.experiment_path,
                    # metrics auto-detect from the table (KNOWN_METRIC_COLUMNS
                    # order): a fixed list here silently EXCLUDED measured
                    # channels — a host with a live power counter would have
                    # had its tpu_energy_J column ignored by the study's own
                    # post-hoc analysis while the pipeline's
                    # measured-outranks-model selection sat unused (caught
                    # by the round-5 fake-counter e2e test)
                    metrics=None,
                    # the notebook's figure families are part of the study's
                    # deliverable (nb cells 21-28, 39-40), not an opt-in
                    make_plots=True,
                )
            except Exception as exc:  # analysis must never lose run data
                from ..runner import term

                term.log_warn(f"post-hoc analysis failed: {exc}")
